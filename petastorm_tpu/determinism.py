"""Deterministic pipeline mode: order-stable shuffle, resequencing, cursor.

The default pipeline guarantees only multiset-exactness across a
checkpoint/resume (``checkpoint.py``): worker interleaving reorders rows, so
a killed-and-resumed job trains on a *different batch sequence* than an
uninterrupted one, and changing the worker or host count on restart changes
the stream entirely. The reproducible-pipelines literature (PAPERS.md,
arxiv 2604.21275) shows order-determinism is achievable without giving up
parallel decode, and the elastic tf.data-service work (arxiv 2210.14826)
makes it the precondition for elastic scaling. ``deterministic=True`` on the
reader factories turns the batch stream into a pure function of
``(dataset, schema, seed, epoch, position)`` via three mechanisms hosted
here:

:func:`epoch_order` / :func:`feistel_permute`
    A counter-based pseudorandom permutation over the epoch's ventilation
    items: a 4-round Feistel network over the item-index space, keyed by
    ``(seed, epoch)`` through a hash, with cycle-walking to fit an
    arbitrary domain size. Pure Python-int arithmetic — identical on every
    platform, numpy version, and host — so any process can recompute "what
    the shuffle chose for epoch e" from two scalars, with no RNG state to
    carry. This is what makes resume *fast-forward* (recompute the
    permutation, start feeding at the cursor) instead of skip-on-arrival,
    and what makes the order independent of worker topology.

:class:`Resequencer`
    Workers tag every published chunk with its ventilation sequence number
    (``pst_det`` item kwarg -> ``det`` chunk metadata, carried by all three
    pool transports and the data-service wire). The resequencer sits
    between the results queue and the consumer, holding out-of-order
    chunks in a bounded buffer and releasing them strictly in ventilation
    order. Its buffer is naturally bounded by the ventilator's in-flight
    cap (at most that many items can be outstanding). A seq hole that
    never fills (a wedged worker publish) surfaces through
    :meth:`Resequencer.stats` — registered as a watchdog probe so the
    PR-3 health machinery classifies it ``resequencer-stalled`` and
    escalates instead of deadlocking.

:class:`DeterministicCursor`
    The deterministic replacement for ``checkpoint.ConsumptionTracker``:
    because delivery order equals ventilation order, the whole consumption
    state collapses to a compact stream cursor ``(epoch, global position,
    rows into the open chunk)``. Resume fast-forwards the ventilator to
    the cursor rather than skipping chunks consumer-side.

Resharding: in deterministic mode ``cur_shard``/``shard_count`` is applied
as a **stride over the global deterministic order** inside the ventilator
(not a static row-group partition at filter time): host ``h`` of ``M``
feeds global positions ``p`` with ``(p - resume_base) % M == h``. The
global item sequence is the same for every ``M``, so a job checkpointed on
N hosts resumes on M hosts — each host derives its positions from the same
global cursor — and the round-robin concatenation of the per-host streams
is identical to a single-host run. ``tests/test_determinism.py`` proves
bit-identity (via the PR-7 per-field CRC32 lineage digests) across
restarts, worker counts, pool types, and 1<->2<->3-shard strides.
"""

import hashlib
import json
import threading
import time
from collections import deque

MODE = 'deterministic'
STATE_VERSION = 1

_M64 = (1 << 64) - 1
_MISSING = object()


def deterministic_safe(fn):
    """Marker: ``fn`` is on the order-defining path of deterministic mode
    and must be a pure function of its arguments — no wall-clock reads, no
    process-global RNG state, no set-iteration order. The marker changes
    nothing at runtime; the pstlint ``det-taint`` checker
    (:mod:`petastorm_tpu.analysis.determinism_taint`) enforces the purity
    claim statically, *transitively through everything the function
    calls*. Decorate any new function whose output feeds the deterministic
    stream's order."""
    fn.__deterministic_safe__ = True
    return fn


# --------------------------------------------------------------------------
# seed-stable permutation (counter-based PRP: Feistel + cycle-walking)
# --------------------------------------------------------------------------

@deterministic_safe
def epoch_key(seed, epoch):
    """64-bit permutation key for ``(seed, epoch)`` — hashed, so nearby
    seeds/epochs produce unrelated permutations."""
    digest = hashlib.md5('pst-det:{}:{}'.format(seed, epoch).encode()).digest()
    return int.from_bytes(digest[:8], 'little')


@deterministic_safe
def _mix64(v):
    """splitmix64 finalizer on a Python int (wraps mod 2^64): well-mixed,
    platform-independent — deliberately NOT a numpy Generator, whose
    bit-exactness across versions is not guaranteed."""
    v &= _M64
    v = ((v ^ (v >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    v = ((v ^ (v >> 27)) * 0x94D049BB133111EB) & _M64
    return v ^ (v >> 31)


@deterministic_safe
def feistel_permute(index, n, key):
    """Position of ``index`` under the keyed permutation of ``[0, n)``.

    4-round balanced Feistel over the smallest even-bit domain covering
    ``n``, cycle-walking out-of-domain values back through the network
    (the domain is < 4n, so the expected walk is short). A bijection on
    ``[0, n)`` for every key; O(1) memory — no materialized shuffle state.
    """
    if n <= 1:
        return 0
    if not 0 <= index < n:
        raise ValueError('index {} out of [0, {})'.format(index, n))
    half_bits = ((n - 1).bit_length() + 1) // 2
    mask = (1 << half_bits) - 1
    x = index
    while True:
        left, right = x >> half_bits, x & mask
        for rnd in range(4):
            left, right = right, left ^ (
                _mix64(right + key + 0x9E3779B97F4A7C15 * (rnd + 1)) & mask)
        x = (left << half_bits) | right
        if x < n:
            return x


@deterministic_safe
def epoch_order(n, seed, epoch, shuffle=True):
    """The full item order for ``epoch`` as a list of item indices:
    ``order[p]`` is the canonical item fed at global position ``p``.
    Recomputable from scalars — identical across hosts, restarts, and
    worker topologies. ``shuffle=False`` (``shuffle_row_groups=False``)
    keeps storage order: the identity, every epoch."""
    if not shuffle:
        return list(range(n))
    key = epoch_key(seed, epoch)
    return [feistel_permute(p, n, key) for p in range(n)]


@deterministic_safe
def shard_positions(n, base, cur_shard, shard_count, phase=0):
    """The global positions host ``cur_shard`` of ``shard_count`` feeds for
    one epoch: ``p`` in ``[base, n)`` with ``(p - base + phase) %
    shard_count == cur_shard``. ``base`` is the resume cursor position for
    the resumed epoch (0 for fresh epochs); ``phase`` is the count of
    global positions fed in EARLIER epochs since the job's stride base
    (mod ``shard_count``). The phase keeps host assignment continuous
    across epoch rolls — without it, an epoch whose item count is not
    divisible by ``shard_count`` would restart the round-robin at host 0
    mid-round, desynchronizing the concatenated stream from the epoch
    boundary on. With it, global item ``j`` (counted from the stride base,
    across epochs) always lands on host ``j % shard_count``, so the
    round-robin concatenation of the per-host streams is the global order
    from the cursor on — the same sequence for every ``shard_count``,
    which is the reshard-invariance mechanism."""
    first = base + ((cur_shard - phase) % shard_count)
    return list(range(first, n, shard_count))


@deterministic_safe
def order_digest(items, order):
    """Short digest of an epoch's fed order (by each item's JSON-safe
    identity keys) — the deterministic-mode twin of the ventilator's
    lineage order digest."""
    digest = hashlib.md5()
    for index in order:
        item = items[index]
        identity = ((item.get('piece_index', index),
                     item.get('shuffle_row_drop_partition'))
                    if isinstance(item, dict) else index)
        digest.update(repr(identity).encode())
    return digest.hexdigest()[:12]


# --------------------------------------------------------------------------
# chunk metadata access
# --------------------------------------------------------------------------

HOLE_KEY = '__pst_det_hole__'


def hole_marker(det):
    """The placeholder a worker publishes for a ventilated item that
    produced no chunk (empty after predicate/drop-partition slicing):
    without it the item's seq would be a hole the resequencer waits on
    forever. The results-queue readers consume and discard these after
    the resequencer advances past them. (Arrow workers publish a zero-row
    table carrying the ``b'pst.det'`` metadata instead — a dict can't
    cross the Arrow IPC serializer.)"""
    return {HOLE_KEY: 1, 'det': det}


def is_hole(chunk):
    """True for payloads that exist only to fill a sequence hole: the
    dict marker above, or a zero-row Arrow table."""
    if isinstance(chunk, dict):
        return bool(chunk.get(HOLE_KEY))
    return getattr(chunk, 'num_rows', None) == 0


def chunk_det(chunk):
    """The ``{'seq', 'epoch', 'pos'}`` deterministic tag of a published
    chunk, or ``None``. Dict payloads (tensor/py_dict/markers) carry it
    under ``'det'``; Arrow tables in their schema metadata (``b'pst.det'``,
    which survives the IPC serializer and the data-service wire)."""
    if isinstance(chunk, dict):
        return chunk.get('det')
    schema = getattr(chunk, 'schema', None)
    md = getattr(schema, 'metadata', None) if schema is not None else None
    if md and b'pst.det' in md:
        try:
            return json.loads(md[b'pst.det'].decode())
        except ValueError:
            return None
    return None


class ResequencedReads(object):
    """Mixin for results-queue readers: route pool pops through the
    reader's :class:`Resequencer` when deterministic mode armed one."""

    _resequencer = None

    def set_resequencer(self, resequencer):
        self._resequencer = resequencer

    def _pull(self, pool):
        resequencer = self._resequencer
        if resequencer is not None:
            return resequencer.next_chunk(pool)
        return pool.get_results()


# --------------------------------------------------------------------------
# order restoration
# --------------------------------------------------------------------------

class Resequencer(object):
    """Bounded reorder buffer releasing chunks strictly in ventilation order.

    Driven by the consumer thread (:meth:`next_chunk`); quarantine sinks
    fill holes for items that will never publish (:meth:`mark_satisfied`);
    the watchdog samples :meth:`stats` from its own thread — hence the
    lock (all operations are off the per-row hot path: one acquisition
    per *chunk*).

    The buffer needs no explicit pacing: the ventilator feeds at most its
    in-flight cap ahead of completion, so at most that many chunks can be
    out of order. ``max_buffer`` is a safety net against seq-accounting
    bugs, far above any real cap.
    """

    def __init__(self, max_buffer=4096, end_grace_s=2.0):
        self._lock = threading.Lock()
        self._expected = 0
        self._buffer = {}
        self._satisfied = set()   # seqs satisfied without a chunk (quarantine)
        self._wait_since = None   # monotonic time the current hole opened
        self._max_buffer = max_buffer
        self._out_of_order = 0
        #: Lost-chunk verdicts are CONSUME-UNTIL, not one-shot: the pool's
        #: end-of-data signal samples a completed-flag, an in-flight
        #: counter, and three queues non-atomically, so under heavy load a
        #: first EmptyResultError can race a final quarantine record or
        #: chunk still crossing the handoff (observed once as a full-suite
        #: load flake in PR 12). Re-polling the pool for this grace lets a
        #: transient verdict correct itself; a genuinely lost seq still
        #: raises — just ``end_grace_s`` later, on a now-stable verdict.
        self._end_grace_s = float(end_grace_s)

    def next_chunk(self, pool):
        """The next chunk in ventilation order (pulling from ``pool`` as
        needed). End-of-data / timeout / stall errors from the pool
        propagate unchanged; untagged payloads pass straight through."""
        from petastorm_tpu.workers import EmptyResultError
        grace_deadline = None
        while True:
            with self._lock:
                chunk = self._pop_ready_locked()
            if chunk is not _MISSING:
                return chunk
            try:
                result = pool.get_results()
            except EmptyResultError:
                with self._lock:
                    buffered = len(self._buffer)
                if buffered:
                    # End-of-data declared while chunks still sit behind a
                    # hole. Don't trust the first sample: poll-until the
                    # verdict holds for the whole grace (a late quarantine
                    # record or chunk re-polls out of the pool and the
                    # stream continues), THEN surface the accounting bug
                    # instead of silently reordering or dropping the
                    # buffered chunks.
                    now = time.monotonic()
                    if grace_deadline is None:
                        grace_deadline = now + self._end_grace_s
                    if now < grace_deadline:
                        time.sleep(0.01)
                        continue
                    raise RuntimeError(
                        'Resequencer: pool exhausted with {} chunk(s) '
                        'buffered behind missing ventilation seq {} — a '
                        'published chunk was lost'.format(
                            buffered, self._expected))
                raise
            grace_deadline = None
            det = chunk_det(result)
            if det is None:
                return result
            seq = det.get('seq')
            with self._lock:
                if seq is None or seq == self._expected:
                    self._advance_locked()
                    return result
                if seq < self._expected:
                    # Stale duplicate (should not happen under the pools'
                    # exactly-once redelivery); dropping preserves order.
                    continue
                self._out_of_order += 1
                self._buffer[seq] = result
                if self._wait_since is None:
                    self._wait_since = time.monotonic()
                if len(self._buffer) > self._max_buffer:
                    raise RuntimeError(
                        'Resequencer buffer overflow: {} chunks held waiting '
                        'for ventilation seq {} — sequence accounting is '
                        'broken'.format(len(self._buffer), self._expected))

    def _pop_ready_locked(self):
        while self._expected in self._satisfied:
            self._satisfied.discard(self._expected)
            self._expected += 1
        chunk = self._buffer.pop(self._expected, _MISSING)
        if chunk is not _MISSING:
            self._advance_locked()
        return chunk

    def _advance_locked(self):
        self._expected += 1
        while self._expected in self._satisfied:
            self._satisfied.discard(self._expected)
            self._expected += 1
        self._wait_since = time.monotonic() if self._buffer else None

    def mark_satisfied(self, seq):
        """Record that ``seq`` will never publish a chunk (its row-group
        was quarantined): the hole is filled so ordered release continues
        past it instead of deadlocking."""
        with self._lock:
            if seq == self._expected:
                self._advance_locked()
            elif seq > self._expected:
                self._satisfied.add(seq)

    def stats(self):
        """Watchdog-probe snapshot: how long the stream has been held at a
        hole, and how much is buffered behind it. ``waiting_s`` > 0 with
        ``buffered`` > 0 is the ``resequencer-stalled`` signature
        (``health.classify_stall``)."""
        with self._lock:
            waiting = (time.monotonic() - self._wait_since
                       if self._wait_since is not None and self._buffer
                       else 0.0)
            return {'expected_seq': self._expected,
                    'buffered': len(self._buffer),
                    'waiting_s': round(waiting, 3),
                    'out_of_order_total': self._out_of_order}

    def buffered_nbytes(self):
        """Estimated bytes held by chunks parked behind a sequence hole —
        the memory governor's ``resequencer`` accounting hook
        (``membudget.py``). The buffer is bounded by the ventilator's
        in-flight cap, so walking it per sampler tick is cheap."""
        from petastorm_tpu.membudget import approx_nbytes
        with self._lock:
            chunks = list(self._buffer.values())
        return sum(approx_nbytes(chunk) for chunk in chunks)

    def reset(self):
        """Restart sequence expectations (``Reader.reset()`` pairs this
        with the ventilator's own reset)."""
        with self._lock:
            self._expected = 0
            self._buffer.clear()
            self._satisfied.clear()
            self._wait_since = None


# --------------------------------------------------------------------------
# stream cursor
# --------------------------------------------------------------------------

class DeterministicCursor(object):
    """Consumption tracking in deterministic mode: a compact stream cursor.

    Chunks arrive strictly in ventilation order (the resequencer
    guarantees it), so consumption state is just the frontier:
    ``(epoch, global position of the open item, rows consumed into it)``.
    Unlike ``ConsumptionTracker`` there are no per-key multisets and
    resume does not skip chunks consumer-side — the ventilator
    fast-forwards the recomputable permutation to the cursor instead; the
    only consumer-side skip is the partial ``rows_into`` of the first
    chunk.

    Thread-safe for the same reason as ``ConsumptionTracker``: the
    consuming side may be a background thread while ``state_dict()`` runs
    from the training thread mid-iteration.

    Entries for chunks delivered but not yet fully attributed (rows
    buffered downstream under row-granular accounting) queue in ``_open``;
    the frontier only advances past an item when all its rows were
    attributed, so a checkpoint never counts a row the trainer has not
    seen.
    """

    def __init__(self, resume_state=None):
        self._lock = threading.Lock()
        self._open = deque()     # [epoch, pos, total_rows, rows_done]
        epoch, pos, rows = 1, 0, 0
        if resume_state:
            if resume_state.get('mode') != MODE:
                raise ValueError(
                    'resume_state is not a deterministic-mode cursor '
                    '(mode={!r}); it was captured without '
                    'deterministic=True'.format(resume_state.get('mode')))
            if resume_state.get('version') != STATE_VERSION:
                raise ValueError('Unsupported deterministic cursor version '
                                 '{!r}'.format(resume_state.get('version')))
            epoch = int(resume_state.get('epoch', 1))
            pos = int(resume_state.get('pos', 0))
            rows = int(resume_state.get('rows_into', 0))
        self.start_epoch = epoch
        self.start_pos = pos
        self.start_rows = rows
        self._frontier = (epoch, pos, rows)
        self._resume_pending = rows > 0

    def normalize(self, n_items):
        """Fold a cursor sitting exactly at an epoch's end (``pos ==
        n_items``) onto the next epoch's start, so the ventilator's
        fast-forward never targets a position past the permutation."""
        with self._lock:
            while n_items and self.start_pos >= n_items:
                self.start_epoch += 1
                self.start_pos = 0
                self.start_rows = 0
                self._resume_pending = False
                self._frontier = (self.start_epoch, 0, 0)

    # -- consumption events (same protocol as ConsumptionTracker) ----------

    def on_chunk(self, key, total_rows, det=None):
        """A chunk for global position ``det['pos']`` arrived (in order).
        Returns leading rows to drop (non-zero only for the resume
        chunk's prior-session partial)."""
        if det is None:
            return 0
        with self._lock:
            skip = 0
            if self._resume_pending:
                if (det.get('epoch') == self.start_epoch
                        and det.get('pos') == self.start_pos):
                    skip = min(self.start_rows, total_rows)
                    self._resume_pending = False
                elif (det.get('epoch', 0) > self.start_epoch
                      or (det.get('epoch') == self.start_epoch
                          and det.get('pos', 0) > self.start_pos)):
                    # Delivery is strictly ordered, so a chunk PAST the
                    # cursor means the cursor chunk will never arrive on
                    # this host — a resharded resume strides it to shard 0
                    # while shards 1..M-1 start one position later. Clear
                    # the flag or their checkpoints would stay pinned to
                    # the prior session's cursor forever.
                    self._resume_pending = False
            self._open.append([det.get('epoch'), det.get('pos'),
                               total_rows, skip])
            self._commit_locked()
            return skip

    def rows_yielded(self, key, n):
        """Attribute ``n`` consumed rows to open items in delivery order
        (``key`` is unused: order IS the identity here)."""
        with self._lock:
            while n > 0 and self._open:
                head = self._open[0]
                free = head[2] - head[3]
                if free <= 0:
                    self._commit_locked()
                    continue
                take = min(n, free)
                head[3] += take
                n -= take
                self._commit_locked()

    def _commit_locked(self):
        while self._open:
            head = self._open[0]
            if head[3] < head[2]:
                self._frontier = (head[0], head[1], head[3])
                return
            self._open.popleft()
            self._frontier = (head[0], head[1] + 1, 0)

    # -- persistence -------------------------------------------------------

    def state_dict(self):
        with self._lock:
            epoch, pos, rows = self._frontier
            if self._resume_pending:
                # Prior-session partial not yet re-observed: carry forward.
                epoch, pos, rows = (self.start_epoch, self.start_pos,
                                    self.start_rows)
            return {'version': STATE_VERSION, 'mode': MODE,
                    'epoch': int(epoch), 'pos': int(pos),
                    'rows_into': int(rows)}


def det_tag_cursor(det, rows_into=0):
    """Resume cursor for the stream position AFTER the chunk tagged ``det``.

    ``det`` is a per-chunk deterministic-mode tag ``{'seq', 'epoch',
    'pos'}`` (``Reader.last_chunk_det`` / ``RemoteReader.last_chunk_det``
    — it rides the data-service wire). The returned dict is a valid
    ``resume_state`` for any deterministic reader with the same config:
    the stream it produces continues exactly where the tagged chunk left
    off. This is the cursor a data-service consumer ships to a
    replacement server when its original died mid-epoch
    (``RemoteReader.det_cursor`` / the ``attach`` rpc) — reconnect-with-
    resume is then bit-identical to an uninterrupted stream.

    ``rows_into`` > 0 records a partially consumed tagged chunk (the
    resumed stream re-delivers only its tail)."""
    if not isinstance(det, dict) or det.get('pos') is None:
        raise ValueError('det_tag_cursor needs a deterministic chunk tag '
                         'with epoch/pos, got {!r}'.format(det))
    rows_into = int(rows_into)
    if rows_into > 0:
        # Mid-chunk cursor: resume re-delivers the open chunk's tail.
        return {'version': STATE_VERSION, 'mode': MODE,
                'epoch': int(det.get('epoch', 1)), 'pos': int(det['pos']),
                'rows_into': rows_into}
    return {'version': STATE_VERSION, 'mode': MODE,
            'epoch': int(det.get('epoch', 1)), 'pos': int(det['pos']) + 1,
            'rows_into': 0}


def merge_cursors(states):
    """The global stream cursor of a sharded job: the *least-advanced*
    per-host cursor.

    Each host of an N-shard deterministic job checkpoints its own frontier
    (the global position of ITS open item — strided positions, so hosts
    differ by at most ``shard_count``). Resuming on M hosts needs ONE
    global cursor every new host derives its stride from; the conservative
    choice is the minimum frontier — positions between it and faster
    hosts' frontiers re-deliver at most ``N - 1`` items (and any partial
    ``rows_into`` of a faster host is dropped: a merged resume restarts
    those few items from their first row). For exactly-once across a
    reshard, checkpoint at an aligned step on every host (the usual
    synchronous-training case) so the frontiers agree.

    The merge is **mandatory** for every multi-host resume: a host's own
    cursor is its private strided frontier, and resuming from it
    duplicates some positions across hosts while never delivering others
    — so the reader refuses unmerged multi-shard cursors. Pass ALL N
    hosts' cursors here (validated when they carry their shard identity)
    and hand the single merged result to every resuming host.
    """
    cursors, configs = [], []
    shard_counts, shards_seen = set(), set()
    for state in states:
        if not isinstance(state, dict) or state.get('mode') != MODE:
            raise ValueError('merge_cursors needs deterministic-mode '
                             'cursors, got {!r}'.format(state))
        if state.get('shard_count') is not None:
            shard_counts.add(int(state['shard_count']))
            if state.get('cur_shard') is not None:
                shards_seen.add(int(state['cur_shard']))
        if isinstance(state.get('config'), dict):
            configs.append(state['config'])
        cursors.append((int(state.get('epoch', 1)), int(state.get('pos', 0)),
                        int(state.get('rows_into', 0))))
    if not cursors:
        raise ValueError('merge_cursors needs at least one cursor')
    if len(shard_counts) > 1:
        raise ValueError('cursors disagree on shard_count ({}) — they were '
                         'not captured by one job'.format(sorted(shard_counts)))
    if shard_counts:
        count = shard_counts.pop()
        if shards_seen and shards_seen != set(range(count)):
            raise ValueError(
                'merge_cursors got shards {} of a {}-shard job; the global '
                'cursor needs every host\'s cursor (a missing fast shard '
                'could silently re-deliver, a missing slow one could skip '
                'rows)'.format(sorted(shards_seen), count))
    if configs and any(c != configs[0] for c in configs[1:]):
        raise ValueError('cursors carry differing reader config '
                         'fingerprints — they were not captured by one job')
    epoch, pos, rows = min(cursors)
    if (epoch, pos) != max(cursors)[:2]:
        rows = 0   # partial row offsets only make sense on an agreed item
    merged = {'version': STATE_VERSION, 'mode': MODE, 'merged': True,
              'epoch': epoch, 'pos': pos, 'rows_into': rows}
    if configs:
        # Carry the fingerprint so a resharded resume still gets the
        # config-drift warning at resume time (the deterministic
        # fingerprint already nulls cur_shard/shard_count, so every
        # host of one job stores the identical dict).
        merged['config'] = configs[0]
    return merged
