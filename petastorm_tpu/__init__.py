"""petastorm_tpu: a TPU-native Parquet data-access framework for ML training.

Brand-new implementation of the capabilities of petastorm
(github.com/WeichenXu123/petastorm, surveyed in /root/repo/SURVEY.md), designed
for JAX/XLA on TPU pods: each TPU-VM host reads a disjoint row-group shard
(``cur_shard=jax.process_index()``), decodes on host CPUs in a worker pool,
and collates batches into mesh-sharded ``jax.Array`` with double-buffered
host->HBM staging (see ``petastorm_tpu.jax_loader``).
"""

__version__ = '0.1.0'

from petastorm_tpu.autotune import AutotuneConfig  # noqa: F401
from petastorm_tpu.chunk_store import DecodedChunkStore  # noqa: F401
from petastorm_tpu.decode_budget import (  # noqa: F401
    DecodeThreadBudget, get_decode_budget)
from petastorm_tpu.determinism import (DeterministicCursor,  # noqa: F401
                                       det_tag_cursor, merge_cursors)
from petastorm_tpu.converter import make_converter  # noqa: F401
from petastorm_tpu.data_service import (DataServer, RemoteReader,  # noqa: F401
                                        checkpoint_shared_stream,
                                        load_server_snapshot, serve_dataset,
                                        verify_shared_stream_complete)
from petastorm_tpu.device_cache import DeviceDatasetCache  # noqa: F401
from petastorm_tpu.errors import (HostMemoryExceededError,  # noqa: F401
                                  PipelineStallError,
                                  RowGroupQuarantinedError, WorkerLostError)
from petastorm_tpu.flight_recorder import FlightRecorder  # noqa: F401
from petastorm_tpu.membudget import MemoryGovernor  # noqa: F401
from petastorm_tpu.job_checkpoint import JobCheckpointer  # noqa: F401
from petastorm_tpu.lineage import (LineageTracker,  # noqa: F401
                                   replay_record, verify_record)
from petastorm_tpu.metrics import (MetricsExporter,  # noqa: F401
                                   MetricsRegistry, start_http_exporter)
from petastorm_tpu.serving import (LookupClient, LookupEngine,  # noqa: F401
                                   LookupServer)
from petastorm_tpu.reader import (Reader, make_batch_reader,  # noqa: F401
                                  make_pod_reader, make_reader,
                                  make_tensor_reader)
from petastorm_tpu.trace import Tracer  # noqa: F401
from petastorm_tpu.transform import TransformSpec  # noqa: F401
from petastorm_tpu.unischema import Unischema, UnischemaField  # noqa: F401
