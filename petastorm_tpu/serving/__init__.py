"""Online lookup tier: millisecond point reads over the training cache.

ROADMAP item 5 ("millions of users means point reads, not just epoch
streams"): a feature-store-grade random-access path composed from pieces
already in-tree — the row-group index machinery
(``etl/rowgroup_indexing``) extended to row granularity, the
predicates/selectors, the mmap decoded-chunk store as a memcpy-speed hot
tier, and the data-service control-plane discipline (leases, graceful
drain, admission control with typed refusals, client circuit breaker +
hedged requests). The disaggregation thesis of the tf.data service
(arXiv:2210.14826) applied to the serving side, with the cache-tier
discipline of tf.data (arXiv:2101.12127): trainers and online lookups
warm ONE shared cache hierarchy.

Modules
-------

:mod:`petastorm_tpu.serving.row_index`
    Loads the row-level key index a ``SingleFieldRowIndexer`` pass
    persisted into ``_common_metadata``: key value -> ``(row-group,
    row-offset)`` locations.

:mod:`petastorm_tpu.serving.engine`
    :class:`~petastorm_tpu.serving.engine.LookupEngine` — the local
    request path: ``lookup(keys)`` / ``query(predicate, selector)``
    resolved through the index, served from the
    :class:`~petastorm_tpu.chunk_store.DecodedChunkStore` mmap hot tier
    (one memcpy on a hit), decode-and-fill on a miss through the same
    ``tensor_chunk_key`` the training readers use, with per-row-group
    request coalescing so a hot-key storm decodes once.

:mod:`petastorm_tpu.serving.placement`
    :class:`~petastorm_tpu.serving.placement.PartitionMap` — versioned
    consistent-hash placement of the key space over a replicated server
    fleet: partitions -> ranked replicas, a pure function of the
    membership set (every party computes the identical map), published
    in lease heartbeats so clients converge. Drain reassigns the
    drained member's key range live; a joining replica warm-fills its
    chunk store from a peer instead of cold-decoding.

:mod:`petastorm_tpu.serving.server` / :mod:`petastorm_tpu.serving.client`
    The service plane: ``lookup``/``query`` verbs on a ZMQ rpc socket
    with lease heartbeats, graceful drain, ``max_consumers`` admission
    (typed refusals), a ``membudget``-registered response pool, and SLO
    metrics (``pst_lookup_requests_total{verb,outcome}``,
    ``pst_lookup_latency_seconds``, ``pst_lookup_cache_hits_total{tier}``);
    the client failovers across endpoints, breaks the circuit on
    blackholed servers, and hedges slow reads.

Smoke-test without writing code::

    python -m petastorm_tpu.tools.lookup --dataset-url URL \
        --key id=7 [--build-index] [--store DIR] [--serve]
"""

from petastorm_tpu.serving.client import LookupClient  # noqa: F401
from petastorm_tpu.serving.engine import LookupEngine  # noqa: F401
from petastorm_tpu.serving.placement import (  # noqa: F401
    PartitionMap, build_partition_map)
from petastorm_tpu.serving.row_index import RowLocationIndex  # noqa: F401
from petastorm_tpu.serving.server import LookupServer  # noqa: F401
