"""PartitionMap: consistent-hash placement for the lookup fleet.

The lookup tier's key space is split into ``n_partitions`` hash
partitions; each partition is owned by ``replication`` servers ranked
primary-first. Placement is a **pure function of the membership set** —
a consistent-hash ring of member vnodes, walked clockwise from each
partition's own ring point — so every server (and every client) that
knows the same members computes the *identical* map without any
coordinator. Membership changes go through :func:`add_member` /
:func:`remove_member`, which recompute the ring and bump ``version``;
the consistent-hash property keeps most partition->replica assignments
stable across a single join or drain, which is what bounds the cache
warm-up a reassignment costs.

Maps travel as JSON (:meth:`PartitionMap.to_wire` /
:meth:`PartitionMap.from_wire`) inside the servers' lease-heartbeat PUB
stream and the ``pmap`` / ``pmap_update`` rpc verbs; clients and peers
converge on the highest version they have seen.

Two granularities hang off one map:

* **keys** route by hash — :meth:`PartitionMap.partition_of_key` uses
  the same string form the row-level index stores, so a client can
  route without holding the index;
* **row-group pieces** partition modularly —
  :meth:`PartitionMap.pieces_of_partition` assigns piece ordinal ``i``
  to partition ``i % n_partitions``, giving predicate scatter a disjoint
  exact cover of the dataset.
"""

import bisect
import hashlib
import json

#: Ring points per member. More vnodes = smoother balance per member at
#: O(members * vnodes * log) build cost; 64 keeps a 2-server fleet
#: within a few percent of even.
DEFAULT_VNODES = 64

DEFAULT_PARTITIONS = 8


def _hash64(text):
    """Stable 64-bit ring position (md5-derived: identical across
    processes, platforms, and PYTHONHASHSEED)."""
    digest = hashlib.md5(text.encode('utf-8')).digest()
    return int.from_bytes(digest[:8], 'little')


def partition_of_key(value, n_partitions):
    """The hash partition serving key ``value`` — matched by the key's
    STRING form, same as :class:`~petastorm_tpu.serving.row_index.
    RowLocationIndex` (so ``7`` and ``'7'`` route identically)."""
    return _hash64('key:{}'.format(value)) % int(n_partitions)


class PartitionMap(object):
    """One versioned placement: partitions -> ranked replica servers.

    :param version: monotonic map version; fleets converge on the max.
    :param n_partitions: hash-partition count (fixed for a map's life).
    :param replication: replica target R per partition (effective R is
        ``min(R, len(members))``).
    :param members: ``{server_name: {'rpc': endpoint,
        'control': endpoint-or-None}}``.
    :param assignments: ``{partition: (server_name, ...)}`` ranked
        primary-first.
    """

    def __init__(self, version, n_partitions, replication, members,
                 assignments):
        self.version = int(version)
        self.n_partitions = int(n_partitions)
        self.replication = int(replication)
        self.members = {str(name): dict(info)
                        for name, info in members.items()}
        self.assignments = {int(pid): tuple(names)
                            for pid, names in assignments.items()}

    # -- routing -----------------------------------------------------------

    def partition_of_key(self, value):
        return partition_of_key(value, self.n_partitions)

    def replicas(self, partition):
        """Server names owning ``partition``, primary first."""
        return list(self.assignments.get(int(partition), ()))

    def endpoints(self, partition):
        """The replicas' rpc endpoints, in replica-rank order."""
        out = []
        for name in self.replicas(partition):
            rpc = (self.members.get(name) or {}).get('rpc')
            if rpc and rpc not in out:
                out.append(rpc)
        return out

    def is_primary(self, name, partition):
        reps = self.assignments.get(int(partition), ())
        return bool(reps) and reps[0] == name

    def partitions_of(self, name):
        """Partitions ``name`` replicates, ascending."""
        return [pid for pid in sorted(self.assignments)
                if name in self.assignments[pid]]

    def pieces_of_partition(self, partition, n_pieces):
        """Row-group piece ordinals the modular cover assigns to
        ``partition`` — disjoint and exact over ``range(n_pieces)``."""
        return list(range(int(partition), int(n_pieces), self.n_partitions))

    # -- wire format -------------------------------------------------------

    def to_wire(self):
        """JSON-safe dict (heartbeat bodies, rpc replies)."""
        return {'version': self.version,
                'n_partitions': self.n_partitions,
                'replication': self.replication,
                'members': {name: dict(info)
                            for name, info in self.members.items()},
                'assignments': {str(pid): list(names)
                                for pid, names in self.assignments.items()}}

    @classmethod
    def from_wire(cls, wire):
        try:
            return cls(wire['version'], wire['n_partitions'],
                       wire['replication'], wire['members'],
                       wire['assignments'])
        except (TypeError, KeyError, ValueError) as e:
            raise ValueError('malformed partition map {!r}: {}'
                             .format(wire, e))

    def to_json(self):
        return json.dumps(self.to_wire(), sort_keys=True)

    def __eq__(self, other):
        return (isinstance(other, PartitionMap)
                and self.to_wire() == other.to_wire())

    def __ne__(self, other):
        return not self.__eq__(other)

    def __repr__(self):
        return ('PartitionMap(v{m.version}, {m.n_partitions}p x '
                'R{m.replication}, members={names})'.format(
                    m=self, names=sorted(self.members)))


def _ring(names, vnodes):
    points = []
    for name in names:
        for vnode in range(vnodes):
            points.append((_hash64('member:{}#{}'.format(name, vnode)),
                           name))
    points.sort()
    return points


def build_partition_map(members, n_partitions=DEFAULT_PARTITIONS,
                        replication=2, version=1, vnodes=DEFAULT_VNODES):
    """Compute placement from scratch: deterministic in ``members`` (any
    two parties holding the same membership derive byte-identical
    assignments). Each partition hashes onto the vnode ring and takes
    the next ``replication`` DISTINCT members clockwise, primary first.
    """
    names = sorted(str(n) for n in members)
    if not names:
        raise ValueError('a partition map needs at least one member')
    n_partitions = int(n_partitions)
    if n_partitions < 1:
        raise ValueError('n_partitions must be >= 1, got {}'
                         .format(n_partitions))
    effective_r = min(int(replication), len(names))
    if effective_r < 1:
        raise ValueError('replication must be >= 1, got {}'
                         .format(replication))
    points = _ring(names, vnodes)
    assignments = {}
    for pid in range(n_partitions):
        start = bisect.bisect_left(points,
                                   (_hash64('partition:{}'.format(pid)), ''))
        chosen = []
        for offset in range(len(points)):
            name = points[(start + offset) % len(points)][1]
            if name not in chosen:
                chosen.append(name)
                if len(chosen) == effective_r:
                    break
        assignments[pid] = tuple(chosen)
    return PartitionMap(version, n_partitions, replication,
                        {name: dict(members[name]) for name in members},
                        assignments)


def add_member(pmap, name, rpc, control=None):
    """A joining replica: recomputed placement over ``members + name``,
    ``version + 1``."""
    members = {n: dict(info) for n, info in pmap.members.items()}
    members[str(name)] = {'rpc': rpc, 'control': control}
    return build_partition_map(members, n_partitions=pmap.n_partitions,
                               replication=pmap.replication,
                               version=pmap.version + 1)


def remove_member(pmap, name):
    """A draining/dead replica: recomputed placement without ``name``,
    ``version + 1``. The last member cannot leave (an empty map routes
    nothing — keep the map and let lease expiry mark the corpse)."""
    members = {n: dict(info) for n, info in pmap.members.items()
               if n != str(name)}
    if not members:
        raise ValueError('cannot remove the last fleet member {!r}'
                         .format(name))
    return build_partition_map(members, n_partitions=pmap.n_partitions,
                               replication=pmap.replication,
                               version=pmap.version + 1)
