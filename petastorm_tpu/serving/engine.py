"""LookupEngine: the local point-read request path.

Resolves ``lookup(keys)`` / ``query(predicate, selector)`` through the
row-level index (:mod:`petastorm_tpu.serving.row_index`) and serves
decoded rows from the same cache hierarchy the training feed warms:

* **chunk-store hit** — the row-group's decoded block is mmapped out of
  the :class:`~petastorm_tpu.chunk_store.DecodedChunkStore` (one memcpy
  per served row; the store key is the *identical*
  :func:`~petastorm_tpu.chunk_store.tensor_chunk_key` the training
  ``TensorWorker`` computes, so an epoch that already ran — or a
  ``tools.transcode`` pre-fill — makes every point read warm, and a
  lookup-driven fill warms the next training epoch right back);
* **memory hit** — a small per-engine LRU of recently served blocks
  skips even the store's dict/validation work for hot row-groups
  (``membudget``-registered: the governor's degrade rung sheds it);
* **decode miss** — read + decode the row-group through the same
  ``decode_table_to_blocks`` path the workers use, with **per-row-group
  request coalescing**: of N concurrent requests hitting one cold
  row-group, one decodes and the rest wait on its fill — a hot-key storm
  costs one decode, not N.

The engine is thread-safe (the :class:`~petastorm_tpu.serving.server.
LookupServer` drives it from several rpc worker threads) and the block
path is lock-free once a block is resident.
"""

import hashlib
import logging
import threading
from collections import OrderedDict

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

logger = logging.getLogger(__name__)

#: Cache-tier labels for ``pst_lookup_cache_hits_total{tier}``.
TIER_MEMORY = 'memory'
TIER_DECODE = 'decode'
TIER_COALESCED = 'coalesced'

_DEFAULT_BLOCK_CACHE_ENTRIES = 8


class _Fill(object):
    """One in-flight block fill other requests coalesce onto."""

    __slots__ = ('event', 'cols', 'tier', 'error')

    def __init__(self):
        self.event = threading.Event()
        self.cols = None
        self.tier = None
        self.error = None


class LookupEngine(object):
    """Low-latency random access over one dataset.

    :param dataset_url: the dataset to serve (``file://``, ...).
    :param index_name: name of the row-level index
        (``SingleFieldRowIndexer``) to resolve keys through; ``None``
        auto-selects when the dataset stores exactly one.
    :param cache: the hot tier — a
        :class:`~petastorm_tpu.chunk_store.DecodedChunkStore` (or any
        ``CacheBase``), a directory path (builds a chunk store there; the
        engine owns and closes it), or ``None`` (every cold block is a
        fresh decode; the in-engine LRU still absorbs hot row-groups).
        Share the TRAINING pipeline's store directory so both sides warm
        one cache.
    :param schema_fields: field-name list to serve (``None`` = all). Must
        match the training reader's selection for chunk-store keys to
        line up (the key hashes the schema's field set).
    :param block_cache_entries: in-engine decoded-block LRU depth.
    :param decode_threads: native decode threads per miss (``None`` =
        the process decode budget's default resolution).
    """

    def __init__(self, dataset_url, index_name=None, cache=None,
                 schema_fields=None, storage_options=None,
                 block_cache_entries=_DEFAULT_BLOCK_CACHE_ENTRIES,
                 decode_threads=None):
        from petastorm_tpu import metrics as metrics_mod
        from petastorm_tpu.etl.dataset_metadata import get_schema
        from petastorm_tpu.serving.row_index import RowLocationIndex
        from petastorm_tpu.storage import ParquetStore
        from petastorm_tpu.tensor_worker import validate_tensor_schema

        self._store = ParquetStore(dataset_url, storage_options)
        schema = get_schema(self._store)
        if schema_fields is not None:
            schema = schema.create_schema_view(list(schema_fields))
        # Same constraint as make_tensor_reader: rows decode into dense
        # blocks (that is what the chunk store persists and what a
        # memcpy-speed hit requires).
        validate_tensor_schema(schema)
        self.schema = schema
        self._pieces = self._store.row_groups()
        self._partition_names = set(self._store.partition_names)
        self._physical = [n for n in schema.fields
                          if n not in self._partition_names]
        self._path_hash = hashlib.md5(
            self._store.url.encode()).hexdigest()[:12]
        self.index = RowLocationIndex.load(self._store, index_name)
        if self.index.field not in schema.fields:
            raise ValueError(
                'row index {!r} keys field {!r}, which the served schema '
                'does not include'.format(self.index.name, self.index.field))
        self._decode_threads = decode_threads

        self._owns_cache = isinstance(cache, str)
        if self._owns_cache:
            from petastorm_tpu.chunk_store import DecodedChunkStore
            cache = DecodedChunkStore(cache)
        self._cache = cache

        self._lock = threading.Lock()
        self._blocks = OrderedDict()        # piece_index -> cols dict
        self._max_blocks = max(1, int(block_cache_entries))
        self._fills = {}                    # piece_index -> _Fill
        self._tier_counts = {}
        self._coalesced = 0
        self._closed = False

        self._m_hits = metrics_mod.counter(
            'pst_lookup_cache_hits_total',
            'Lookup-path block fetches, by serving tier',
            labelnames=('tier',))
        self._m_warm_fills = metrics_mod.counter(
            'pst_partition_warm_fill_chunks_total',
            'Chunk-store entries pre-filled from a peer replica at '
            'warm join')
        # Open-mmap / block accounting rides the memory governor like
        # every other byte-holding pool: the LRU sheds on degrade, and an
        # engine-owned chunk store registers its mmap residency too.
        from petastorm_tpu import membudget
        self._mem_handles = [membudget.register_pool(
            'lookup-blocks', self._blocks_nbytes,
            degrade_fn=self._shed_blocks)]
        if self._owns_cache:
            self._mem_handles.append(membudget.register_pool(
                'lookup-store', cache.governed_nbytes,
                degrade_fn=cache.close_lru_mmaps,
                advisory_fn=cache.set_spill_paused))

    # -- cache accounting --------------------------------------------------

    def _blocks_nbytes(self):
        with self._lock:
            blocks = list(self._blocks.values())
        return sum(int(getattr(arr, 'nbytes', 0))
                   for cols in blocks for arr in cols.values())

    def _shed_blocks(self):
        """Governor degrade hook: drop the older half of the block LRU.
        Returns True when anything was released."""
        with self._lock:
            keep = len(self._blocks) // 2
            dropped = 0
            while len(self._blocks) > keep:
                self._blocks.popitem(last=False)
                dropped += 1
        return dropped > 0

    def _count_tier(self, tier):
        self._m_hits.labels(tier).inc()
        with self._lock:
            self._tier_counts[tier] = self._tier_counts.get(tier, 0) + 1

    # -- block path --------------------------------------------------------

    def _chunk_key(self, piece):
        from petastorm_tpu.chunk_store import tensor_chunk_key
        return tensor_chunk_key(self._path_hash, piece.path,
                                piece.row_group, self.schema)

    def _decode_block(self, piece):
        """Read + decode one row-group into ``{field: block}`` — the same
        path ``TensorWorker.load()`` takes on a cache miss, so a
        lookup-driven fill publishes byte-identical blocks."""
        from petastorm_tpu.tensor_worker import decode_table_to_blocks
        with self._store.open_file(piece.path) as f:
            table = pq.ParquetFile(f).read_row_group(
                piece.row_group, columns=self._physical)
        for name, value in piece.partition_values.items():
            if name in self.schema.fields \
                    and name not in table.column_names:
                table = table.append_column(
                    name, pa.array([value] * table.num_rows))
        return decode_table_to_blocks(table, self.schema,
                                      self._decode_threads)

    def _fetch_block(self, piece_index):
        """``{field: block}`` for one row-group, through memory LRU ->
        chunk store -> decode, coalescing concurrent cold fetches."""
        while True:
            with self._lock:
                if self._closed:
                    raise RuntimeError('LookupEngine is closed')
                cols = self._blocks.get(piece_index)
                if cols is not None:
                    self._blocks.move_to_end(piece_index)
                    self._m_hits.labels(TIER_MEMORY).inc()
                    self._tier_counts[TIER_MEMORY] = \
                        self._tier_counts.get(TIER_MEMORY, 0) + 1
                    return cols
                fill = self._fills.get(piece_index)
                filler = fill is None
                if filler:
                    fill = self._fills[piece_index] = _Fill()
            if not filler:
                fill.event.wait()
                if fill.error is not None:
                    raise fill.error
                self._m_hits.labels(TIER_COALESCED).inc()
                with self._lock:
                    self._tier_counts[TIER_COALESCED] = \
                        self._tier_counts.get(TIER_COALESCED, 0) + 1
                    self._coalesced += 1
                return fill.cols
            try:
                cols, tier = self._fill_block(piece_index)
                fill.cols, fill.tier = cols, tier
            except Exception as e:  # noqa: BLE001 - waiters re-raise it too
                fill.error = e
                raise
            finally:
                with self._lock:
                    self._fills.pop(piece_index, None)
                    if fill.cols is not None:
                        self._blocks[piece_index] = fill.cols
                        while len(self._blocks) > self._max_blocks:
                            self._blocks.popitem(last=False)
                fill.event.set()
            self._count_tier(tier)
            return cols

    def _fill_block(self, piece_index):
        """(cols, tier) through the shared cache (or a bare decode)."""
        piece = self._pieces[piece_index]
        if self._cache is None:
            return self._decode_block(piece), TIER_DECODE
        decoded_fresh = []

        def load():
            decoded_fresh.append(True)
            return self._decode_block(piece)

        cols = self._cache.get(self._chunk_key(piece), load)
        if cols is None:       # empty row-group (cannot happen via index)
            cols = {name: np.empty((0,)) for name in self.schema.fields}
        tier = (TIER_DECODE if decoded_fresh
                else getattr(self._cache, 'lineage_tier', 'cache'))
        return cols, tier

    # -- fleet support -----------------------------------------------------

    @property
    def piece_count(self):
        return len(self._pieces)

    def chunk_key(self, piece_index):
        """The chunk-store cache key of one row-group piece — identical
        across replicas serving the same dataset url/schema, which is
        what makes peer-to-peer cache warming sound."""
        return self._chunk_key(self._pieces[piece_index])

    def has_cached(self, piece_index):
        """True when the hot tier already holds this piece (warm join
        skips it without touching the peer)."""
        has = getattr(self._cache, 'has', None)
        if not callable(has):
            return False
        return bool(has(self.chunk_key(piece_index)))

    def packed_chunk(self, piece_index):
        """One piece's decoded block serialized in the chunk-store
        layout (CRC-protected) — the peer side of the warm-join
        protocol. Fetches through the normal tier ladder, so exporting
        warms the exporter too."""
        from petastorm_tpu.chunk_store import pack_tensor_chunk
        return pack_tensor_chunk(self._fetch_block(piece_index))

    def warm_fill(self, piece_index, blob):
        """The joining side: validate a peer's packed chunk and persist
        it straight into this engine's :class:`DecodedChunkStore` under
        the piece's own ``tensor_chunk_key`` — the piece's first real
        read then hits the chunk-store tier instead of cold-decoding.
        Raises ``CorruptChunkError`` on a torn/bit-rotted blob and
        ``ValueError`` when the peer served a different field set."""
        from petastorm_tpu.chunk_store import read_tensor_chunk
        put = getattr(self._cache, 'put', None)
        if not callable(put):
            raise ValueError(
                'warm_fill needs a DecodedChunkStore hot tier (engine '
                'cache is {!r})'.format(type(self._cache).__name__))
        cols = read_tensor_chunk(bytes(blob),
                                 source='warm-fill:{}'.format(piece_index))
        missing = set(self.schema.fields) - set(cols)
        if missing:
            raise ValueError('peer chunk for piece {} lacks served '
                             'fields {}'.format(piece_index,
                                                sorted(missing)))
        accepted = bool(put(self.chunk_key(piece_index), cols))
        if accepted:
            self._m_warm_fills.inc()
        return accepted

    def pieces_for_partitions(self, pmap, partitions):
        """Row-group piece ordinals a replica owning ``partitions``
        should hold warm: every piece the modular query cover assigns it
        plus every piece holding a key that hashes into one of its
        partitions (resolved through the row index)."""
        wanted = set(int(p) for p in partitions)
        pieces = set()
        for pid in wanted:
            pieces.update(pmap.pieces_of_partition(pid, len(self._pieces)))
        for key in self.index.keys():
            if pmap.partition_of_key(key) in wanted:
                pieces.update(p for p, _ in self.index.locations(key))
        return sorted(pieces)

    # -- request path ------------------------------------------------------

    def _slice_row(self, cols, offset, fields):
        """One served row: a fresh copy of each field's row slice (the
        blocks may be shared read-only mmap views — the response must not
        alias the store)."""
        row = {}
        for name in fields:
            row[name] = np.array(cols[name][offset], copy=True)
        return row

    def _resolve_fields(self, fields):
        if fields is None:
            return list(self.schema.fields)
        unknown = [f for f in fields if f not in self.schema.fields]
        if unknown:
            raise ValueError('unknown fields {} (serving {})'.format(
                unknown, sorted(self.schema.fields)))
        return list(fields)

    def lookup(self, keys, fields=None):
        """Point reads: for each key, the list of matching rows (each a
        ``{field: numpy value}`` dict; empty list = key absent). Keys
        hitting one row-group share a single block fetch."""
        fields = self._resolve_fields(fields)
        locations = [self.index.locations(key) for key in keys]
        needed = []          # piece ordinals, deduped, in first-use order
        for locs in locations:
            for piece, _ in locs:
                if piece not in needed:
                    needed.append(piece)
        blocks = {piece: self._fetch_block(piece) for piece in needed}
        return [[self._slice_row(blocks[piece], offset, fields)
                 for piece, offset in locs]
                for locs in locations]

    def query(self, predicate, selector=None, limit=None, fields=None,
              pieces=None, with_locations=False):
        """Predicate scan with index pruning: evaluate ``predicate`` (a
        ``predicates.PredicateBase``, e.g. ``in_lambda``) over every row
        of the candidate row-groups — all of them, or the set a
        ``selectors``-module selector picks from the stored indexes —
        serving matches until ``limit``.

        ``pieces`` restricts the scan to those row-group ordinals (the
        fleet's scatter-gather sends each partition its modular share of
        the dataset, so the union over partitions covers every piece
        exactly once). ``with_locations=True`` wraps each match as
        ``{'piece', 'offset', 'row'}`` so a gatherer can merge partial
        results back into single-engine dataset order."""
        fields = self._resolve_fields(fields)
        if limit is not None and limit <= 0:
            return []
        predicate_fields = sorted(predicate.get_fields())
        unknown = set(predicate_fields) - set(self.schema.fields)
        if unknown:
            raise ValueError(
                'predicate uses fields the engine does not serve: {}'
                .format(sorted(unknown)))
        if selector is not None:
            from petastorm_tpu.etl.rowgroup_indexing import \
                get_row_group_indexes
            indexes = get_row_group_indexes(self._store)
            candidates = sorted(
                p for p in selector.select_row_groups(indexes)
                if 0 <= p < len(self._pieces))
        else:
            candidates = range(len(self._pieces))
        if pieces is not None:
            allowed = set(int(p) for p in pieces)
            candidates = [p for p in candidates if p in allowed]
        rows = []
        for piece_index in candidates:
            cols = self._fetch_block(piece_index)
            n = len(next(iter(cols.values()))) if cols else 0
            for i in range(n):
                values = {f: cols[f][i] for f in predicate_fields}
                if predicate.do_include(values):
                    row = self._slice_row(cols, i, fields)
                    rows.append({'piece': piece_index, 'offset': i,
                                 'row': row} if with_locations else row)
                    if limit is not None and len(rows) >= limit:
                        return rows
        return rows

    # -- observability / lifecycle ----------------------------------------

    def flush(self, timeout_s=30.0):
        """Block until the hot tier's write-behind spill drains (lookup-
        driven fills are published asynchronously — flush before
        measuring warm reads or handing the store to another consumer).
        True when drained, or when the cache has no spill to flush."""
        cache_flush = getattr(self._cache, 'flush', None)
        if cache_flush is None:
            return True
        return bool(cache_flush(timeout_s))

    def stats(self):
        with self._lock:
            tiers = dict(self._tier_counts)
            resident = len(self._blocks)
        out = {'dataset_url': self._store.url,
               'index': self.index.name,
               'index_field': self.index.field,
               'indexed_keys': len(self.index),
               'row_groups': len(self._pieces),
               'tiers': tiers,
               'coalesced': self._coalesced,
               'resident_blocks': resident}
        cache_stats = getattr(self._cache, 'stats', None)
        if callable(cache_stats):
            out['store'] = cache_stats()
        return out

    def close(self):
        with self._lock:
            self._closed = True
            self._blocks.clear()
        for handle in self._mem_handles:
            handle.close()
        self._mem_handles = []
        if self._owns_cache:
            self._cache.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
