"""LookupClient: fleet-aware point reads with failover, breaker, hedging.

The consumer half of the lookup tier's control-plane contract
(:mod:`petastorm_tpu.serving.server`):

* **typed-refusal failover** — a ``{'refused': 'draining'|'overloaded'}``
  reply is breaker-*success* (the server is alive and answering) but the
  request immediately moves to the next endpoint; when EVERY endpoint
  refuses, :class:`~petastorm_tpu.errors.ServerOverloaded` carries the
  refusal reason out to the caller;
* **per-endpoint circuit breaker**
  (:class:`~petastorm_tpu.retry.CircuitBreaker`) — a blackholed server
  costs the rpc timeout ``failure_threshold`` times, then is skipped
  instantly until its half-open probe heals;
* **hedged reads** — when a server sits on a request past
  ``hedge_after_ms``, the same read is also sent to the next healthy
  endpoint; first valid reply wins (``pst_lookup_hedges_total``). Safe
  because lookups are idempotent reads of an immutable dataset;
* **lease awareness** — the client drains the servers' heartbeat PUB
  stream between requests; endpoints whose last heartbeat reported
  ``draining``/``drained``, or that went a full lease silent after
  heartbeating, sort to the back of the candidate list (the PR-10
  zero-rpc liveness rule).
"""

import logging
import pickle
import threading
import time
import uuid

logger = logging.getLogger(__name__)

_REFUSAL_REASONS = ('draining', 'drained', 'overloaded')


class LookupClient(object):
    """Point reads against one or more :class:`LookupServer` endpoints
    serving the SAME dataset (replicas — hedging and failover assume any
    endpoint can answer any read).

    :param endpoints: rpc endpoint list (``tcp://host:port``).
    :param control_endpoints: matching heartbeat endpoints (optional;
        enables lease-aware endpoint ordering).
    :param timeout_ms: whole-request deadline.
    :param hedge_after_ms: silence before the next endpoint is hedged.
    :param consumer_id: admission identity (default: a fresh uuid).
    """

    def __init__(self, endpoints, control_endpoints=None, timeout_ms=5000,
                 hedge_after_ms=300, consumer_id=None,
                 breaker_threshold=3, breaker_reset_s=15.0):
        import zmq
        self._zmq = zmq
        self._context = zmq.Context.instance()
        self._endpoints = list(endpoints)
        if not self._endpoints:
            raise ValueError('LookupClient needs at least one endpoint')
        self._timeout_ms = int(timeout_ms)
        self._hedge_after_ms = int(hedge_after_ms)
        self._consumer_id = consumer_id or 'lookup-{}'.format(
            uuid.uuid4().hex[:12])
        self._lock = threading.Lock()
        self._breakers = {}
        self._breaker_threshold = int(breaker_threshold)
        self._breaker_reset_s = float(breaker_reset_s)
        # Persistent per-endpoint REQ sockets (the "lazy pirate"
        # optimization): a fresh TCP + ZMTP handshake costs several ms —
        # more than a warm point read itself — so sockets that completed
        # a clean round trip are cached and reused. A socket whose
        # request timed out, got a garbled reply, or was abandoned by a
        # winning hedge is CLOSED instead (a REQ state machine cannot
        # take a new request while one is outstanding).
        self._socks = {}
        from petastorm_tpu import metrics as metrics_mod
        self._m_hedges = metrics_mod.counter(
            'pst_lookup_hedges_total',
            'Lookup requests where a hedge was sent to another endpoint')
        self.hedges = 0
        self._closed = False
        # Lease watching: SUB to every control endpoint; heartbeats drain
        # non-blocking at each request. Keyed by the DIALED rpc endpoint:
        # {endpoint: (state, lease_s, at)}. A heartbeat advertises the
        # server's own view of its rpc address, which can differ from the
        # address the client dialed (wildcard binds resolve to a
        # hostname; the operator dialed an IP) — `_server_ids` maps the
        # heartbeat's server_id to the dialed endpoint, learned from rpc
        # replies (every reply carries `server_id`), so the ranking
        # always looks heartbeats up under the key it ranks by.
        self._hb = {}
        self._server_ids = {}
        self._sub = None
        if control_endpoints:
            self._sub = self._context.socket(zmq.SUB)
            self._sub.setsockopt(zmq.SUBSCRIBE, b'')
            for ctrl_ep in control_endpoints:
                self._sub.connect(ctrl_ep)

    # -- endpoint health ---------------------------------------------------

    def _breaker(self, endpoint):
        from petastorm_tpu.retry import CircuitBreaker
        with self._lock:
            breaker = self._breakers.get(endpoint)
            if breaker is None:
                breaker = self._breakers[endpoint] = CircuitBreaker(
                    failure_threshold=self._breaker_threshold,
                    reset_timeout_s=self._breaker_reset_s)
            return breaker

    def _socket_for(self, endpoint):
        """A ready REQ socket for ``endpoint`` — the cached one (idle,
        clean) or a fresh connect."""
        zmq = self._zmq
        with self._lock:
            sock = self._socks.pop(endpoint, None)
        if sock is None:
            sock = self._context.socket(zmq.REQ)
            sock.setsockopt(zmq.LINGER, 0)
            sock.connect(endpoint)
        return sock

    def _release_socket(self, endpoint, sock):
        """Return a socket whose round trip completed cleanly."""
        with self._lock:
            if self._closed or endpoint in self._socks:
                pass
            else:
                self._socks[endpoint] = sock
                return
        sock.close(linger=0)

    def breaker_state(self, endpoint):
        return self._breaker(endpoint).state

    def _note_server_id(self, endpoint, reply):
        """Bind a reply's server identity to the endpoint we dialed —
        what lets heartbeats (which advertise the server's OWN address
        view) resolve back to the dialed key the ranking uses."""
        sid = reply.get('server_id') if isinstance(reply, dict) else None
        if sid is not None:
            self._server_ids[sid] = endpoint

    def _drain_heartbeats(self):
        """Non-blocking: fold every queued lease heartbeat into the
        per-endpoint view (SUB sockets are owned by the caller thread —
        requests are issued from whatever thread calls them, but the
        client is documented single-caller like RemoteReader)."""
        if self._sub is None:
            return
        from petastorm_tpu.serving.server import CTRL_HB
        import json
        zmq = self._zmq
        while True:
            try:
                raw = self._sub.recv(zmq.NOBLOCK)
            except zmq.Again:
                return
            except zmq.ZMQError:
                return
            if not raw.startswith(CTRL_HB):
                continue
            try:
                body = json.loads(raw[len(CTRL_HB):].decode('utf-8'))
            except ValueError:
                continue
            # Resolve the heartbeat to the DIALED endpoint the ranking
            # keys by: via the server-id binding learned from replies,
            # else the advertised rpc address when it happens to be one
            # we dialed (the loopback/test case).
            endpoint = self._server_ids.get(body.get('server_id'))
            if endpoint is None:
                rpc = body.get('rpc')
                endpoint = rpc if rpc in self._endpoints else None
            if endpoint is not None:
                self._hb[endpoint] = (body.get('state'),
                                      float(body.get('lease_s') or 10.0),
                                      time.monotonic())

    def _candidates(self):
        """Endpoints to try, healthiest first: breaker-open endpoints
        last, then lease-draining/expired ones, then everything else in
        declared order."""
        from petastorm_tpu.retry import CircuitBreaker
        self._drain_heartbeats()
        now = time.monotonic()

        def rank(endpoint):
            score = 0
            if self._breaker(endpoint).state == CircuitBreaker.OPEN:
                score += 4
            hb = self._hb.get(endpoint)
            if hb is not None:
                state, lease_s, at = hb
                if state in ('draining', 'drained'):
                    score += 2
                if now - at > lease_s:
                    # Heartbeats stopped for a whole lease: presumed dead
                    # without paying an rpc timeout to find out.
                    score += 3
            return score
        return sorted(self._endpoints, key=rank)

    # -- the request core --------------------------------------------------

    def _request(self, request, hedge=True):
        """One logical request with failover + hedging. Returns the first
        non-refusal reply; raises ``ServerOverloaded`` when every
        endpoint refused, ``RpcUnanswered`` when nobody answered."""
        from petastorm_tpu.data_service import RpcUnanswered
        from petastorm_tpu.errors import ServerOverloaded
        zmq = self._zmq
        if self._closed:
            raise RuntimeError('LookupClient is closed')
        request = dict(request, consumer=self._consumer_id)
        payload = pickle.dumps(request, protocol=5)
        candidates = self._candidates()
        deadline = time.monotonic() + self._timeout_ms / 1000.0
        poller = zmq.Poller()
        socks = {}
        pending = list(candidates)
        refusal = None
        try:
            while True:
                now = time.monotonic()
                if now >= deadline:
                    break
                if pending and (hedge or not socks):
                    endpoint = pending.pop(0)
                    if not self._breaker(endpoint).allow():
                        # Open circuit: skip instantly (the allow() call
                        # is also what grants the half-open heal probe
                        # once the reset timeout elapses).
                        continue
                    # A genuine hedge = sending while another endpoint
                    # still has this request outstanding. A sequential
                    # failover (prior endpoint already answered a typed
                    # refusal, nothing in flight) is NOT one — counting
                    # it would inflate the hedge SLO metric on every
                    # read of a rolling drain.
                    is_hedge = bool(socks)
                    sock = self._socket_for(endpoint)
                    sock.send(payload)
                    poller.register(sock, zmq.POLLIN)
                    socks[sock] = endpoint
                    if is_hedge:
                        self._m_hedges.inc()
                        with self._lock:
                            self.hedges += 1
                elif not socks:
                    break            # everyone answered a refusal/error
                wait_ms = (deadline - now) * 1000.0
                if pending and hedge:
                    wait_ms = min(wait_ms, self._hedge_after_ms)
                for sock, _ in poller.poll(max(int(wait_ms), 1)):
                    endpoint = socks[sock]
                    try:
                        reply = pickle.loads(sock.recv())
                    except Exception:  # noqa: BLE001 - garbled: next hedge
                        self._breaker(endpoint).record_failure()
                        poller.unregister(sock)
                        sock.close(linger=0)
                        del socks[sock]
                        continue
                    self._breaker(endpoint).record_success()
                    self._note_server_id(endpoint, reply)
                    poller.unregister(sock)
                    del socks[sock]
                    self._release_socket(endpoint, sock)
                    if isinstance(reply, dict) and 'refused' in reply:
                        # Typed admission refusal: the server is healthy
                        # but not taking us — remember why, fail over NOW
                        # (don't wait out the hedge delay).
                        refusal = (endpoint, reply)
                        self._hb[endpoint] = (
                            reply.get('state') or reply.get('refused'),
                            self._hb.get(endpoint, (None, 10.0, 0))[1],
                            time.monotonic())
                        continue
                    if isinstance(reply, dict) and 'error' in reply:
                        raise RuntimeError(
                            'lookup rpc failed on {}: {}'.format(
                                endpoint, reply['error']))
                    return reply
                if not socks and not pending:
                    break
            for endpoint in socks.values():
                # Sat on the request for the whole budget: breaker-visible.
                self._breaker(endpoint).record_failure()
            if refusal is not None:
                endpoint, reply = refusal
                raise ServerOverloaded(
                    'every lookup endpoint refused this consumer '
                    '(last: {} said {!r})'.format(endpoint,
                                                  reply.get('refused')),
                    endpoint=endpoint,
                    reason=reply.get('reason') or reply.get('refused'))
            raise RpcUnanswered(
                'no lookup endpoint answered within {}ms (tried {})'.format(
                    self._timeout_ms, candidates))
        finally:
            for sock in socks:
                sock.close(linger=0)

    # -- public verbs ------------------------------------------------------

    def lookup(self, keys, fields=None):
        """Point reads: per key, the list of matching rows
        (``{field: numpy value}`` dicts; empty list = absent key)."""
        reply = self._request({'cmd': 'lookup', 'keys': list(keys),
                               'fields': list(fields) if fields else None})
        return reply['rows']

    def lookup_one(self, key, fields=None):
        """The single row for ``key``, or ``None`` when absent; raises
        on a key matching several rows (use :meth:`lookup`)."""
        rows = self.lookup([key], fields=fields)[0]
        if len(rows) > 1:
            raise ValueError('key {!r} matches {} rows'.format(
                key, len(rows)))
        return rows[0] if rows else None

    def query(self, predicate, selector=None, limit=None, fields=None):
        """Server-side predicate scan (``predicates.in_lambda`` etc.,
        with optional ``selectors`` row-group pruning). The predicate and
        selector must be picklable — module-level functions, not bare
        lambdas."""
        reply = self._request({'cmd': 'query', 'predicate': predicate,
                               'selector': selector, 'limit': limit,
                               'fields': list(fields) if fields else None})
        return reply['rows']

    def attach(self):
        """Explicit admission handshake (reads attach implicitly)."""
        return self._request({'cmd': 'attach'}, hedge=False)

    def stats(self):
        return self._request({'cmd': 'stats'})

    def schema(self):
        return self._request({'cmd': 'schema'})['schema']

    def fleet_metrics(self, timeout_ms=2000):
        """Per-server metrics snapshots + the summed fleet aggregate —
        the same shape as ``RemoteReader.fleet_metrics()`` (deduped on
        the process registry id so co-located servers fold once)."""
        from petastorm_tpu import metrics as metrics_mod
        per_server, unreachable, seen = {}, [], set()
        for endpoint in self._endpoints:
            try:
                reply = self._request_one(endpoint,
                                          {'cmd': 'metrics'},
                                          timeout_ms)
            except Exception as e:  # noqa: BLE001 - fold into unreachable
                unreachable.append({'endpoint': endpoint,
                                    'error': repr(e)})
                continue
            if not isinstance(reply, dict) or 'metrics' not in reply:
                unreachable.append({'endpoint': endpoint,
                                    'error': repr(reply)})
                continue
            per_server[endpoint] = reply
        snapshots = []
        for reply in per_server.values():
            rid = reply.get('registry_id')
            if rid is not None and rid in seen:
                continue
            seen.add(rid)
            snapshots.append(reply['metrics'])
        return {'servers': per_server,
                'aggregate': metrics_mod.aggregate_snapshots(snapshots),
                'unreachable': unreachable}

    def _request_one(self, endpoint, request, timeout_ms):
        """Single-endpoint rpc (no failover) under the breaker."""
        from petastorm_tpu.data_service import RpcUnanswered
        zmq = self._zmq
        breaker = self._breaker(endpoint)
        if not breaker.allow():
            raise RpcUnanswered('{} circuit open'.format(endpoint))
        sock = self._socket_for(endpoint)
        clean = False
        try:
            sock.send(pickle.dumps(dict(request,
                                        consumer=self._consumer_id),
                                   protocol=5))
            if not sock.poll(timeout_ms):
                breaker.record_failure()
                raise RpcUnanswered('{} gave no reply within {}ms'.format(
                    endpoint, timeout_ms))
            reply = pickle.loads(sock.recv())
            breaker.record_success()
            self._note_server_id(endpoint, reply)
            clean = True
            return reply
        finally:
            if clean:
                self._release_socket(endpoint, sock)
            else:
                sock.close(linger=0)

    def close(self):
        self._closed = True
        with self._lock:
            cached, self._socks = dict(self._socks), {}
        for sock in cached.values():
            sock.close(linger=0)
        if self._sub is not None:
            self._sub.close(linger=0)
            self._sub = None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
