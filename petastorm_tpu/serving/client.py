"""LookupClient: fleet-aware point reads with failover, breaker, hedging.

The consumer half of the lookup tier's control-plane contract
(:mod:`petastorm_tpu.serving.server`):

* **typed-refusal failover** — a ``{'refused': 'draining'|'overloaded'}``
  reply is breaker-*success* (the server is alive and answering) but the
  request immediately moves to the next endpoint; when EVERY endpoint
  refuses, :class:`~petastorm_tpu.errors.ServerOverloaded` carries the
  refusal reason out to the caller;
* **per-endpoint circuit breaker**
  (:class:`~petastorm_tpu.retry.CircuitBreaker`) — a blackholed server
  costs the rpc timeout ``failure_threshold`` times, then is skipped
  instantly until its half-open probe heals;
* **hedged reads** — when a server sits on a request past
  ``hedge_after_ms``, the same read is also sent to the next healthy
  endpoint; first valid reply wins (``pst_lookup_hedges_total``). Safe
  because lookups are idempotent reads of an immutable dataset;
* **lease awareness** — the client drains the servers' heartbeat PUB
  stream between requests; endpoints whose last heartbeat reported
  ``draining``/``drained``, or that went a full lease silent after
  heartbeating, sort to the back of the candidate list (the PR-10
  zero-rpc liveness rule);
* **partition routing** — once a
  :class:`~petastorm_tpu.serving.placement.PartitionMap` is known
  (constructor, heartbeat stream, or :meth:`refresh_partition_map`),
  every key routes key -> partition -> ranked replicas: a partition's
  own replicas head the candidate list in placement order (healthiest
  first), every other fleet endpoint forms the fallback tail — so
  failover past a dead replica set is still possible (all replicas
  serve the same immutable dataset) and a read is never silently
  dropped;
* **scatter-gather** — multi-key lookups group keys by partition and
  fan out one request per partition on short-lived ``pst-fleet-scatter``
  threads; predicate queries scatter each partition its disjoint
  modular share of the row groups and merge replies back into
  single-engine dataset order, applying ``limit`` across partitions
  (per-partition limits are a superset of each partition's contribution
  to the global cut). A partition whose replicas all fail raises the
  typed error — **partial results are never returned silently**;
* **bounded endpoint state** — heartbeat and server-id entries for
  endpoints that left the candidate set (fleet churn) expire one lease
  window after their last update, so a long-lived client watching a
  churning fleet holds O(live fleet) state, not O(history).
"""

import logging
import pickle
import threading
import time
import uuid

logger = logging.getLogger(__name__)

_REFUSAL_REASONS = ('draining', 'drained', 'overloaded')


class LookupClient(object):
    """Point reads against one or more :class:`LookupServer` endpoints
    serving the SAME dataset (replicas — hedging and failover assume any
    endpoint can answer any read).

    :param endpoints: rpc endpoint list (``tcp://host:port``).
    :param control_endpoints: matching heartbeat endpoints (optional;
        enables lease-aware endpoint ordering).
    :param timeout_ms: whole-request deadline (scatter-gather runs its
        per-partition requests concurrently, each under this same
        deadline — the per-partition deadline).
    :param hedge_after_ms: silence before the next endpoint is hedged.
    :param consumer_id: admission identity (default: a fresh uuid).
    :param partition_map: optional
        :class:`~petastorm_tpu.serving.placement.PartitionMap` (or its
        wire dict) to route by immediately; newer versions learned from
        heartbeats or ``pmap`` replies supersede it.
    """

    def __init__(self, endpoints, control_endpoints=None, timeout_ms=5000,
                 hedge_after_ms=300, consumer_id=None,
                 breaker_threshold=3, breaker_reset_s=15.0,
                 partition_map=None):
        import zmq

        from petastorm_tpu.retry import BreakerSet
        self._zmq = zmq
        self._context = zmq.Context.instance()
        self._endpoints = list(endpoints)
        if not self._endpoints:
            raise ValueError('LookupClient needs at least one endpoint')
        self._timeout_ms = int(timeout_ms)
        self._hedge_after_ms = int(hedge_after_ms)
        self._consumer_id = consumer_id or 'lookup-{}'.format(
            uuid.uuid4().hex[:12])
        self._lock = threading.Lock()
        self._breakers = BreakerSet(failure_threshold=breaker_threshold,
                                    reset_timeout_s=breaker_reset_s)
        # Persistent per-endpoint REQ sockets (the "lazy pirate"
        # optimization): a fresh TCP + ZMTP handshake costs several ms —
        # more than a warm point read itself — so sockets that completed
        # a clean round trip are cached and reused. A socket whose
        # request timed out, got a garbled reply, or was abandoned by a
        # winning hedge is CLOSED instead (a REQ state machine cannot
        # take a new request while one is outstanding).
        self._socks = {}
        from petastorm_tpu import metrics as metrics_mod
        self._m_hedges = metrics_mod.counter(
            'pst_lookup_hedges_total',
            'Lookup requests where a hedge was sent to another endpoint')
        self._m_map_updates = metrics_mod.counter(
            'pst_partition_map_updates_total',
            'Partition-map versions this process\'s lookup clients '
            'adopted')
        self._m_part_retries = metrics_mod.counter(
            'pst_partition_retries_total',
            'Partition-routed reads retried on a sibling replica '
            '(failover past the ranked head, or a hedge that fired)')
        self.hedges = 0
        self.scatters = 0
        self.partition_retries = 0
        self._closed = False
        # Lease watching: SUB to every control endpoint; heartbeats drain
        # non-blocking at each request. Keyed by the DIALED rpc endpoint:
        # {endpoint: (state, lease_s, at)}. A heartbeat advertises the
        # server's own view of its rpc address, which can differ from the
        # address the client dialed (wildcard binds resolve to a
        # hostname; the operator dialed an IP) — `_server_ids` maps the
        # heartbeat's server_id to the dialed endpoint, learned from rpc
        # replies (every reply carries `server_id`), so the ranking
        # always looks heartbeats up under the key it ranks by.
        self._hb = {}
        self._server_ids = {}        # server_id -> (endpoint, noted_at)
        self._sub = None
        self._sub_endpoints = set()
        if control_endpoints:
            self._ensure_sub()
            for ctrl_ep in control_endpoints:
                self._sub.connect(ctrl_ep)
                self._sub_endpoints.add(ctrl_ep)
        self._pmap = None
        if partition_map is not None:
            self._adopt_pmap(partition_map)

    # -- endpoint health ---------------------------------------------------

    def _breaker(self, endpoint):
        return self._breakers.get(endpoint)

    def _socket_for(self, endpoint):
        """A ready REQ socket for ``endpoint`` — the cached one (idle,
        clean) or a fresh connect."""
        zmq = self._zmq
        with self._lock:
            sock = self._socks.pop(endpoint, None)
        if sock is None:
            sock = self._context.socket(zmq.REQ)
            sock.setsockopt(zmq.LINGER, 0)
            sock.connect(endpoint)
        return sock

    def _release_socket(self, endpoint, sock):
        """Return a socket whose round trip completed cleanly."""
        with self._lock:
            if self._closed or endpoint in self._socks:
                pass
            else:
                self._socks[endpoint] = sock
                return
        sock.close(linger=0)

    def breaker_state(self, endpoint):
        return self._breaker(endpoint).state

    def _note_server_id(self, endpoint, reply):
        """Bind a reply's server identity to the endpoint we dialed —
        what lets heartbeats (which advertise the server's OWN address
        view) resolve back to the dialed key the ranking uses."""
        sid = reply.get('server_id') if isinstance(reply, dict) else None
        if sid is not None:
            self._server_ids[sid] = (endpoint, time.monotonic())

    def _ensure_sub(self):
        if self._sub is None:
            self._sub = self._context.socket(self._zmq.SUB)
            self._sub.setsockopt(self._zmq.SUBSCRIBE, b'')

    # -- partition map -----------------------------------------------------

    @property
    def partition_map(self):
        return self._pmap

    def _adopt_pmap(self, pmap):
        """Converge on a newer map version; subscribing to any member
        control endpoints not yet watched (a joining replica's
        heartbeats start mattering the moment the map names it)."""
        from petastorm_tpu.serving.placement import PartitionMap
        if not isinstance(pmap, PartitionMap):
            pmap = PartitionMap.from_wire(pmap)
        if self._pmap is not None and pmap.version <= self._pmap.version:
            return False
        self._pmap = pmap
        self._m_map_updates.inc()
        ctrl_eps = [info.get('control')
                    for info in pmap.members.values() if info.get('control')]
        if ctrl_eps:
            self._ensure_sub()
            for ctrl_ep in ctrl_eps:
                if ctrl_ep not in self._sub_endpoints:
                    self._sub.connect(ctrl_ep)
                    self._sub_endpoints.add(ctrl_ep)
        return True

    def refresh_partition_map(self):
        """Pull the fleet's current map over the ``pmap`` verb (the
        deterministic bootstrap — heartbeats converge eventually, this
        converges now). Returns the held map (possibly None when no
        server carries one)."""
        reply = self._request({'cmd': 'pmap'}, hedge=False)
        wire = reply.get('pmap') if isinstance(reply, dict) else None
        if wire is not None:
            self._adopt_pmap(wire)
        return self._pmap

    def _endpoints_all(self):
        """Declared endpoints plus every map member's rpc endpoint —
        the live candidate set."""
        endpoints = list(self._endpoints)
        if self._pmap is not None:
            for info in self._pmap.members.values():
                rpc = info.get('rpc')
                if rpc and rpc not in endpoints:
                    endpoints.append(rpc)
        return endpoints

    def _prune_endpoint_state(self):
        """Bound `_hb`/`_server_ids` against fleet churn: an endpoint no
        longer in the candidate set keeps its entries for one lease
        window (it may be mid-rejoin), then they expire."""
        now = time.monotonic()
        live = set(self._endpoints_all())
        for endpoint, (_, lease_s, at) in list(self._hb.items()):
            if endpoint not in live and now - at > lease_s:
                del self._hb[endpoint]
        for sid, (endpoint, at) in list(self._server_ids.items()):
            if endpoint in live:
                continue
            lease_s = self._hb.get(endpoint, (None, 10.0, 0.0))[1]
            if now - at > lease_s:
                del self._server_ids[sid]

    def _drain_heartbeats(self):
        """Non-blocking: fold every queued lease heartbeat into the
        per-endpoint view — and adopt any newer partition map riding in
        a heartbeat body. (SUB sockets are owned by the caller thread —
        the client is documented single-caller like RemoteReader; the
        scatter worker threads never touch the SUB.)"""
        if self._sub is None:
            self._prune_endpoint_state()
            return
        from petastorm_tpu.serving.server import CTRL_HB
        import json
        zmq = self._zmq
        while True:
            try:
                raw = self._sub.recv(zmq.NOBLOCK)
            except zmq.Again:
                break
            except zmq.ZMQError:
                break
            if not raw.startswith(CTRL_HB):
                continue
            try:
                body = json.loads(raw[len(CTRL_HB):].decode('utf-8'))
            except ValueError:
                continue
            pmap_wire = body.get('pmap')
            if pmap_wire is not None:
                try:
                    self._adopt_pmap(pmap_wire)
                except ValueError:
                    logger.warning('ignoring malformed partition map in '
                                   'heartbeat from %r',
                                   body.get('server_id'))
            # Resolve the heartbeat to the DIALED endpoint the ranking
            # keys by: via the server-id binding learned from replies,
            # else the advertised rpc address when it happens to be one
            # we dial (declared or learned from the map).
            bound = self._server_ids.get(body.get('server_id'))
            endpoint = bound[0] if bound is not None else None
            if endpoint is None:
                rpc = body.get('rpc')
                endpoint = rpc if rpc in self._endpoints_all() else None
            if endpoint is not None:
                self._hb[endpoint] = (body.get('state'),
                                      float(body.get('lease_s') or 10.0),
                                      time.monotonic())
        self._prune_endpoint_state()

    def _candidates(self, partition=None):
        """Endpoints to try, healthiest first: breaker-open endpoints
        last, then lease-draining/expired ones, then everything else in
        declared order. With a routed ``partition``, that partition's
        replicas (placement order, health-sorted stably) head the list
        and every other fleet endpoint forms the failover tail — any
        replica can serve any key, so a partition whose owners all died
        still gets answered rather than silently dropped."""
        from petastorm_tpu.retry import CircuitBreaker
        self._drain_heartbeats()
        now = time.monotonic()

        def rank(endpoint):
            score = 0
            if self._breaker(endpoint).state == CircuitBreaker.OPEN:
                score += 4
            hb = self._hb.get(endpoint)
            if hb is not None:
                state, lease_s, at = hb
                if state in ('draining', 'drained'):
                    score += 2
                if now - at > lease_s:
                    # Heartbeats stopped for a whole lease: presumed dead
                    # without paying an rpc timeout to find out.
                    score += 3
            return score
        ranked = sorted(self._endpoints_all(), key=rank)
        if partition is None or self._pmap is None:
            return ranked
        head = [endpoint
                for endpoint in self._pmap.endpoints(partition)
                if endpoint in set(ranked)]
        head.sort(key=rank)   # stable: replica rank breaks health ties
        return head + [e for e in ranked if e not in set(head)]

    # -- the request core --------------------------------------------------

    def _request(self, request, hedge=True, candidates=None,
                 partition=None):
        """One logical request with failover + hedging. Returns the first
        non-refusal reply; raises ``ServerOverloaded`` when every
        endpoint refused, ``RpcUnanswered`` when nobody answered.
        ``candidates`` overrides the endpoint ordering (scatter workers
        get theirs precomputed on the caller thread — they must not
        touch the single-owner SUB socket); ``partition`` marks a
        partition-routed read so sibling-replica retries are counted."""
        from petastorm_tpu.data_service import RpcUnanswered
        from petastorm_tpu.errors import ServerOverloaded
        zmq = self._zmq
        if self._closed:
            raise RuntimeError('LookupClient is closed')
        request = dict(request, consumer=self._consumer_id)
        payload = pickle.dumps(request, protocol=5)
        if candidates is None:
            candidates = self._candidates(partition=partition)
        deadline = time.monotonic() + self._timeout_ms / 1000.0
        poller = zmq.Poller()
        socks = {}
        pending = list(candidates)
        refusal = None
        sent = 0
        try:
            while True:
                now = time.monotonic()
                if now >= deadline:
                    break
                if pending and (hedge or not socks):
                    endpoint = pending.pop(0)
                    if not self._breaker(endpoint).allow():
                        # Open circuit: skip instantly (the allow() call
                        # is also what grants the half-open heal probe
                        # once the reset timeout elapses).
                        continue
                    # A genuine hedge = sending while another endpoint
                    # still has this request outstanding. A sequential
                    # failover (prior endpoint already answered a typed
                    # refusal, nothing in flight) is NOT one — counting
                    # it would inflate the hedge SLO metric on every
                    # read of a rolling drain.
                    is_hedge = bool(socks)
                    sock = self._socket_for(endpoint)
                    sock.send(payload)
                    sent += 1
                    if partition is not None and sent > 1:
                        # Any send past the ranked head — refusal
                        # failover or a hedge — is a sibling-replica
                        # retry of this partition's read.
                        self._m_part_retries.inc()
                        with self._lock:
                            self.partition_retries += 1
                    poller.register(sock, zmq.POLLIN)
                    socks[sock] = endpoint
                    if is_hedge:
                        self._m_hedges.inc()
                        with self._lock:
                            self.hedges += 1
                elif not socks:
                    break            # everyone answered a refusal/error
                wait_ms = (deadline - now) * 1000.0
                if pending and hedge:
                    wait_ms = min(wait_ms, self._hedge_after_ms)
                for sock, _ in poller.poll(max(int(wait_ms), 1)):
                    endpoint = socks[sock]
                    try:
                        reply = pickle.loads(sock.recv())
                    except Exception:  # noqa: BLE001 - garbled: next hedge
                        self._breaker(endpoint).record_failure()
                        poller.unregister(sock)
                        sock.close(linger=0)
                        del socks[sock]
                        continue
                    self._breaker(endpoint).record_success()
                    self._note_server_id(endpoint, reply)
                    poller.unregister(sock)
                    del socks[sock]
                    self._release_socket(endpoint, sock)
                    if isinstance(reply, dict) and 'refused' in reply:
                        # Typed admission refusal: the server is healthy
                        # but not taking us — remember why, fail over NOW
                        # (don't wait out the hedge delay).
                        refusal = (endpoint, reply)
                        self._hb[endpoint] = (
                            reply.get('state') or reply.get('refused'),
                            self._hb.get(endpoint, (None, 10.0, 0))[1],
                            time.monotonic())
                        continue
                    if isinstance(reply, dict) and 'error' in reply:
                        raise RuntimeError(
                            'lookup rpc failed on {}: {}'.format(
                                endpoint, reply['error']))
                    return reply
                if not socks and not pending:
                    break
            for endpoint in socks.values():
                # Sat on the request for the whole budget: breaker-visible.
                self._breaker(endpoint).record_failure()
            if refusal is not None:
                endpoint, reply = refusal
                raise ServerOverloaded(
                    'every lookup endpoint refused this consumer '
                    '(last: {} said {!r})'.format(endpoint,
                                                  reply.get('refused')),
                    endpoint=endpoint,
                    reason=reply.get('reason') or reply.get('refused'))
            raise RpcUnanswered(
                'no lookup endpoint answered within {}ms (tried {})'.format(
                    self._timeout_ms, candidates))
        finally:
            for sock in socks:
                sock.close(linger=0)

    def _scatter(self, jobs):
        """Fan ``[(partition, request)]`` out, one request per
        partition, each under the full request deadline (the
        per-partition deadline — partitions run concurrently). Candidate
        lists are computed HERE, on the calling thread (the SUB socket
        is single-owner); the short-lived scatter workers only run
        ``_request``, whose shared state (breakers, socket cache,
        counters) is lock-guarded. Partial failure is loud: when any
        partition exhausts its replicas AND the failover tail, the first
        error is raised — a scatter never returns a silently truncated
        result set."""
        plans = [(pid, request, self._candidates(partition=pid))
                 for pid, request in jobs]
        with self._lock:
            self.scatters += 1
        if len(plans) == 1:
            pid, request, candidates = plans[0]
            return {pid: self._request(request, candidates=candidates,
                                       partition=pid)}
        replies, errors = {}, {}

        def serve_one(pid, request, candidates):
            try:
                replies[pid] = self._request(request,
                                             candidates=candidates,
                                             partition=pid)
            except BaseException as e:  # noqa: BLE001 - re-raised below
                errors[pid] = e

        threads = [threading.Thread(
            target=serve_one, args=plan, daemon=True,
            name='pst-fleet-scatter-{}'.format(plan[0]))
            for plan in plans]
        for thread in threads:
            thread.start()
        for thread in threads:
            thread.join()
        if errors:
            raise errors[sorted(errors)[0]]
        return replies

    # -- public verbs ------------------------------------------------------

    def lookup(self, keys, fields=None):
        """Point reads: per key, the list of matching rows
        (``{field: numpy value}`` dicts; empty list = absent key).
        With a partition map, keys group by partition and scatter to
        each partition's ranked replicas; duplicate keys in one request
        are fetched once and answered at every position."""
        keys = list(keys)
        fields = list(fields) if fields else None
        pmap = self._pmap
        if pmap is None or not keys:
            reply = self._request({'cmd': 'lookup', 'keys': keys,
                                   'fields': fields})
            return reply['rows']
        groups = {}        # partition -> unique keys, first-seen order
        for key in keys:
            bucket = groups.setdefault(pmap.partition_of_key(key), [])
            if not any(str(key) == str(seen) for seen in bucket):
                bucket.append(key)
        replies = self._scatter(
            [(pid, {'cmd': 'lookup', 'keys': bucket, 'fields': fields,
                    'partition': pid})
             for pid, bucket in sorted(groups.items())])
        rows_by_key = {}
        for pid, bucket in groups.items():
            for key, rows in zip(bucket, replies[pid]['rows']):
                rows_by_key[str(key)] = rows
        return [rows_by_key[str(key)] for key in keys]

    def lookup_one(self, key, fields=None):
        """The single row for ``key``, or ``None`` when absent; raises
        on a key matching several rows (use :meth:`lookup`)."""
        rows = self.lookup([key], fields=fields)[0]
        if len(rows) > 1:
            raise ValueError('key {!r} matches {} rows'.format(
                key, len(rows)))
        return rows[0] if rows else None

    def query(self, predicate, selector=None, limit=None, fields=None):
        """Server-side predicate scan (``predicates.in_lambda`` etc.,
        with optional ``selectors`` row-group pruning). The predicate and
        selector must be picklable — module-level functions, not bare
        lambdas.

        With a partition map, the scan scatters: each partition serves
        its disjoint modular share of the row groups (tagged with row
        locations), and the gather merges every partial back into
        single-engine dataset order before applying ``limit`` ACROSS
        partitions — each partition's own ``limit``-cut is a superset of
        its contribution to the global cut, so the merge is exact, and
        an empty partition simply contributes nothing."""
        fields = list(fields) if fields else None
        base = {'cmd': 'query', 'predicate': predicate,
                'selector': selector, 'limit': limit, 'fields': fields}
        pmap = self._pmap
        if pmap is None:
            return self._request(base)['rows']
        replies = self._scatter(
            [(pid, dict(base, partition=pid,
                        pieces_mod=[pid, pmap.n_partitions],
                        with_locations=True))
             for pid in range(pmap.n_partitions)])
        tagged = []
        for pid in sorted(replies):
            tagged.extend(replies[pid]['rows'])
        tagged.sort(key=lambda item: (item['piece'], item['offset']))
        if limit is not None:
            tagged = tagged[:max(int(limit), 0)]
        return [item['row'] for item in tagged]

    def attach(self):
        """Explicit admission handshake (reads attach implicitly)."""
        return self._request({'cmd': 'attach'}, hedge=False)

    def stats(self):
        return self._request({'cmd': 'stats'})

    def schema(self):
        return self._request({'cmd': 'schema'})['schema']

    def routing_table(self):
        """The client's current fleet view, JSON-safe: map version,
        per-partition replica ranking with each replica's breaker state
        and lease freshness. Empty partitions dict when no map is
        known."""
        self._drain_heartbeats()
        pmap = self._pmap
        if pmap is None:
            return {'version': None, 'n_partitions': None,
                    'replication': None, 'members': {}, 'partitions': {}}
        now = time.monotonic()
        partitions = {}
        for pid in range(pmap.n_partitions):
            entries = []
            for rank, name in enumerate(pmap.replicas(pid)):
                endpoint = (pmap.members.get(name) or {}).get('rpc')
                hb = self._hb.get(endpoint)
                entries.append({
                    'rank': rank, 'name': name, 'endpoint': endpoint,
                    'breaker': self._breaker(endpoint).state
                    if endpoint else None,
                    'hb_state': hb[0] if hb else None,
                    'lease_fresh': (now - hb[2] <= hb[1])
                    if hb else None})
            partitions[str(pid)] = entries
        return {'version': pmap.version,
                'n_partitions': pmap.n_partitions,
                'replication': pmap.replication,
                'members': {name: dict(info)
                            for name, info in pmap.members.items()},
                'partitions': partitions}

    def scatter_stats(self):
        """Counters for the scatter-gather path of THIS client."""
        with self._lock:
            return {'scatters': self.scatters,
                    'partition_retries': self.partition_retries,
                    'hedges': self.hedges}

    def fleet_metrics(self, timeout_ms=2000):
        """Per-server metrics snapshots + the summed fleet aggregate —
        the same shape as ``RemoteReader.fleet_metrics()`` (deduped on
        the process registry id so co-located servers fold once)."""
        from petastorm_tpu import metrics as metrics_mod
        return metrics_mod.scrape_fleet_metrics(
            self._endpoints_all(),
            lambda ep: self._request_one(ep, {'cmd': 'metrics'},
                                         timeout_ms),
            server_value='reply', unreachable_detail=True)

    def _request_one(self, endpoint, request, timeout_ms):
        """Single-endpoint rpc (no failover) under the breaker."""
        from petastorm_tpu.data_service import RpcUnanswered
        zmq = self._zmq
        breaker = self._breaker(endpoint)
        if not breaker.allow():
            raise RpcUnanswered('{} circuit open'.format(endpoint))
        sock = self._socket_for(endpoint)
        clean = False
        try:
            sock.send(pickle.dumps(dict(request,
                                        consumer=self._consumer_id),
                                   protocol=5))
            if not sock.poll(timeout_ms):
                breaker.record_failure()
                raise RpcUnanswered('{} gave no reply within {}ms'.format(
                    endpoint, timeout_ms))
            reply = pickle.loads(sock.recv())
            breaker.record_success()
            self._note_server_id(endpoint, reply)
            clean = True
            return reply
        finally:
            if clean:
                self._release_socket(endpoint, sock)
            else:
                sock.close(linger=0)

    def close(self):
        self._closed = True
        with self._lock:
            cached, self._socks = dict(self._socks), {}
        for sock in cached.values():
            sock.close(linger=0)
        if self._sub is not None:
            self._sub.close(linger=0)
            self._sub = None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
