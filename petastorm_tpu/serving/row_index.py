"""Row-level key index: load and resolve ``value -> (row-group, offset)``.

The build side lives in the existing indexer pass
(``etl.rowgroup_indexers.SingleFieldRowIndexer`` run through
``etl.rowgroup_indexing.build_rowgroup_index``); this module is the read
side: pick the row-level payload out of the stored index blob
(``get_row_group_indexes``) and answer point resolutions in O(1).
"""

import logging

logger = logging.getLogger(__name__)

#: Payload ``type`` tag written by ``SingleFieldRowIndexer``.
ROW_INDEX_TYPE = 'single_field_rows'


class RowLocationIndex(object):
    """One loaded row-level index: key value -> row locations.

    :param name: the index name it was stored under.
    :param payload: the stored JSON payload
        (``{'type', 'field', 'values'}``).
    """

    def __init__(self, name, payload):
        if payload.get('type') != ROW_INDEX_TYPE:
            raise ValueError(
                'index {!r} is type {!r}, not a row-level index (build it '
                'with SingleFieldRowIndexer)'.format(
                    name, payload.get('type')))
        self.name = name
        self.field = payload['field']
        # JSON round-trips pairs as lists; normalize to tuples once so
        # lookups hand out hashable, immutable locations.
        self._values = {value: [tuple(loc) for loc in locations]
                        for value, locations in payload['values'].items()}

    @classmethod
    def load(cls, dataset_url_or_store, index_name=None,
             storage_options=None):
        """Load the row-level index from a dataset's stored index blob.

        ``index_name=None`` auto-selects when exactly one row-level index
        exists; several (or none) raise with the available names so the
        caller can disambiguate.
        """
        from petastorm_tpu.etl.rowgroup_indexing import get_row_group_indexes
        payload = get_row_group_indexes(dataset_url_or_store,
                                        storage_options=storage_options)
        if index_name is not None:
            if index_name not in payload:
                raise ValueError('Index {!r} not found; available: {}'.format(
                    index_name, sorted(payload)))
            return cls(index_name, payload[index_name])
        row_level = {name: p for name, p in payload.items()
                     if p.get('type') == ROW_INDEX_TYPE}
        if len(row_level) != 1:
            raise ValueError(
                'expected exactly one row-level index, found {} (stored '
                'indexes: {}); pass index_name= or build one with '
                'SingleFieldRowIndexer'.format(
                    sorted(row_level) or 'none',
                    {name: p.get('type') for name, p in payload.items()}))
        name, p = next(iter(row_level.items()))
        return cls(name, p)

    def locations(self, value):
        """``[(piece_index, row_offset)]`` for ``value`` (dataset order);
        empty when the key is absent. Values are matched by their string
        form — the JSON payload stores string keys, same as the
        row-group-level indexes."""
        return list(self._values.get(str(value), ()))

    def __contains__(self, value):
        return str(value) in self._values

    def __len__(self):
        return len(self._values)

    def keys(self):
        return self._values.keys()
