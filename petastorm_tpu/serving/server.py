"""LookupServer: the lookup tier's ZMQ service plane.

``lookup``/``query`` verbs over a ROUTER socket — served inline on one
thread by default (the lowest-latency path: a warm point read is one
engine call between two socket events), or fanned out to a pool of
inproc REP workers (``rpc_workers > 1``: concurrent heavy queries
coalesce inside the shared
:class:`~petastorm_tpu.serving.engine.LookupEngine`) — run under the
PR-10 control-plane discipline the data service proved out:

* **lease heartbeats** on a PUB socket (``PST_LHB`` + JSON: server id,
  lease seconds, drain state, rpc endpoint) every third of the lease —
  a client that saw one heartbeat then silence for a full lease routes
  around the server with zero rpc round trips;
* **admission control**: a consumer ledger with 3-lease expiry; past
  ``max_consumers`` (or under the memory governor's *shed* rung) new
  consumers get the TYPED refusal (``{'refused': 'overloaded', ...}``)
  instead of silently degrading everyone;
* **graceful drain**: :meth:`drain` (or the ``drain`` verb) stops
  admission, refuses further reads with ``{'refused': 'draining'}``,
  lets in-flight requests complete, and reports ``drained`` — clients
  fail over on the typed reply;
* **SLO observability**: ``pst_lookup_requests_total{verb,outcome}``,
  ``pst_lookup_latency_seconds{verb}`` (the shared log-spaced buckets,
  so fleet histograms merge bucket-for-bucket),
  ``pst_lookup_cache_hits_total{tier}`` (engine-side), all in the
  process metrics registry — scraped over the ``metrics`` verb, by the
  HTTP exporter, and dumped by the flight recorder on escalation;
* chaos surface: the existing ``server-slow`` (delay before the reply)
  and ``rpc-blackhole`` (swallow the request, reset the REP state
  machine) fault sites fire inside the worker loop, so the client's
  circuit breaker and hedging are drill-testable like the data plane's;
  the fleet adds ``partition-lost`` (swallow one partition's requests on
  every replica at once) and ``hb-flap`` (suppress individual lease
  heartbeats);
* **fleet membership**: servers carrying a
  :class:`~petastorm_tpu.serving.placement.PartitionMap` publish it in
  every heartbeat and answer the ``pmap``/``pmap_update`` verbs, so
  clients and peers converge on the highest version with no
  coordinator. :meth:`drain` recomputes placement without the draining
  member and pushes it to the survivors (live reassignment of the
  drained key range); :meth:`join_fleet` adds this server to a peer's
  map and **warm-joins** — pre-filling its ``DecodedChunkStore`` from
  the peer's chunk files over the ``chunk`` verb (byte-validated, same
  ``tensor_chunk_key``) so its first reads hit the chunk-store tier
  instead of cold-decoding.
"""

import json
import logging
import pickle
import threading
import time
import uuid

from petastorm_tpu.fleet import control_plane

logger = logging.getLogger(__name__)

#: Control-plane heartbeat prefix (PUB broadcasts, JSON body) — the
#: shared control plane's JSON dialect; the fleet registry parses both
#: this and the data plane's binary ``PST_HB``.
CTRL_HB = control_plane.CTRL_HB_JSON

DEFAULT_LEASE_S = control_plane.DEFAULT_LEASE_S


def _one_shot(context, endpoint, request, timeout_ms):
    """Fleet-internal rpc: one REQ round trip, fresh socket, hard
    deadline. Used where a server talks to a PEER (map push, warm-join
    chunk pulls) — peers are not clients, so none of the client-side
    breaker/hedge state applies. Raises ``RpcUnanswered`` on silence."""
    import zmq

    from petastorm_tpu.data_service import RpcUnanswered
    sock = context.socket(zmq.REQ)
    sock.setsockopt(zmq.LINGER, 0)
    try:
        sock.connect(endpoint)
        sock.send(pickle.dumps(request, protocol=5))
        if not sock.poll(int(timeout_ms)):
            raise RpcUnanswered('{} gave no reply within {}ms'.format(
                endpoint, timeout_ms))
        return pickle.loads(sock.recv())
    finally:
        sock.close(linger=0)


class LookupServer(object):
    """Serve a :class:`~petastorm_tpu.serving.engine.LookupEngine` over zmq.

    :param engine: the shared local request path (thread-safe).
    :param bind: rpc endpoint, e.g. ``'tcp://127.0.0.1:*'``. Clients
        dial :attr:`rpc_endpoint`.
    :param control_bind: lease-heartbeat PUB endpoint (default: rpc
        port + 1 for tcp binds).
    :param lease_s: lease duration (default ``PETASTORM_TPU_LEASE_S``
        or 10); heartbeats go out every third of it.
    :param max_consumers: admission capacity; ``None`` = unlimited.
    :param rpc_workers: concurrent request handlers. The default (1)
        serves the ROUTER inline on one thread — the LOWEST-latency
        configuration (no inproc hop, no extra thread handoff per
        request; a warm point read is one engine call between two socket
        events). Raise it when many clients run heavy ``query`` scans
        concurrently — point reads then ride the engine's coalescing.
    :param gc_freeze: on :meth:`start`, freeze the baseline object graph
        out of the cyclic collector (``gc.freeze()``). A gen-2 pass over
        a big warm process pauses every thread ~10ms — the exact tail
        the warm-read SLO forbids — while the serving path's own garbage
        is acyclic and dies by refcount. The collector stays ENABLED;
        only startup state stops being re-walked.
    """

    def __init__(self, engine, bind, control_bind=None, lease_s=None,
                 max_consumers=None, rpc_workers=1, gc_freeze=True,
                 server_name=None, job_id=None):
        import zmq

        from petastorm_tpu import membudget
        from petastorm_tpu import metrics as metrics_mod
        from petastorm_tpu.data_service import (_connectable,
                                                _next_port_endpoint)

        self._engine = engine
        self._zmq = zmq
        self._context = zmq.Context.instance()
        self._server_id = uuid.uuid4().hex
        #: Fleet identity: the name placement assigns partitions to.
        #: Operator-chosen for durable fleets; defaults to a fresh one.
        self.server_name = server_name or 'ls-{}'.format(
            self._server_id[:8])
        self._pmap = None
        self._lease_s = control_plane.resolve_lease_s(lease_s)
        self._max_consumers = (None if max_consumers is None
                               else int(max_consumers))
        # Fleet-registry announce: heartbeats carry job + capacity when
        # this server is a declared member of a preprocessing fleet.
        self._job_id = control_plane.resolve_job_id(job_id)
        self._rpc_workers = max(1, int(rpc_workers))
        self._gc_freeze = bool(gc_freeze)
        self._gc_frozen = False

        self._frontend = self._context.socket(zmq.ROUTER)
        self._ctrl_sock = None
        self._backend = None
        try:
            self._frontend.bind(bind)
            actual = self._frontend.getsockopt(zmq.LAST_ENDPOINT).decode()
            ctrl_endpoint = (control_bind if control_bind is not None
                             else _next_port_endpoint(actual))
            self._ctrl_sock = self._context.socket(zmq.PUB)
            self._ctrl_sock.bind(ctrl_endpoint)
            if self._rpc_workers > 1:
                # Worker fan-out: one DEALER bound inproc; each worker
                # thread connects a REP. inproc requires bind-before-
                # connect, so the backend binds here, before any worker
                # thread starts. (rpc_workers=1 serves the ROUTER inline
                # — no backend at all.)
                self._backend = self._context.socket(zmq.DEALER)
                self._inproc = 'inproc://pst-lookup-{}'.format(
                    self._server_id)
                self._backend.bind(self._inproc)
        except Exception:
            for sock in (self._frontend, self._ctrl_sock, self._backend):
                if sock is not None:
                    sock.close(linger=0)
            raise
        self.rpc_endpoint = _connectable(actual)
        self.control_endpoint = _connectable(
            self._ctrl_sock.getsockopt(zmq.LAST_ENDPOINT).decode())

        self._m_requests = metrics_mod.counter(
            'pst_lookup_requests_total',
            'Lookup-tier rpc requests, by verb and outcome',
            labelnames=('verb', 'outcome'))
        self._m_latency = metrics_mod.histogram(
            'pst_lookup_latency_seconds',
            'Lookup-tier request service latency, by verb',
            labelnames=('verb',))
        self._m_rejected = metrics_mod.counter(
            'pst_consumers_rejected_total',
            'Consumer attach requests a data-service server refused',
            labelnames=('reason',))
        self._m_map_version = metrics_mod.gauge(
            'pst_partition_map_version',
            'Partition-map version this actor currently holds',
            labelnames=('actor',))
        self._m_reassign = metrics_mod.counter(
            'pst_partition_reassignments_total',
            'Partition-map recomputations this server initiated, '
            'by reason',
            labelnames=('reason',))

        # Shared control plane (petastorm_tpu.fleet.control_plane): the
        # admission ledger's lock doubles as this server's one big lock
        # (it guarded consumers + inflight + pmap before the extraction;
        # splitting them would change admission atomicity).
        self._admission = control_plane.AdmissionLedger(self._lease_s)
        self._lock = self._admission.lock
        self._drain_state = control_plane.DrainState()
        self._draining = self._drain_state.draining
        self._drained = self._drain_state.drained
        self._stop = threading.Event()
        self._inflight = 0             # requests inside worker handlers
        self._response_bytes = 0       # serialized replies not yet sent
        self.requests_served = 0

        # Memory-governor wiring: response bytes in flight are accounted,
        # and the *shed* rung flips this server to typed memory-pressure
        # refusals for new consumers (existing ones keep reading — load
        # shedding must not break clients mid-conversation).
        self._mem_shed = False
        self._mem_handle = membudget.register_pool(
            'lookup-responses', self._response_nbytes,
            shed_fn=self._set_mem_shed)

        self._threads = []

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        if self._threads:
            raise RuntimeError('server already started')
        if self._gc_freeze:
            import gc
            gc.collect()
            gc.freeze()
            self._gc_frozen = True
        rpc_target = (self._serve_inline if self._backend is None
                      else self._proxy_loop)
        self._threads = [
            threading.Thread(target=rpc_target, daemon=True,
                             name='pst-lookup-rpc'),
            threading.Thread(target=self._control_loop, daemon=True,
                             name='pst-lookup-lease'),
        ]
        if self._backend is not None:
            self._threads += [
                threading.Thread(target=self._worker_loop, args=(i,),
                                 daemon=True,
                                 name='pst-lookup-worker-{}'.format(i))
                for i in range(self._rpc_workers)]
        for thread in self._threads:
            thread.start()
        return self

    def stop(self):
        if self._gc_frozen:
            # Unpin the start()-time heap snapshot: a process that keeps
            # running after the server stops (a trainer serving between
            # epochs, a test session) must get cyclic collection of that
            # state back, or stop/start cycles grow memory monotonically.
            import gc
            gc.unfreeze()
            self._gc_frozen = False
        self._mem_handle.close()
        self._stop.set()
        joined = True
        for thread in self._threads:
            thread.join(timeout=10)
            joined = joined and not thread.is_alive()
        if joined:
            self._frontend.close(linger=0)
            if self._backend is not None:
                self._backend.close(linger=0)
            self._ctrl_sock.close(linger=0)
        else:  # pragma: no cover - requires a wedged handler
            logger.warning('lookup rpc thread still running after stop(); '
                           'leaking zmq sockets rather than closing them '
                           'from another thread')
        self._threads = []

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.stop()

    # -- drain state machine ----------------------------------------------

    @property
    def state(self):
        return self._drain_state.state()

    def drain(self, timeout_s=30.0, _inflight_floor=0):
        """Stop admitting, refuse further reads with the typed
        ``draining`` reply, wait for in-flight requests to finish, and
        report drained. Idempotent. When this server is a fleet member,
        draining FIRST reassigns its key range: placement is recomputed
        without it (version + 1), adopted locally (the remaining
        heartbeats advertise the new map) and pushed to the surviving
        peers — clients converge and route around the drain while
        in-flight requests finish. ``_inflight_floor`` is the ``drain``
        rpc handler's own request, which is in-flight by definition and
        must not wait on itself."""
        if self._drain_state.request():
            self._reassign_on_drain()
        deadline = time.monotonic() + (timeout_s
                                       if timeout_s is not None else 30.0)
        while time.monotonic() < deadline:
            with self._lock:
                if self._inflight <= _inflight_floor:
                    self._drained.set()
                    return True
            time.sleep(0.01)
        return self._drained.is_set()

    # -- fleet membership --------------------------------------------------

    @property
    def partition_map(self):
        with self._lock:
            return self._pmap

    def init_fleet(self, n_partitions=None, replication=2):
        """Bootstrap a one-member fleet: this server owns every
        partition of a fresh map (version 1). Further replicas
        :meth:`join_fleet` against it."""
        from petastorm_tpu.serving import placement
        pmap = placement.build_partition_map(
            {self.server_name: {'rpc': self.rpc_endpoint,
                                'control': self.control_endpoint}},
            n_partitions=(placement.DEFAULT_PARTITIONS
                          if n_partitions is None else n_partitions),
            replication=replication)
        self.adopt_partition_map(pmap)
        return pmap

    def adopt_partition_map(self, pmap, reason=None):
        """Converge on ``pmap`` (a :class:`PartitionMap` or its wire
        dict) when its version is newer than the held one. Returns True
        when adopted. ``reason`` marks a reassignment THIS server
        initiated (``pst_partition_reassignments_total{reason}``)."""
        from petastorm_tpu.serving.placement import PartitionMap
        if not isinstance(pmap, PartitionMap):
            pmap = PartitionMap.from_wire(pmap)
        with self._lock:
            if self._pmap is not None \
                    and pmap.version <= self._pmap.version:
                return False
            self._pmap = pmap
        self._m_map_version.labels(self.server_name).set(pmap.version)
        if reason is not None:
            self._m_reassign.labels(reason).inc()
        logger.info('lookup server %s adopted partition map v%d '
                    '(members: %s)', self.server_name, pmap.version,
                    sorted(pmap.members))
        return True

    def _push_map_to_peers(self, pmap, timeout_ms=1000):
        """Best-effort ``pmap_update`` to every other member — the
        heartbeat stream converges everyone anyway; the push just makes
        reassignment visible within an rpc round trip instead of a
        heartbeat interval."""
        pushed = 0
        for name, info in sorted(pmap.members.items()):
            if name == self.server_name or not info.get('rpc'):
                continue
            try:
                _one_shot(self._context, info['rpc'],
                          {'cmd': 'pmap_update', 'pmap': pmap.to_wire(),
                           'consumer': 'fleet-{}'.format(self.server_name)},
                          timeout_ms)
                pushed += 1
            except Exception as e:  # noqa: BLE001 - heartbeats converge it
                logger.warning('map push to %s (%s) failed: %r', name,
                               info['rpc'], e)
        return pushed

    def _reassign_on_drain(self):
        from petastorm_tpu.serving import placement
        with self._lock:
            pmap = self._pmap
        if pmap is None or self.server_name not in pmap.members \
                or len(pmap.members) <= 1:
            return
        new_map = placement.remove_member(pmap, self.server_name)
        self.adopt_partition_map(new_map, reason='drain')
        self._push_map_to_peers(new_map)

    def join_fleet(self, peer_endpoint, warm=True, timeout_ms=5000):
        """Join the fleet a peer serves: fetch its map, recompute with
        this server as a member (version + 1), adopt, push to every
        peer — and when ``warm`` (and the engine's hot tier is a
        ``DecodedChunkStore``), pre-fill the owned key range's chunks
        from the peer over the ``chunk`` verb instead of cold-decoding,
        then flush the store so the fills are durable before the first
        client read lands. Returns a summary dict."""
        from petastorm_tpu.serving import placement
        from petastorm_tpu.serving.placement import PartitionMap
        reply = _one_shot(self._context, peer_endpoint,
                          {'cmd': 'pmap',
                           'consumer': 'fleet-{}'.format(self.server_name)},
                          timeout_ms)
        wire = reply.get('pmap') if isinstance(reply, dict) else None
        if wire is None:
            raise ValueError('peer {} holds no partition map — '
                             'init_fleet() it first'.format(peer_endpoint))
        new_map = placement.add_member(PartitionMap.from_wire(wire),
                                       self.server_name,
                                       rpc=self.rpc_endpoint,
                                       control=self.control_endpoint)
        self.adopt_partition_map(new_map, reason='join')
        self._push_map_to_peers(new_map)
        summary = {'version': new_map.version,
                   'partitions': new_map.partitions_of(self.server_name),
                   'warmed_chunks': 0, 'warm_skipped': 0, 'warm_failed': 0}
        if warm:
            summary.update(self._warm_from_peer(peer_endpoint, new_map,
                                                timeout_ms))
        return summary

    def _warm_from_peer(self, peer_endpoint, pmap, timeout_ms):
        """The cache-warming protocol, joining side: for every owned
        piece not already in the hot tier, pull the peer's packed chunk
        and persist it under the shared ``tensor_chunk_key``. Blob bytes
        in flight ride the memory governor like every other pool."""
        from petastorm_tpu import membudget
        engine = self._engine
        if not callable(getattr(engine, 'warm_fill', None)) \
                or not callable(getattr(engine, 'has_cached', None)):
            return {}
        owned = pmap.partitions_of(self.server_name)
        pieces = engine.pieces_for_partitions(pmap, owned)
        warmed = skipped = failed = 0
        inflight = [0]
        with membudget.transient_pool('lookup-warm',
                                      lambda: inflight[0]):
            for piece_index in pieces:
                if engine.has_cached(piece_index):
                    skipped += 1
                    continue
                try:
                    reply = _one_shot(
                        self._context, peer_endpoint,
                        {'cmd': 'chunk', 'piece': piece_index,
                         'consumer': 'warm-{}'.format(self.server_name)},
                        timeout_ms)
                    blob = (reply.get('chunk')
                            if isinstance(reply, dict) else None)
                    if not blob:
                        raise ValueError('peer sent no chunk: {!r}'
                                         .format(reply))
                    inflight[0] = len(blob)
                    if not engine.warm_fill(piece_index, blob):
                        failed += 1
                    else:
                        warmed += 1
                except Exception as e:  # noqa: BLE001 - warm is best-effort
                    # A piece that fails to warm is NOT an error for the
                    # join: it cold-decodes on first read like any miss.
                    logger.warning('warm-join: piece %d pull from %s '
                                   'failed: %r', piece_index,
                                   peer_endpoint, e)
                    failed += 1
                finally:
                    inflight[0] = 0
        engine.flush(timeout_s=30.0)
        return {'warmed_chunks': warmed, 'warm_skipped': skipped,
                'warm_failed': failed}

    # -- membudget hooks ---------------------------------------------------

    def _response_nbytes(self):
        with self._lock:
            return self._response_bytes

    def _set_mem_shed(self, active):
        self._mem_shed = bool(active)

    # -- control plane -----------------------------------------------------

    def _control_loop(self):
        """Owns the PUB socket: lease heartbeats every ``lease_s / 3``
        plus admission-ledger pruning (3 leases without a renew frees a
        crashed consumer's slot)."""
        from petastorm_tpu import faults
        hb_interval = control_plane.heartbeat_interval(self._lease_s)
        while not self._stop.is_set():
            with self._lock:
                pmap = self._pmap
            hb = {'server_id': self._server_id,
                  'name': self.server_name,
                  'lease_s': self._lease_s,
                  'state': self.state,
                  'rpc': self.rpc_endpoint}
            if self._job_id is not None:
                # Fleet announce (same payload the data plane rides on
                # its binary heartbeat tail): membership for the
                # registry, capacity for the autoscaler.
                hb['job'] = self._job_id
                hb['capacity'] = self._max_consumers
            if pmap is not None:
                hb['pmap'] = pmap.to_wire()
            body = json.dumps(hb).encode('utf-8')
            if faults.get_injector().should_fire('hb-flap'):
                logger.warning('fault injection: hb-flap suppressing '
                               'lease heartbeat of %s', self.server_name)
            else:
                self._ctrl_sock.send(CTRL_HB + body)
            now = time.monotonic()
            with self._lock:
                for cid, _entry in self._admission.prune_locked(now):
                    logger.warning('lookup server %s: consumer %s admission '
                                   'lease expired', self.rpc_endpoint, cid)
            self._stop.wait(hb_interval)

    # -- rpc plane ---------------------------------------------------------

    def _proxy_loop(self):
        """The ROUTER <-> inproc DEALER shuttle. Poll-driven so stop()
        can interrupt it; messages route the moment they arrive."""
        zmq = self._zmq
        poller = zmq.Poller()
        poller.register(self._frontend, zmq.POLLIN)
        poller.register(self._backend, zmq.POLLIN)
        while not self._stop.is_set():
            events = dict(poller.poll(100))
            if self._frontend in events:
                self._backend.send_multipart(
                    self._frontend.recv_multipart())
            if self._backend in events:
                self._frontend.send_multipart(
                    self._backend.recv_multipart())

    def _serve_request(self, raw):
        """Decode one request, answer it through the engine under the
        admission/drain rules, time it. Returns the serialized reply, or
        ``None`` when the ``rpc-blackhole`` fault swallowed the request
        (the caller resets its transport state accordingly)."""
        from petastorm_tpu import faults
        if faults.get_injector().should_fire('rpc-blackhole'):
            logger.warning('fault injection: rpc-blackhole dropping '
                           'lookup request without reply')
            return None
        with self._lock:
            self._inflight += 1
        t0 = time.perf_counter()
        verb = 'unknown'
        try:
            try:
                request = pickle.loads(raw)
                verb = str(request.get('cmd') or 'unknown')
                partition = (request.get('partition')
                             if isinstance(request, dict) else None)
                if partition is not None and faults.get_injector() \
                        .should_fire('partition-lost',
                                     key='p{}'.format(partition)):
                    # The "whole key range went dark" drill: every
                    # replica swallows this partition's requests (the
                    # keyed selection fires identically fleet-wide), so
                    # the client must surface a typed failure for the
                    # lost range, never a truncated result.
                    logger.warning('fault injection: partition-lost '
                                   'dropping partition %s request',
                                   partition)
                    return None
                reply = self._handle(request)
            except Exception as e:  # noqa: BLE001 - reply, don't die
                logger.exception('lookup rpc failed')
                reply = {'error': repr(e)}
            outcome = ('refused' if isinstance(reply, dict)
                       and 'refused' in reply
                       else 'error' if isinstance(reply, dict)
                       and 'error' in reply else 'ok')
            self._m_requests.labels(verb, outcome).inc()
            self._m_latency.labels(verb).observe(time.perf_counter() - t0)
            faults.maybe_inject('server-slow')
            try:
                payload = pickle.dumps(reply, protocol=5)
            except Exception as e:  # noqa: BLE001 - degrade typed
                payload = pickle.dumps({'error': repr(e)}, protocol=5)
            with self._lock:
                self.requests_served += 1
            return payload
        finally:
            with self._lock:
                self._inflight -= 1

    def _serve_inline(self):
        """rpc_workers=1: handle requests ON the ROUTER thread. One
        thread, no inproc hop — each warm read is recv, engine call,
        send. A blackholed request is simply not replied to (ROUTER has
        no REP state machine to reset)."""
        while not self._stop.is_set():
            if not self._frontend.poll(100):
                continue
            frames = self._frontend.recv_multipart()
            payload = self._serve_request(frames[-1])
            if payload is None:
                continue
            with self._lock:
                self._response_bytes += len(payload)
            try:
                self._frontend.send_multipart(frames[:-1] + [payload])
            finally:
                with self._lock:
                    self._response_bytes -= len(payload)

    def _worker_loop(self, worker_id):
        """One inproc REP handler behind the proxy (rpc_workers > 1)."""
        zmq = self._zmq
        sock = self._context.socket(zmq.REP)
        sock.connect(self._inproc)
        try:
            while not self._stop.is_set():
                if not sock.poll(100):
                    continue
                raw = sock.recv()
                payload = self._serve_request(raw)
                if payload is None:
                    # Swallowed by the blackhole drill: REP requires
                    # send-before-recv — reset the state machine with a
                    # fresh socket (inproc reconnect is cheap).
                    sock.close(linger=0)
                    sock = self._context.socket(zmq.REP)
                    sock.connect(self._inproc)
                    continue
                with self._lock:
                    self._response_bytes += len(payload)
                try:
                    sock.send(payload)
                finally:
                    with self._lock:
                        self._response_bytes -= len(payload)
        finally:
            sock.close(linger=0)

    def _admit(self, request):
        """Admission/drain gate for one request; a dict = typed refusal
        reply, ``None`` = admitted (and the consumer's lease renewed)."""
        consumer = request.get('consumer') or 'anonymous'
        now = time.monotonic()
        with self._lock:
            known = self._admission.known_locked(consumer)
            state = self.state
            if state in ('draining', 'drained'):
                # Unlike the data plane (which finishes feeding admitted
                # streams), a drained lookup tier refuses EVERY read: each
                # request is standalone, and the typed reply is what makes
                # the client fail over instead of waiting out a corpse.
                self._m_rejected.labels('draining').inc()
                return control_plane.refusal(self._server_id, state, state)
            if not known:
                if self._max_consumers is not None \
                        and self._admission.count_locked() \
                        >= self._max_consumers:
                    self._m_rejected.labels('overloaded').inc()
                    return control_plane.refusal(
                        self._server_id,
                        control_plane.REFUSED_OVERLOADED, state,
                        max_consumers=self._max_consumers)
                if self._mem_shed:
                    self._m_rejected.labels('memory-pressure').inc()
                    return control_plane.refusal(
                        self._server_id,
                        control_plane.REFUSED_OVERLOADED, state,
                        reason=control_plane.REASON_MEMORY_PRESSURE)
            partition = request.get('partition')
            if self._mem_shed and partition is not None \
                    and self._pmap is not None \
                    and not self._pmap.is_primary(self.server_name,
                                                  partition):
                # Governor-shed, partition-aware: under the shed rung a
                # replica keeps serving the partitions it is PRIMARY for
                # (its working set — the reads only it can serve warmest)
                # and sheds secondary-partition traffic back to each
                # partition's own primary via the typed refusal. Known
                # consumers included: shedding must move load, not just
                # refuse strangers.
                self._m_rejected.labels('memory-pressure').inc()
                return control_plane.refusal(
                    self._server_id,
                    control_plane.REFUSED_OVERLOADED, state,
                    reason=control_plane.REASON_MEMORY_PRESSURE,
                    partition=partition)
            if known:
                entry = self._admission.renew_locked(consumer, now)
            else:
                entry = self._admission.admit_locked(consumer, now)
            # Transport tier as a session property (shared vocabulary
            # with the data plane's negotiated wire): lookup replies ride
            # the rpc plane itself, so every session is the pickle tier —
            # recorded anyway so fleet tooling reads ONE ledger shape
            # across data servers and lookup servers.
            entry.setdefault('wire', control_plane.DEFAULT_TRANSPORT)
        return None

    def _handle(self, request):
        cmd = request.get('cmd')
        if cmd == 'attach':
            refusal = self._admit(request)
            if refusal is not None:
                return refusal
            return {'server_id': self._server_id,
                    'name': self.server_name, 'state': self.state,
                    'lease_s': self._lease_s}
        if cmd == 'detach':
            with self._lock:
                self._admission.release_locked(request.get('consumer'))
            return {'ok': True}
        if cmd == 'lookup':
            refusal = self._admit(request)
            if refusal is not None:
                return refusal
            rows = self._engine.lookup(request.get('keys') or (),
                                       fields=request.get('fields'))
            return {'server_id': self._server_id, 'rows': rows}
        if cmd == 'query':
            refusal = self._admit(request)
            if refusal is not None:
                return refusal
            pieces = request.get('pieces')
            pieces_mod = request.get('pieces_mod')
            if pieces_mod is not None:
                # Scatter-gather's modular cover: [pid, n_partitions]
                # names this server's disjoint share of the row groups.
                pid, n_partitions = (int(pieces_mod[0]),
                                     int(pieces_mod[1]))
                pieces = range(pid, self._engine.piece_count,
                               n_partitions)
            rows = self._engine.query(
                request['predicate'],
                selector=request.get('selector'),
                limit=request.get('limit'),
                fields=request.get('fields'),
                pieces=pieces,
                with_locations=bool(request.get('with_locations')))
            return {'server_id': self._server_id, 'rows': rows}
        if cmd == 'pmap':
            with self._lock:
                pmap = self._pmap
            return {'server_id': self._server_id,
                    'name': self.server_name,
                    'pmap': None if pmap is None else pmap.to_wire()}
        if cmd == 'pmap_update':
            adopted = self.adopt_partition_map(request['pmap'])
            with self._lock:
                version = (None if self._pmap is None
                           else self._pmap.version)
            return {'server_id': self._server_id, 'adopted': adopted,
                    'version': version}
        if cmd == 'chunk':
            # Warm-join export: serve one piece's packed chunk to a
            # joining peer. Deliberately NOT behind _admit — a draining
            # replica is exactly who a reassigned partition's new owner
            # evacuates the cache from, and peers are not consumers.
            blob = self._engine.packed_chunk(int(request['piece']))
            return {'server_id': self._server_id,
                    'name': self.server_name,
                    'chunk': blob}
        if cmd == 'drain':
            drained = self.drain(float(request.get('timeout_s', 30.0)),
                                 _inflight_floor=1)
            return {'server_id': self._server_id, 'state': self.state,
                    'drained': bool(drained)}
        if cmd == 'stats':
            with self._lock:
                n_consumers = self._admission.count_locked()
                served = self.requests_served
                pmap = self._pmap
                wire_sessions = control_plane.session_transports_locked(
                    self._admission)
            return {'server_id': self._server_id,
                    'name': self.server_name, 'state': self.state,
                    'lease_s': self._lease_s,
                    'consumers': n_consumers,
                    'wire': wire_sessions,
                    'max_consumers': self._max_consumers,
                    'requests_served': served,
                    'partition_map_version': (None if pmap is None
                                              else pmap.version),
                    'engine': self._engine.stats()}
        if cmd == 'metrics':
            from petastorm_tpu import metrics as metrics_mod
            return {'server_id': self._server_id,
                    'registry_id': metrics_mod.REGISTRY_INSTANCE_ID,
                    'metrics': metrics_mod.get_registry().collect()}
        if cmd == 'schema':
            return {'schema': self._engine.schema,
                    'index': self._engine.index.name,
                    'index_field': self._engine.index.field}
        raise ValueError('unknown rpc command {!r}'.format(cmd))
