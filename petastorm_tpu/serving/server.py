"""LookupServer: the lookup tier's ZMQ service plane.

``lookup``/``query`` verbs over a ROUTER socket — served inline on one
thread by default (the lowest-latency path: a warm point read is one
engine call between two socket events), or fanned out to a pool of
inproc REP workers (``rpc_workers > 1``: concurrent heavy queries
coalesce inside the shared
:class:`~petastorm_tpu.serving.engine.LookupEngine`) — run under the
PR-10 control-plane discipline the data service proved out:

* **lease heartbeats** on a PUB socket (``PST_LHB`` + JSON: server id,
  lease seconds, drain state, rpc endpoint) every third of the lease —
  a client that saw one heartbeat then silence for a full lease routes
  around the server with zero rpc round trips;
* **admission control**: a consumer ledger with 3-lease expiry; past
  ``max_consumers`` (or under the memory governor's *shed* rung) new
  consumers get the TYPED refusal (``{'refused': 'overloaded', ...}``)
  instead of silently degrading everyone;
* **graceful drain**: :meth:`drain` (or the ``drain`` verb) stops
  admission, refuses further reads with ``{'refused': 'draining'}``,
  lets in-flight requests complete, and reports ``drained`` — clients
  fail over on the typed reply;
* **SLO observability**: ``pst_lookup_requests_total{verb,outcome}``,
  ``pst_lookup_latency_seconds{verb}`` (the shared log-spaced buckets,
  so fleet histograms merge bucket-for-bucket),
  ``pst_lookup_cache_hits_total{tier}`` (engine-side), all in the
  process metrics registry — scraped over the ``metrics`` verb, by the
  HTTP exporter, and dumped by the flight recorder on escalation;
* chaos surface: the existing ``server-slow`` (delay before the reply)
  and ``rpc-blackhole`` (swallow the request, reset the REP state
  machine) fault sites fire inside the worker loop, so the client's
  circuit breaker and hedging are drill-testable like the data plane's.
"""

import json
import logging
import pickle
import threading
import time
import uuid

logger = logging.getLogger(__name__)

#: Control-plane heartbeat prefix (PUB broadcasts, JSON body).
CTRL_HB = b'PST_LHB'

DEFAULT_LEASE_S = 10.0


class LookupServer(object):
    """Serve a :class:`~petastorm_tpu.serving.engine.LookupEngine` over zmq.

    :param engine: the shared local request path (thread-safe).
    :param bind: rpc endpoint, e.g. ``'tcp://127.0.0.1:*'``. Clients
        dial :attr:`rpc_endpoint`.
    :param control_bind: lease-heartbeat PUB endpoint (default: rpc
        port + 1 for tcp binds).
    :param lease_s: lease duration (default ``PETASTORM_TPU_LEASE_S``
        or 10); heartbeats go out every third of it.
    :param max_consumers: admission capacity; ``None`` = unlimited.
    :param rpc_workers: concurrent request handlers. The default (1)
        serves the ROUTER inline on one thread — the LOWEST-latency
        configuration (no inproc hop, no extra thread handoff per
        request; a warm point read is one engine call between two socket
        events). Raise it when many clients run heavy ``query`` scans
        concurrently — point reads then ride the engine's coalescing.
    :param gc_freeze: on :meth:`start`, freeze the baseline object graph
        out of the cyclic collector (``gc.freeze()``). A gen-2 pass over
        a big warm process pauses every thread ~10ms — the exact tail
        the warm-read SLO forbids — while the serving path's own garbage
        is acyclic and dies by refcount. The collector stays ENABLED;
        only startup state stops being re-walked.
    """

    def __init__(self, engine, bind, control_bind=None, lease_s=None,
                 max_consumers=None, rpc_workers=1, gc_freeze=True):
        import zmq

        from petastorm_tpu import membudget
        from petastorm_tpu import metrics as metrics_mod
        from petastorm_tpu.data_service import (ENV_LEASE, _connectable,
                                                _env_float,
                                                _next_port_endpoint)

        self._engine = engine
        self._zmq = zmq
        self._context = zmq.Context.instance()
        self._server_id = uuid.uuid4().hex
        self._lease_s = float(lease_s if lease_s is not None
                              else _env_float(ENV_LEASE, DEFAULT_LEASE_S))
        self._max_consumers = (None if max_consumers is None
                               else int(max_consumers))
        self._rpc_workers = max(1, int(rpc_workers))
        self._gc_freeze = bool(gc_freeze)
        self._gc_frozen = False

        self._frontend = self._context.socket(zmq.ROUTER)
        self._ctrl_sock = None
        self._backend = None
        try:
            self._frontend.bind(bind)
            actual = self._frontend.getsockopt(zmq.LAST_ENDPOINT).decode()
            ctrl_endpoint = (control_bind if control_bind is not None
                             else _next_port_endpoint(actual))
            self._ctrl_sock = self._context.socket(zmq.PUB)
            self._ctrl_sock.bind(ctrl_endpoint)
            if self._rpc_workers > 1:
                # Worker fan-out: one DEALER bound inproc; each worker
                # thread connects a REP. inproc requires bind-before-
                # connect, so the backend binds here, before any worker
                # thread starts. (rpc_workers=1 serves the ROUTER inline
                # — no backend at all.)
                self._backend = self._context.socket(zmq.DEALER)
                self._inproc = 'inproc://pst-lookup-{}'.format(
                    self._server_id)
                self._backend.bind(self._inproc)
        except Exception:
            for sock in (self._frontend, self._ctrl_sock, self._backend):
                if sock is not None:
                    sock.close(linger=0)
            raise
        self.rpc_endpoint = _connectable(actual)
        self.control_endpoint = _connectable(
            self._ctrl_sock.getsockopt(zmq.LAST_ENDPOINT).decode())

        self._m_requests = metrics_mod.counter(
            'pst_lookup_requests_total',
            'Lookup-tier rpc requests, by verb and outcome',
            labelnames=('verb', 'outcome'))
        self._m_latency = metrics_mod.histogram(
            'pst_lookup_latency_seconds',
            'Lookup-tier request service latency, by verb',
            labelnames=('verb',))
        self._m_rejected = metrics_mod.counter(
            'pst_consumers_rejected_total',
            'Consumer attach requests a data-service server refused',
            labelnames=('reason',))

        self._lock = threading.Lock()
        self._consumers = {}           # consumer id -> last renew (monotonic)
        self._draining = threading.Event()
        self._drained = threading.Event()
        self._stop = threading.Event()
        self._inflight = 0             # requests inside worker handlers
        self._response_bytes = 0       # serialized replies not yet sent
        self.requests_served = 0

        # Memory-governor wiring: response bytes in flight are accounted,
        # and the *shed* rung flips this server to typed memory-pressure
        # refusals for new consumers (existing ones keep reading — load
        # shedding must not break clients mid-conversation).
        self._mem_shed = False
        self._mem_handle = membudget.register_pool(
            'lookup-responses', self._response_nbytes,
            shed_fn=self._set_mem_shed)

        self._threads = []

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        if self._threads:
            raise RuntimeError('server already started')
        if self._gc_freeze:
            import gc
            gc.collect()
            gc.freeze()
            self._gc_frozen = True
        rpc_target = (self._serve_inline if self._backend is None
                      else self._proxy_loop)
        self._threads = [
            threading.Thread(target=rpc_target, daemon=True,
                             name='pst-lookup-rpc'),
            threading.Thread(target=self._control_loop, daemon=True,
                             name='pst-lookup-lease'),
        ]
        if self._backend is not None:
            self._threads += [
                threading.Thread(target=self._worker_loop, args=(i,),
                                 daemon=True,
                                 name='pst-lookup-worker-{}'.format(i))
                for i in range(self._rpc_workers)]
        for thread in self._threads:
            thread.start()
        return self

    def stop(self):
        if self._gc_frozen:
            # Unpin the start()-time heap snapshot: a process that keeps
            # running after the server stops (a trainer serving between
            # epochs, a test session) must get cyclic collection of that
            # state back, or stop/start cycles grow memory monotonically.
            import gc
            gc.unfreeze()
            self._gc_frozen = False
        self._mem_handle.close()
        self._stop.set()
        joined = True
        for thread in self._threads:
            thread.join(timeout=10)
            joined = joined and not thread.is_alive()
        if joined:
            self._frontend.close(linger=0)
            if self._backend is not None:
                self._backend.close(linger=0)
            self._ctrl_sock.close(linger=0)
        else:  # pragma: no cover - requires a wedged handler
            logger.warning('lookup rpc thread still running after stop(); '
                           'leaking zmq sockets rather than closing them '
                           'from another thread')
        self._threads = []

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.stop()

    # -- drain state machine ----------------------------------------------

    @property
    def state(self):
        if self._drained.is_set():
            return 'drained'
        if self._draining.is_set():
            return 'draining'
        return 'serving'

    def drain(self, timeout_s=30.0, _inflight_floor=0):
        """Stop admitting, refuse further reads with the typed
        ``draining`` reply, wait for in-flight requests to finish, and
        report drained. Idempotent. ``_inflight_floor`` is the ``drain``
        rpc handler's own request, which is in-flight by definition and
        must not wait on itself."""
        self._draining.set()
        deadline = time.monotonic() + (timeout_s
                                       if timeout_s is not None else 30.0)
        while time.monotonic() < deadline:
            with self._lock:
                if self._inflight <= _inflight_floor:
                    self._drained.set()
                    return True
            time.sleep(0.01)
        return self._drained.is_set()

    # -- membudget hooks ---------------------------------------------------

    def _response_nbytes(self):
        with self._lock:
            return self._response_bytes

    def _set_mem_shed(self, active):
        self._mem_shed = bool(active)

    # -- control plane -----------------------------------------------------

    def _control_loop(self):
        """Owns the PUB socket: lease heartbeats every ``lease_s / 3``
        plus admission-ledger pruning (3 leases without a renew frees a
        crashed consumer's slot)."""
        hb_interval = max(self._lease_s / 3.0, 0.05)
        while not self._stop.is_set():
            body = json.dumps({'server_id': self._server_id,
                               'lease_s': self._lease_s,
                               'state': self.state,
                               'rpc': self.rpc_endpoint}).encode('utf-8')
            self._ctrl_sock.send(CTRL_HB + body)
            now = time.monotonic()
            expiry = 3 * self._lease_s
            with self._lock:
                for cid in [c for c, t in self._consumers.items()
                            if now - t > expiry]:
                    del self._consumers[cid]
                    logger.warning('lookup server %s: consumer %s admission '
                                   'lease expired', self.rpc_endpoint, cid)
            self._stop.wait(hb_interval)

    # -- rpc plane ---------------------------------------------------------

    def _proxy_loop(self):
        """The ROUTER <-> inproc DEALER shuttle. Poll-driven so stop()
        can interrupt it; messages route the moment they arrive."""
        zmq = self._zmq
        poller = zmq.Poller()
        poller.register(self._frontend, zmq.POLLIN)
        poller.register(self._backend, zmq.POLLIN)
        while not self._stop.is_set():
            events = dict(poller.poll(100))
            if self._frontend in events:
                self._backend.send_multipart(
                    self._frontend.recv_multipart())
            if self._backend in events:
                self._frontend.send_multipart(
                    self._backend.recv_multipart())

    def _serve_request(self, raw):
        """Decode one request, answer it through the engine under the
        admission/drain rules, time it. Returns the serialized reply, or
        ``None`` when the ``rpc-blackhole`` fault swallowed the request
        (the caller resets its transport state accordingly)."""
        from petastorm_tpu import faults
        if faults.get_injector().should_fire('rpc-blackhole'):
            logger.warning('fault injection: rpc-blackhole dropping '
                           'lookup request without reply')
            return None
        with self._lock:
            self._inflight += 1
        t0 = time.perf_counter()
        verb = 'unknown'
        try:
            try:
                request = pickle.loads(raw)
                verb = str(request.get('cmd') or 'unknown')
                reply = self._handle(request)
            except Exception as e:  # noqa: BLE001 - reply, don't die
                logger.exception('lookup rpc failed')
                reply = {'error': repr(e)}
            outcome = ('refused' if isinstance(reply, dict)
                       and 'refused' in reply
                       else 'error' if isinstance(reply, dict)
                       and 'error' in reply else 'ok')
            self._m_requests.labels(verb, outcome).inc()
            self._m_latency.labels(verb).observe(time.perf_counter() - t0)
            faults.maybe_inject('server-slow')
            try:
                payload = pickle.dumps(reply, protocol=5)
            except Exception as e:  # noqa: BLE001 - degrade typed
                payload = pickle.dumps({'error': repr(e)}, protocol=5)
            with self._lock:
                self.requests_served += 1
            return payload
        finally:
            with self._lock:
                self._inflight -= 1

    def _serve_inline(self):
        """rpc_workers=1: handle requests ON the ROUTER thread. One
        thread, no inproc hop — each warm read is recv, engine call,
        send. A blackholed request is simply not replied to (ROUTER has
        no REP state machine to reset)."""
        while not self._stop.is_set():
            if not self._frontend.poll(100):
                continue
            frames = self._frontend.recv_multipart()
            payload = self._serve_request(frames[-1])
            if payload is None:
                continue
            with self._lock:
                self._response_bytes += len(payload)
            try:
                self._frontend.send_multipart(frames[:-1] + [payload])
            finally:
                with self._lock:
                    self._response_bytes -= len(payload)

    def _worker_loop(self, worker_id):
        """One inproc REP handler behind the proxy (rpc_workers > 1)."""
        zmq = self._zmq
        sock = self._context.socket(zmq.REP)
        sock.connect(self._inproc)
        try:
            while not self._stop.is_set():
                if not sock.poll(100):
                    continue
                raw = sock.recv()
                payload = self._serve_request(raw)
                if payload is None:
                    # Swallowed by the blackhole drill: REP requires
                    # send-before-recv — reset the state machine with a
                    # fresh socket (inproc reconnect is cheap).
                    sock.close(linger=0)
                    sock = self._context.socket(zmq.REP)
                    sock.connect(self._inproc)
                    continue
                with self._lock:
                    self._response_bytes += len(payload)
                try:
                    sock.send(payload)
                finally:
                    with self._lock:
                        self._response_bytes -= len(payload)
        finally:
            sock.close(linger=0)

    def _admit(self, request):
        """Admission/drain gate for one request; a dict = typed refusal
        reply, ``None`` = admitted (and the consumer's lease renewed)."""
        consumer = request.get('consumer') or 'anonymous'
        now = time.monotonic()
        with self._lock:
            known = consumer in self._consumers
            state = self.state
            if state in ('draining', 'drained'):
                # Unlike the data plane (which finishes feeding admitted
                # streams), a drained lookup tier refuses EVERY read: each
                # request is standalone, and the typed reply is what makes
                # the client fail over instead of waiting out a corpse.
                self._m_rejected.labels('draining').inc()
                return {'server_id': self._server_id, 'refused': state,
                        'state': state}
            if not known:
                if self._max_consumers is not None \
                        and len(self._consumers) >= self._max_consumers:
                    self._m_rejected.labels('overloaded').inc()
                    return {'server_id': self._server_id,
                            'refused': 'overloaded',
                            'max_consumers': self._max_consumers,
                            'state': state}
                if self._mem_shed:
                    self._m_rejected.labels('memory-pressure').inc()
                    return {'server_id': self._server_id,
                            'refused': 'overloaded',
                            'reason': 'memory-pressure',
                            'state': state}
            self._consumers[consumer] = now
        return None

    def _handle(self, request):
        cmd = request.get('cmd')
        if cmd == 'attach':
            refusal = self._admit(request)
            if refusal is not None:
                return refusal
            return {'server_id': self._server_id, 'state': self.state,
                    'lease_s': self._lease_s}
        if cmd == 'detach':
            with self._lock:
                self._consumers.pop(request.get('consumer'), None)
            return {'ok': True}
        if cmd == 'lookup':
            refusal = self._admit(request)
            if refusal is not None:
                return refusal
            rows = self._engine.lookup(request.get('keys') or (),
                                       fields=request.get('fields'))
            return {'server_id': self._server_id, 'rows': rows}
        if cmd == 'query':
            refusal = self._admit(request)
            if refusal is not None:
                return refusal
            rows = self._engine.query(request['predicate'],
                                      selector=request.get('selector'),
                                      limit=request.get('limit'),
                                      fields=request.get('fields'))
            return {'server_id': self._server_id, 'rows': rows}
        if cmd == 'drain':
            drained = self.drain(float(request.get('timeout_s', 30.0)),
                                 _inflight_floor=1)
            return {'server_id': self._server_id, 'state': self.state,
                    'drained': bool(drained)}
        if cmd == 'stats':
            with self._lock:
                n_consumers = len(self._consumers)
                served = self.requests_served
            return {'server_id': self._server_id, 'state': self.state,
                    'lease_s': self._lease_s,
                    'consumers': n_consumers,
                    'max_consumers': self._max_consumers,
                    'requests_served': served,
                    'engine': self._engine.stats()}
        if cmd == 'metrics':
            from petastorm_tpu import metrics as metrics_mod
            return {'server_id': self._server_id,
                    'registry_id': metrics_mod.REGISTRY_INSTANCE_ID,
                    'metrics': metrics_mod.get_registry().collect()}
        if cmd == 'schema':
            return {'schema': self._engine.schema,
                    'index': self._engine.index.name,
                    'index_field': self._engine.index.field}
        raise ValueError('unknown rpc command {!r}'.format(cmd))
