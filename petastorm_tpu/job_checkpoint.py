"""Whole-job checkpointing: model + optimizer + input pipeline, one artifact.

The reference has no checkpointing at all (SURVEY §5.4); ``checkpoint.py``
closes the *reader* half (mid-epoch exactly-once resume). This module closes
the other half and joins them: a :class:`JobCheckpointer` saves the training
state (params / optimizer / batch stats — any JAX pytree, mesh-sharded
arrays included) **together with** the reader's ``state_dict()`` and
arbitrary JSON metadata, atomically, under one step directory. Restoring
returns both, so a preempted TPU job resumes with the exact parameters AND
the exact row position — no replayed batches, no lost rows.

TPU-first choices:

* orbax-checkpoint underneath: sharded ``jax.Array`` leaves are written in
  parallel from every host of a pod and restored to the template's
  ``NamedSharding`` — no host gathers the full state (a 10B-param state
  never materializes on one machine).
* ``async_save=True`` hides serialization behind the next train steps
  (orbax's AsyncCheckpointer); ``wait()``/``close()`` fence it.
* The loader state rides in the same orbax composite as a JSON entry, so a
  checkpoint is atomic: either both halves land or neither — never a
  params file paired with a stale row position (orbax finalizes the step
  directory with a rename).
"""

import logging

logger = logging.getLogger(__name__)


class JobCheckpoint(object):
    """What :meth:`JobCheckpointer.restore` returns."""

    def __init__(self, step, state, loader_state, extra):
        self.step = step
        self.state = state
        self.loader_state = loader_state
        self.extra = extra

    def __repr__(self):
        return 'JobCheckpoint(step={}, loader_state={}, extra={})'.format(
            self.step, 'yes' if self.loader_state else 'no', self.extra)


class JobCheckpointer(object):
    """Save/restore (training state, reader position, metadata) per step.

    :param directory: checkpoint root (local path or fsspec URL the
        underlying orbax filesystem supports).
    :param max_to_keep: retained checkpoints; older steps are garbage
        collected by orbax.
    :param async_save: serialize in the background (call :meth:`wait` —
        or let ``close``/ctx-exit do it — before relying on the files).
    :param save_interval_steps: ``save()`` calls off the interval are no-ops
        (orbax ``should_save``), so the training loop can call every step.
    """

    def __init__(self, directory, max_to_keep=3, async_save=False,
                 save_interval_steps=1):
        import orbax.checkpoint as ocp

        from petastorm_tpu import metrics

        self._ocp = ocp
        self._directory = _to_abs_path(directory)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=bool(async_save))
        self._manager = ocp.CheckpointManager(self._directory, options=options)
        # Checkpoint cadence on the shared scrape surface: a preemption-
        # heavy fleet alerting on "no save in N minutes" (or a save-latency
        # regression eating step time) reads these, not the logs.
        self._m_saves = metrics.counter(
            'pst_checkpoint_saves_total',
            'Job checkpoints actually saved (interval skips excluded)')
        self._m_restores = metrics.counter(
            'pst_checkpoint_restore_total',
            'Job checkpoints restored')
        self._m_save_seconds = metrics.histogram(
            'pst_checkpoint_save_seconds',
            'JobCheckpointer.save latency (dispatch only under async_save)')

    # -- save --------------------------------------------------------------

    def save(self, step, state, loader=None, extra=None, force=False):
        """Checkpoint ``state`` (any pytree) at ``step``.

        :param loader: a ``JaxLoader``/``Reader`` (anything with
            ``state_dict()``) or an already-captured state dict. Capture
            happens here, synchronously — the row position and the params
            snapshot correspond even under ``async_save``.
        :param extra: JSON-serializable metadata (epoch, metrics, rng seed).
        :param force: bypass ``save_interval_steps``.
        :returns: True if a save was performed (interval not skipped).
        """
        import time

        ocp = self._ocp
        loader_state = _capture_loader_state(loader)
        items = {'state': ocp.args.StandardSave(state)}
        # JSON entries; always present so restore never probes directories.
        items['loader'] = ocp.args.JsonSave(_encode_loader_state(loader_state))
        items['extra'] = ocp.args.JsonSave(extra if extra is not None else {})
        t0 = time.perf_counter()
        saved = self._manager.save(step, args=ocp.args.Composite(**items),
                                   force=force)
        if saved:
            self._m_saves.inc()
            self._m_save_seconds.observe(time.perf_counter() - t0)
            logger.info('job checkpoint step %d -> %s', step, self._directory)
        return bool(saved)

    # -- restore -----------------------------------------------------------

    def latest_step(self):
        """Most recent checkpointed step, or None."""
        return self._manager.latest_step()

    def restore(self, state_template, step=None):
        """Restore a :class:`JobCheckpoint`.

        :param state_template: a pytree matching the saved structure — pass
            the freshly-initialized training state. Sharded leaves (e.g.
            from ``create_train_state(mesh=...)``) restore straight to
            their ``NamedSharding``, never gathered to one host.
        :param step: specific step (default: latest).
        :returns: :class:`JobCheckpoint` or None if nothing is saved.
        """
        ocp = self._ocp
        if step is None:
            step = self._manager.latest_step()
            if step is None:
                return None
        elif step not in self._manager.all_steps():
            # Never saved, or already garbage-collected by max_to_keep —
            # honor the "or None" contract instead of surfacing orbax's
            # FileNotFoundError.
            return None
        restored = self._manager.restore(
            step, args=ocp.args.Composite(
                state=ocp.args.StandardRestore(state_template),
                loader=ocp.args.JsonRestore(),
                extra=ocp.args.JsonRestore()))
        loader_state = _decode_loader_state(restored['loader']) or None
        self._m_restores.inc()
        return JobCheckpoint(step=step, state=restored['state'],
                             loader_state=loader_state,
                             extra=restored['extra'] or {})

    # -- lifecycle ---------------------------------------------------------

    def wait(self):
        """Block until any in-flight async save is durable."""
        self._manager.wait_until_finished()

    def close(self):
        self._manager.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False


_PICKLED_KEY = '__pst_pickled_b64__'


def _pickle_to_json(loader_state):
    import base64
    import pickle
    return {_PICKLED_KEY: base64.b64encode(
        pickle.dumps(loader_state, protocol=5)).decode('ascii')}


def _encode_loader_state(loader_state):
    """Loader states are JSON by contract — except the data service's,
    whose snapshot embeds the drained in-flight numpy chunks
    (``RemoteReader.state_dict``). Those ride as base64 pickle inside the
    same JSON entry, keeping the composite atomic (params + loader land
    or neither) without a second artifact format."""
    if loader_state is None:
        return {}
    if (isinstance(loader_state, dict) and 'server_states' in loader_state
            and ('pending' in loader_state or 'consumers' in loader_state)):
        # The service snapshot shapes (sole-consumer state_dict or
        # checkpoint_shared_stream) — known non-JSON (and potentially
        # megabytes of chunks): go straight to pickle, no throwaway probe.
        return _pickle_to_json(loader_state)
    import json
    try:
        # Cheap for the contract-conformant states (small dicts of chunk
        # counters). The probe checks ROUND-TRIP fidelity, not just
        # serializability: an exotic state with int dict keys or tuples
        # would survive json.dumps but come back altered (str keys,
        # lists) — such states must take the pickle path too.
        if json.loads(json.dumps(loader_state)) == loader_state:
            return loader_state
    except TypeError:
        pass
    return _pickle_to_json(loader_state)


def _decode_loader_state(entry):
    if isinstance(entry, dict) and _PICKLED_KEY in entry:
        import base64
        import pickle
        return pickle.loads(base64.b64decode(entry[_PICKLED_KEY]))
    return entry


def _capture_loader_state(loader):
    if loader is None:
        return None
    if isinstance(loader, dict):
        return loader
    state_dict = getattr(loader, 'state_dict', None)
    if state_dict is None:
        raise TypeError('loader must expose state_dict() or be a dict, got {}'
                        .format(type(loader).__name__))
    return state_dict()


def _to_abs_path(directory):
    """Orbax requires absolute paths for local directories."""
    import os
    if '://' in str(directory):
        return directory
    return os.path.abspath(str(directory))
