"""Decoded-columnar row-group worker: the TPU hot path.

The reference offers two mutually exclusive read modes: per-row decoded
(``py_dict_reader_worker.py`` — codecs run, but every sample crosses the
pool as a Python dict) and columnar raw (``arrow_reader_worker.py:39-79`` —
zero-copy-ish, but codec cells stay encoded). Neither can feed an
accelerator decoded tensors without per-row Python costs. This worker is
the missing third mode: it decodes every codec column *inside the worker*
straight into one contiguous ``[N, ...field.shape]`` numpy block per field
(images via the native C++ batch decoder with the GIL released,
``native/src/image_codec.cc``), and publishes a small dict of big arrays —
O(fields) Python objects per row-group instead of O(rows).

Downstream, ``jax_loader.iter_numpy_batches`` slices these blocks into
fixed-size batches with one memcpy per batch and stages them with
``jax.device_put`` / ``make_array_from_process_local_data`` — decoded
tensors cross zero per-row Python boundaries end to end.

Requires every non-scalar field to have a fully static shape (XLA needs
static shapes anyway); ``make_tensor_reader`` validates this up front.
"""

import logging
import time

import numpy as np
import pyarrow as pa

from petastorm_tpu.checkpoint import DeferredRowAccounting, chunk_key
from petastorm_tpu.codecs import (CompressedImageCodec, CompressedNdarrayCodec,
                                  NdarrayCodec, ScalarCodec, _fast_npy_decode,
                                  _native_image)
from petastorm_tpu.determinism import ResequencedReads, is_hole
from petastorm_tpu.errors import DecodeFieldError
from petastorm_tpu.workers.rowgroup_worker_base import (RowGroupWorkerBase,
                                                        chunk_row_permutation,
                                                        compute_row_slice)

logger = logging.getLogger(__name__)


def validate_tensor_schema(schema):
    """Raise unless every field can decode into a fixed-shape dense block."""
    for name, field in schema.fields.items():
        codec = field.resolved_codec()
        if isinstance(codec, ScalarCodec) or (codec is None and field.shape == ()):
            continue
        if field.shape and any(dim is None for dim in field.shape):
            raise ValueError(
                'make_tensor_reader requires static shapes, but field {!r} has '
                'shape {} (None = variable dim). Re-materialize with a fixed '
                'shape, or use make_reader with a shape policy in the '
                'JaxLoader.'.format(name, field.shape))
        if codec is None and field.shape:
            raise ValueError(
                'make_tensor_reader requires a codec on tensor field {!r} '
                '(plain Parquet stores: use make_batch_reader)'.format(name))


class TensorWorker(RowGroupWorkerBase):
    """Same args dict as PyDictWorker/ArrowWorker (see PyDictWorker docstring).

    Publishes ``{'__pst_tensor_chunk__': 1, 'key': str, 'cols': {name: np
    block}, 'timings': {...}}`` per row-group. The per-stage timings feed the
    bench's read/decode/transport/assemble/stage profile (VERDICT r2 #1).
    """

    #: Reader-mode tag for batch provenance contexts (lineage.py replay
    #: picks its decode path by this).
    lineage_mode = 'tensor'

    def process(self, piece_index, worker_predicate=None,
                shuffle_row_drop_partition=None, pst_det=None):
        from petastorm_tpu.faults import maybe_inject, rowgroup_fault_key

        piece = self.args['row_groups'][piece_index]
        schema = self.args['schema']
        maybe_inject('decode-corrupt',
                     key=rowgroup_fault_key(piece.path, piece.row_group))
        timings = {}
        decoded_fresh = []    # load() ran => served from decode, not a cache

        def load():
            from petastorm_tpu import metrics
            from petastorm_tpu.trace import get_global_tracer

            decoded_fresh.append(True)
            t0 = time.perf_counter()
            table = self._load_table(piece, worker_predicate)
            timings['read_s'] = time.perf_counter() - t0
            if table is None or table.num_rows == 0:
                return None
            t0 = time.perf_counter()
            # The decode span (process-local global tracer — a sidecar
            # spiller inside pool workers, see trace.install_worker_tracer)
            # is what makes worker-subprocess decode visible on a merged
            # timeline; the histogram is its scrape-surface twin.
            with get_global_tracer().span('decode', 'worker'):
                cols = decode_table_to_blocks(
                    table, schema, self.args.get('decode_threads'),
                    fault_key=rowgroup_fault_key(piece.path, piece.row_group),
                    raw_fields=self.args.get('raw_image_fields') or ())
            timings['decode_s'] = time.perf_counter() - t0
            metrics.histogram(
                'pst_decode_seconds',
                'Row-group decode latency inside workers').observe(
                    timings['decode_s'])
            return cols

        from petastorm_tpu.cache import NullCache
        # The predicate path bypasses the cache entirely, so its chunks are
        # always private — no defensive copy needed before transforms.
        cached = (worker_predicate is None
                  and not isinstance(self.args['cache'], NullCache))
        # Block-handoff ownership marker: ``private=False`` blocks are (or
        # may be) shared by reference with the RAM cache and MUST only ever
        # be copied FROM downstream — the loader's recycled-arena collate
        # path would corrupt every later epoch if it took ownership of (or
        # padded/recycled in place) a cached block. Transform and in-chunk-
        # shuffle below both copy, flipping the chunk back to private.
        private = not cached
        if worker_predicate is None:
            # Shared key builder (chunk_store.tensor_chunk_key): the NVMe
            # store lookup happens here, AHEAD of decode — cache.get only
            # runs load() (read + decode) on a store miss, and the reader's
            # ventilation-order readahead computes the identical key.
            from petastorm_tpu.chunk_store import tensor_chunk_key
            cache_key = tensor_chunk_key(self.args['dataset_path_hash'],
                                         piece.path, piece.row_group, schema)
            t0 = time.perf_counter()
            cols = self.args['cache'].get(cache_key, load)
            # Cache bookkeeping only: the miss's read/decode seconds are
            # reported under their own keys, not double-counted here.
            timings['cache_s'] = (time.perf_counter() - t0
                                  - timings.get('read_s', 0.0)
                                  - timings.get('decode_s', 0.0))
        else:
            cols = load()
        if cols is None:
            return self._publish_hole(pst_det)
        n_rows = len(next(iter(cols.values())))

        row_slice = compute_row_slice(n_rows, shuffle_row_drop_partition)
        if row_slice is not None:
            start, stop = row_slice
            if stop <= start:
                return self._publish_hole(pst_det)
            cols = {k: v[start:stop] for k, v in cols.items()}
            n_rows = stop - start

        transform_spec = self.args.get('transform_spec')
        if transform_spec is not None and transform_spec.func is not None:
            # Tensor-mode transforms operate on the dict of column blocks
            # (numpy in, numpy out) — the vectorized analog of the reference's
            # pandas TransformSpec (``arrow_reader_worker.py:163-178``).
            # Cached blocks are shared by reference across epochs; in-place
            # user transforms (a common idiom) must see private copies or
            # epoch 2's cache hit would serve already-transformed data.
            if cached:
                cols = {k: np.array(v, copy=True) for k, v in cols.items()}
                private = True
            out = transform_spec.func(dict(cols))
            for name in transform_spec.removed_fields:
                out.pop(name, None)
            keep = self.args['transformed_schema'].fields
            cols = {k: np.asarray(v) for k, v in out.items() if k in keep}
            if not cols:
                return self._publish_hole(pst_det)
            n_rows = len(next(iter(cols.values())))

        if n_rows and self.args.get('shuffle_rows_in_chunk'):
            # Deterministic per-(seed, row-group, drop-partition) permutation:
            # fixed across epochs and across sessions, so mid-epoch resume
            # skips target the same (permuted) leading rows. Fancy indexing
            # copies, so cached blocks are never mutated.
            perm = chunk_row_permutation(
                self.args.get('shuffle_seed'), self.args['dataset_path_hash'],
                piece.path, piece.row_group, shuffle_row_drop_partition, n_rows)
            cols = {k: v[perm] for k, v in cols.items()}
            private = True

        if n_rows:
            from petastorm_tpu.lineage import chunk_lineage
            from petastorm_tpu.trace import get_global_tracer
            # Serving tier: a fresh decode when load() actually ran (incl.
            # every predicate read, which bypasses the cache), else the
            # cache's own tier label (memory / chunk-store / disk).
            tier = ('decode' if decoded_fresh or worker_predicate is not None
                    else getattr(self.args['cache'], 'lineage_tier', 'cache'))
            lineage = chunk_lineage(
                piece, piece_index, shuffle_row_drop_partition, n_rows,
                tier, permuted=bool(n_rows
                                    and self.args.get('shuffle_rows_in_chunk')),
                filtered=worker_predicate is not None,
                worker_id=self.worker_id)
            payload = {'__pst_tensor_chunk__': 1,
                       'key': chunk_key(piece_index, shuffle_row_drop_partition),
                       'cols': cols,
                       'private': private,
                       'lineage': lineage,
                       'timings': timings}
            if pst_det is not None:
                payload['det'] = pst_det
            with get_global_tracer().span('handoff', 'worker'):
                self.publish_func(payload)
        else:
            self._publish_hole(pst_det)

    # --- loading ------------------------------------------------------

    def _load_table(self, piece, worker_predicate):
        schema = self.args['schema']
        field_names = list(schema.fields)
        partition_names = set(self.args['partition_names'])
        physical = [n for n in field_names if n not in partition_names]

        if worker_predicate is not None:
            table = self._load_with_predicate(piece, physical, field_names,
                                              worker_predicate)
            if table is None:
                return None
        else:
            table = self._read_row_group(piece, physical)
        for name, value in piece.partition_values.items():
            if name in field_names and name not in table.column_names:
                table = table.append_column(name, pa.array([value] * table.num_rows))
        return table

    def _load_with_predicate(self, piece, physical, field_names, predicate):
        """Two-phase predicate read on *decoded* values.

        Unlike the Arrow worker (which evaluates predicates on raw cells),
        tensor-mode predicates see what ``make_reader`` predicates see:
        decoded scalars. Tensor fields in predicates are rejected by
        ``make_tensor_reader``.
        """
        predicate_fields = sorted(predicate.get_fields())
        full_schema = self.args['full_schema']
        unknown = set(predicate_fields) - set(full_schema.fields)
        if unknown:
            raise ValueError('Predicate uses unknown fields: {}'.format(sorted(unknown)))
        partition_names = set(self.args['partition_names'])
        pred_physical = [n for n in predicate_fields if n not in partition_names]
        pred_table = (self._read_row_group(piece, pred_physical) if pred_physical
                      else None)
        n = pred_table.num_rows if pred_table is not None else None
        pred_cols = {}
        if pred_table is not None:
            pred_schema = full_schema.create_schema_view(
                [f for f in predicate_fields if f in full_schema.fields and f in pred_physical])
            pred_cols = decode_table_to_blocks(pred_table, pred_schema,
                                               self.args.get('decode_threads'))
        for name in predicate_fields:
            if name in piece.partition_values:
                if n is None:
                    raise ValueError('Predicate on partition values only should '
                                     'have been pruned before ventilation')
                pred_cols[name] = np.asarray([piece.partition_values[name]] * n)
        mask = np.asarray([predicate.do_include({f: pred_cols[f][i] for f in predicate_fields})
                           for i in range(n)], dtype=bool)
        if not mask.any():
            return None
        table = self._read_row_group(piece, physical)
        return table.take(pa.array(np.flatnonzero(mask)))


class TensorResultsQueueReader(DeferredRowAccounting, ResequencedReads):
    """Consumer side: one decoded chunk -> namedtuple of numpy blocks.

    Checkpoint accounting is chunk-level by default, row-granular after
    ``enable_deferred_rows`` (see ``checkpoint.DeferredRowAccounting``).
    In deterministic mode chunk pops route through the reader's
    resequencer (``ResequencedReads``) so delivery order equals
    ventilation order.
    """

    def __init__(self):
        self._timings = {'read_s': 0.0, 'decode_s': 0.0, 'cache_s': 0.0,
                         'chunks': 0}
        self._last_private = False
        self._last_lineage = None
        self._last_det = None
        #: Optional health.Heartbeat (wired by ``Reader.attach_health``):
        #: beaten per decoded chunk crossing the pool->consumer handoff,
        #: so the watchdog sees TensorWorker output flow directly.
        self.heartbeat = None

    @property
    def batched_output(self):
        return True

    @property
    def stage_timings(self):
        return dict(self._timings)

    @property
    def last_chunk_private(self):
        """Ownership of the chunk most recently returned by ``read_next``:
        True when its blocks are NOT shared with a cache, so a downstream
        collate stage may take ownership of (donate/recycle) them. Read
        synchronously right after the reader yields — the flag refers to
        that sample. Resume-skip slicing keeps the flag: a view of a
        private block is still unshared."""
        return self._last_private

    @property
    def last_chunk_lineage(self):
        """Provenance segment of the chunk most recently returned by
        ``read_next`` (``petastorm_tpu.lineage``): published-chunk
        coordinates with ``row_start`` advanced past any resume skip.
        ``None`` for payloads without lineage metadata."""
        return self._last_lineage

    def read_next(self, pool, schema, ngram):
        if ngram is not None:
            raise NotImplementedError('NGram is not supported with tensor readers')
        while True:
            chunk = self._pull(pool)
            if self.heartbeat is not None:
                self.heartbeat.beat('handoff')
            if is_hole(chunk):
                # Deterministic-mode placeholder: its only job (advancing
                # the resequencer frontier) is already done.
                continue
            cols, key = chunk['cols'], chunk['key']
            det = chunk.get('det')
            self._last_private = bool(chunk.get('private'))
            lineage = chunk.get('lineage')
            t = chunk.get('timings') or {}
            for k in ('read_s', 'decode_s', 'cache_s'):
                if k in t:
                    self._timings[k] += t[k]
            self._timings['chunks'] += 1
            n_rows = len(next(iter(cols.values())))
            if self._tracker is not None:
                skip = self._tracker.on_chunk(key, n_rows, det=det)
                if skip:
                    cols = {k: v[skip:] for k, v in cols.items()}
                    n_rows -= skip
                    if lineage is not None:
                        # Resume re-delivery: the prior session consumed the
                        # chunk's leading rows — the delivered span starts
                        # past them (chunk_rows stays the published length,
                        # which is what replay's permutation recompute needs).
                        lineage = dict(lineage)
                        lineage['row_start'] = lineage.get('row_start', 0) + skip
                if n_rows <= 0:
                    continue
                self._record_chunk(key, n_rows)
            self._last_lineage = lineage
            self._last_det = det
            break
        names = [n for n in schema.fields if n in cols]
        return schema.make_namedtuple(**{n: cols[n] for n in names})

    @property
    def last_chunk_det(self):
        """Deterministic-mode tag (``seq``/``epoch``/``pos``) of the chunk
        most recently returned, or None outside deterministic mode."""
        return self._last_det


# --------------------------------------------------------------------------
# columnar decode
# --------------------------------------------------------------------------

def decode_table_to_blocks(table, schema, decode_threads=None,
                           fault_key=None, raw_fields=()):
    """Arrow table -> dict of contiguous per-field numpy blocks, decoded.

    ``raw_fields`` names image-codec columns shipped *encoded* (the
    on-device decode path): those come out as object-dtype columns of the
    raw bytes instead of decoded pixel blocks — the loader's staging step
    owns their decode (``JaxLoader`` docstring, ``on_device_augment``).
    """
    cols = {}
    for name in schema.fields:
        if name not in table.column_names:
            continue
        field = schema.fields[name]
        column = table.column(name).combine_chunks()
        if column.null_count:
            raise DecodeFieldError(
                'Field {!r} contains nulls; the tensor path requires dense '
                'columns (fill them with a TransformSpec or use make_reader)'
                .format(name))
        codec = field.resolved_codec()
        try:
            if isinstance(codec, CompressedImageCodec):
                if name in raw_fields:
                    cols[name] = _raw_image_column(column)
                else:
                    cols[name] = _decode_image_column(
                        column, field, decode_threads, fault_key=fault_key)
            elif isinstance(codec, (NdarrayCodec, CompressedNdarrayCodec)):
                cols[name] = _decode_ndarray_column(column, field, codec)
            else:  # scalars (incl. partition-value columns)
                cols[name] = _scalar_column_to_numpy(column, field)
        except DecodeFieldError:
            raise
        except Exception as e:
            raise DecodeFieldError('Unable to decode field {!r}: {}'.format(name, e)) from e
    return cols


def _binary_column_view(column):
    """(base_address + offsets, lengths) pointer math over a BinaryArray —
    no per-cell ``bytes`` objects."""
    buffers = column.buffers()
    # [validity, offsets, data]; offset dtype depends on binary vs large_binary
    off_dtype = np.int64 if pa.types.is_large_binary(column.type) else np.int32
    offsets = np.frombuffer(buffers[1], dtype=off_dtype,
                            count=len(column) + column.offset + 1)
    offsets = offsets[column.offset:column.offset + len(column) + 1].astype(np.int64)
    base = buffers[2].address
    return base + offsets[:-1], np.diff(offsets)


def _decode_image_column(column, field, decode_threads, fault_key=None):
    """One contiguous ``[N, ...field.shape]`` block per column via the
    shared batched core (:func:`petastorm_tpu.codecs.decode_image_batch_into`):
    pointer math over the Arrow value buffer feeds one native call for the
    whole row-group; scalar/fallback paths produce byte-identical blocks."""
    from petastorm_tpu.codecs import decode_image_batch_into
    n = len(column)
    dtype = np.dtype(field.numpy_dtype)
    out = np.empty((n,) + tuple(field.shape), dtype=dtype)
    ptrs = lens = None
    if _native_image() is not None and dtype == np.uint8:
        ptrs, lens = _binary_column_view(column)
    decode_image_batch_into(field, out, lambda i: column[i].as_py(),
                            ptrs=ptrs, lens=lens,
                            decode_threads=decode_threads,
                            fault_key=fault_key)
    return out


def _raw_image_column(column):
    """Encoded bytes as an object-dtype column (the raw-image handoff for
    on-device decode): O(1)-per-cell reference copies, no pixel work."""
    n = len(column)
    out = np.empty(n, dtype=object)
    for i, cell in enumerate(column):
        out[i] = cell.as_py()
    return out


def _decode_ndarray_column(column, field, codec):
    n = len(column)
    out = np.empty((n,) + tuple(field.shape), dtype=field.numpy_dtype)
    if isinstance(codec, NdarrayCodec):
        for i, cell in enumerate(column):
            arr = _fast_npy_decode(cell.as_py())
            if arr is None:
                arr = codec.decode(field, cell.as_py())
            out[i] = arr
    else:
        for i, cell in enumerate(column):
            out[i] = codec.decode(field, cell.as_py())
    return out


def _scalar_column_to_numpy(column, field):
    np_dtype = np.dtype(field.numpy_dtype)
    if np_dtype.kind in ('O', 'S', 'U'):
        return np.asarray(column.to_pylist(), dtype=object)
    if np_dtype.kind == 'M':
        return column.to_numpy(zero_copy_only=False).astype('datetime64[ns]')
    arr = column.to_numpy(zero_copy_only=False)
    if arr.dtype != np_dtype:
        arr = arr.astype(np_dtype)
    # Blocks may be sliced + concatenated downstream; ensure ownership so the
    # chunk's Arrow table can be dropped.
    return np.ascontiguousarray(arr)
