"""Bounded random shuffling buffer for row-level decorrelation.

Parity: reference ``petastorm/reader_impl/shuffling_buffer.py`` —
``ShufflingBufferBase`` (``:22``), ``NoopShufflingBuffer`` (``:75``),
``RandomShufflingBuffer`` (``:103-180``) with the swap-with-last O(1) random
pop (``:158-167``) and the ``min_after_retrieve`` decorrelation floor.

TPU-first improvement: the RNG is seedable for cross-host reproducibility.
"""

import numpy as np


class ShufflingBufferBase(object):
    def add_many(self, items):
        raise NotImplementedError

    def retrieve(self):
        raise NotImplementedError

    def can_add(self):
        raise NotImplementedError

    def can_retrieve(self):
        raise NotImplementedError

    @property
    def size(self):
        raise NotImplementedError

    def finish(self):
        """Signal no more items will be added; drain below the floor."""
        raise NotImplementedError


class NoopShufflingBuffer(ShufflingBufferBase):
    """Pass-through FIFO."""

    def __init__(self):
        from collections import deque
        self._store = deque()
        self._done = False

    def add_many(self, items):
        self._store.extend(items)

    def retrieve(self):
        return self._store.popleft()

    def can_add(self):
        return not self._done

    def can_retrieve(self):
        return len(self._store) > 0

    @property
    def size(self):
        return len(self._store)

    def finish(self):
        self._done = True


class RandomShufflingBuffer(ShufflingBufferBase):
    """Uniform random retrieval from a bounded buffer.

    :param shuffling_buffer_capacity: soft cap; ``can_add`` is False at/above it.
    :param min_after_retrieve: retrieval floor before ``finish()`` — keeps the
        buffer full enough to decorrelate.
    :param extra_capacity: how far a single ``add_many`` may overshoot the cap.
    :param seed: RNG seed for reproducible shuffling.
    """

    def __init__(self, shuffling_buffer_capacity, min_after_retrieve,
                 extra_capacity=1000, seed=None):
        if min_after_retrieve >= shuffling_buffer_capacity:
            raise ValueError('min_after_retrieve ({}) must be < capacity ({})'.format(
                min_after_retrieve, shuffling_buffer_capacity))
        self._capacity = shuffling_buffer_capacity
        self._min_after_retrieve = min_after_retrieve
        self._extra_capacity = extra_capacity
        self._store = []
        self._done_adding = False
        self._rng = np.random.default_rng(seed)

    def add_many(self, items):
        if self._done_adding:
            raise RuntimeError('Cannot add after finish()')
        if len(self._store) + len(items) > self._capacity + self._extra_capacity:
            raise RuntimeError(
                'add_many of {} items would exceed capacity+extra ({}+{}); current size {}. '
                'Check can_add() before adding.'.format(
                    len(items), self._capacity, self._extra_capacity, len(self._store)))
        self._store.extend(items)

    def retrieve(self):
        if not self.can_retrieve():
            raise RuntimeError('Buffer below decorrelation floor; add more or finish()')
        index = int(self._rng.integers(0, len(self._store)))
        # O(1) random pop: swap with last (parity: shuffling_buffer.py:158-167)
        self._store[index], self._store[-1] = self._store[-1], self._store[index]
        return self._store.pop()

    def can_add(self):
        return len(self._store) < self._capacity and not self._done_adding

    def can_retrieve(self):
        if self._done_adding:
            return len(self._store) > 0
        return len(self._store) > self._min_after_retrieve

    @property
    def size(self):
        return len(self._store)

    def finish(self):
        self._done_adding = True
