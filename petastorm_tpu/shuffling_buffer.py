"""Bounded random shuffling buffer for row-level decorrelation.

Parity: reference ``petastorm/reader_impl/shuffling_buffer.py`` —
``ShufflingBufferBase`` (``:22``), ``NoopShufflingBuffer`` (``:75``),
``RandomShufflingBuffer`` (``:103-180``) with the swap-with-last O(1) random
pop (``:158-167``) and the ``min_after_retrieve`` decorrelation floor.

TPU-first improvement: the RNG is seedable for cross-host reproducibility,
and :class:`RandomShufflingBuffer` is checkpointable —
``state_dict()``/``restore()`` snapshot the buffered-but-undelivered rows
together with the RNG state, so a mid-epoch job checkpoint taken while a
row-level shuffle is engaged no longer forces a drain (and a resumed
buffer replays the same retrieval draw sequence).
"""

import threading

import numpy as np


class ShufflingBufferBase(object):
    def add_many(self, items):
        raise NotImplementedError

    def retrieve(self):
        raise NotImplementedError

    def can_add(self):
        raise NotImplementedError

    def can_retrieve(self):
        raise NotImplementedError

    @property
    def size(self):
        raise NotImplementedError

    def finish(self):
        """Signal no more items will be added; drain below the floor."""
        raise NotImplementedError


class NoopShufflingBuffer(ShufflingBufferBase):
    """Pass-through FIFO."""

    def __init__(self):
        from collections import deque
        self._store = deque()
        self._done = False

    def add_many(self, items):
        self._store.extend(items)

    def retrieve(self):
        return self._store.popleft()

    def can_add(self):
        return not self._done

    def can_retrieve(self):
        return len(self._store) > 0

    @property
    def size(self):
        return len(self._store)

    def finish(self):
        self._done = True


class RandomShufflingBuffer(ShufflingBufferBase):
    """Uniform random retrieval from a bounded buffer.

    :param shuffling_buffer_capacity: soft cap; ``can_add`` is False at/above it.
    :param min_after_retrieve: retrieval floor before ``finish()`` — keeps the
        buffer full enough to decorrelate.
    :param extra_capacity: how far a single ``add_many`` may overshoot the cap.
    :param seed: RNG seed for reproducible shuffling.
    """

    def __init__(self, shuffling_buffer_capacity, min_after_retrieve,
                 extra_capacity=1000, seed=None):
        if min_after_retrieve >= shuffling_buffer_capacity:
            raise ValueError('min_after_retrieve ({}) must be < capacity ({})'.format(
                min_after_retrieve, shuffling_buffer_capacity))
        self._capacity = shuffling_buffer_capacity
        self._min_after_retrieve = min_after_retrieve
        self._extra_capacity = extra_capacity
        self._store = []
        self._row_nbytes = None   # per-row estimate, sampled on first add
        self._pending = None   # armed by track_pending()
        #: Field order of the buffered row tuples (set by the batch
        #: iterator once it learns its selection). Rides the checkpoint:
        #: a resumed reader that yields ZERO samples (every remaining row
        #: was already buffered at checkpoint time) has no first sample to
        #: learn field names from — the snapshot's names are then the only
        #: way to drain the restored rows.
        self.field_names = None
        self._done_adding = False
        self._rng = np.random.default_rng(seed)
        # Guards store + RNG mutations against a concurrent state_dict():
        # the buffer is driven by the staging engine's assemble thread
        # while checkpoints are taken from the training thread mid-
        # iteration — an unlocked snapshot could capture a row both popped
        # and present. One uncontended acquisition per chunk/row.
        self._lock = threading.Lock()

    def add_many(self, items):
        with self._lock:
            if self._done_adding:
                raise RuntimeError('Cannot add after finish()')
            if len(self._store) + len(items) > self._capacity + self._extra_capacity:
                raise RuntimeError(
                    'add_many of {} items would exceed capacity+extra ({}+{}); current size {}. '
                    'Check can_add() before adding.'.format(
                        len(items), self._capacity, self._extra_capacity, len(self._store)))
            if len(items):
                # Running EMA over one sampled row per add (not a frozen
                # first-row sample): variable-length rows whose early
                # values are small would otherwise under-report the
                # governor's largest loader-side pool for the whole run.
                from petastorm_tpu.membudget import approx_nbytes
                sample = max(1, approx_nbytes(items[0]))
                if self._row_nbytes is None:
                    self._row_nbytes = sample
                else:
                    self._row_nbytes += 0.2 * (sample - self._row_nbytes)
            self._store.extend(items)

    def retrieve(self):
        with self._lock:
            if not self._can_retrieve_locked():
                raise RuntimeError('Buffer below decorrelation floor; add more or finish()')
            index = int(self._rng.integers(0, len(self._store)))
            # O(1) random pop: swap with last (parity: shuffling_buffer.py:158-167)
            self._store[index], self._store[-1] = self._store[-1], self._store[index]
            row = self._store.pop()
            if self._pending is not None:
                self._pending.append(row)
            return row

    def can_add(self):
        return len(self._store) < self._capacity and not self._done_adding

    def can_retrieve(self):
        return self._can_retrieve_locked()

    def _can_retrieve_locked(self):
        # Reads of len()/bool are atomic; safe locked or not.
        if self._done_adding:
            return len(self._store) > 0
        return len(self._store) > self._min_after_retrieve

    @property
    def size(self):
        return len(self._store)

    @property
    def capacity(self):
        return self._capacity

    @property
    def nbytes(self):
        """Estimated resident bytes (buffered + pending rows x the sampled
        per-row size) — the memory governor's ``shuffling-buffer`` pool."""
        if self._row_nbytes is None:
            return 0
        pending = len(self._pending) if self._pending is not None else 0
        return int((len(self._store) + pending) * self._row_nbytes)

    def shrink_capacity(self, factor=2):
        """Halve (by default) the soft capacity AND the decorrelation
        floor — the governor's *degrade* hook for NON-deterministic
        pipelines (changing the buffer depth changes the draw sequence,
        so deterministic readers never register it). The floor is what
        actually sets steady-state residency (retrieval stops at
        ``min_after_retrieve`` buffered rows), so shrinking the cap alone
        would free nothing; halving both trades shuffle quality for
        bytes, gradually. No buffered row is dropped — the store drains
        under the new floor as the consumer retrieves. Returns True when
        anything moved."""
        factor = max(1, int(factor))
        with self._lock:
            new_min = max(1, self._min_after_retrieve // factor)
            # Never below the CURRENT fill: the loader's feed path calls
            # add_many without a can_add gate (overshoot headroom is the
            # contract), so a cap under the resident rows would turn the
            # next add into a RuntimeError — the rung meant to prevent an
            # OOM kill must not kill the run itself. The per-tick degrade
            # cadence ratchets the cap further down as the store drains
            # below each new floor.
            new_cap = max(new_min + 1, self._capacity // factor,
                          len(self._store))
            if new_cap >= self._capacity and new_min >= self._min_after_retrieve:
                return False
            self._capacity = min(new_cap, self._capacity)
            self._min_after_retrieve = min(new_min, self._min_after_retrieve)
            return True

    def finish(self):
        self._done_adding = True

    # -- checkpoint (petastorm_tpu ISSUE 8: no forced drain) ----------------

    STATE_VERSION = 1

    def track_pending(self):
        """Arm delivered-row tracking: retrieved rows are retained in a
        FIFO until :meth:`mark_delivered` attributes them to a batch the
        consumer actually received, and ``state_dict()`` folds
        still-pending rows into the snapshot. For owners whose draws pass
        through a staging pipeline (``JaxLoader``): without this, rows
        drawn into staged-but-undelivered batches at checkpoint time
        would be in neither the snapshot nor the trainer's hands — lost
        to a finite-epoch resumed run."""
        with self._lock:
            if self._pending is None:
                from collections import deque
                self._pending = deque()

    def mark_delivered(self, n):
        """Release the ``n`` oldest pending rows (their batch reached the
        consumer). Draining past the pending count is a no-op — a padded
        or short final batch over-reports harmlessly."""
        with self._lock:
            if self._pending is None:
                return
            for _ in range(min(int(n), len(self._pending))):
                self._pending.popleft()

    def state_dict(self):
        """Snapshot of the buffered-but-undelivered rows plus the RNG
        state. Rows may be arbitrary Python/numpy values — the snapshot is
        pickle-, not JSON-safe (``JobCheckpointer`` detects that and
        pickles the loader entry transparently). With
        :meth:`track_pending` armed, rows drawn but not yet delivered ride
        along (re-shuffled into the restored buffer)."""
        with self._lock:
            rows = list(self._pending or ()) + list(self._store)
            return {'version': self.STATE_VERSION,
                    'rows': rows,
                    'rng_state': self._rng.bit_generator.state,
                    'field_names': (list(self.field_names)
                                    if self.field_names is not None else None),
                    'size': len(rows)}

    def restore(self, state):
        """Refill from a :meth:`state_dict` snapshot: buffered rows come
        back (delivered ahead of newly-decoded ones per the usual random
        retrieval) and the RNG continues the prior session's draw
        sequence. Call before iteration starts."""
        if state.get('version') != self.STATE_VERSION:
            raise ValueError('Unsupported shuffling-buffer state version '
                             '{!r}'.format(state.get('version')))
        with self._lock:
            if self._store:
                raise RuntimeError('restore() into a non-empty buffer')
            self._store = list(state['rows'])
            self._rng.bit_generator.state = state['rng_state']
            if state.get('field_names'):
                self.field_names = list(state['field_names'])
