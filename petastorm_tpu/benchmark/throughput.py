"""Reader throughput harness.

Parity: reference ``petastorm/benchmark/throughput.py`` — warmup + measure
cycles, samples/sec, RSS and CPU%% via psutil (``:69-91``), python or JAX read
paths (``:94-110``), optional spawn-in-fresh-process for clean memory stats
(``:146-151``).
"""

import time
from collections import namedtuple

import psutil

BenchmarkResult = namedtuple('BenchmarkResult',
                             ['time_mean', 'samples_per_second', 'memory_rss_mb',
                              'cpu_percent'])

_READ_PATHS = ('python', 'jax', 'tensor', 'tf')


def reader_throughput(dataset_url, field_regex=None, warmup_cycles_count=200,
                      measure_cycles_count=1000, pool_type='thread',
                      loaders_count=3, read_method='python',
                      shuffling_queue_size=500, min_after_dequeue=400,
                      spawn_new_process=False, reader_extra_args=None,
                      jax_batch_size=32, shape_policies=None,
                      profile_threads=False):
    """Measure decoded-samples/sec of a reader configuration.

    ``read_method``: 'python' (per-row ``make_reader``), 'jax' (JaxLoader
    batches), 'tensor' (decoded-columnar ``make_tensor_reader`` chunks), or
    'tf' (``make_petastorm_dataset`` tf.data feed — parity with the
    reference's TF read path, ``benchmark/throughput.py:94-110``).
    ``profile_threads`` enables per-worker cProfile, aggregated and printed
    on pool join (parity: reference ``--profile-threads``,
    ``benchmark/throughput.py:190`` / ``thread_pool.py:48-49``).
    """
    if read_method not in _READ_PATHS:
        raise ValueError('read_method must be one of {}'.format(_READ_PATHS))
    if spawn_new_process:
        # Clean-memory measurement in a fresh interpreter
        # (parity: throughput.py:146-151).
        from petastorm_tpu.workers.exec_in_new_process import exec_in_new_process
        import json
        import tempfile

        out_path = tempfile.mktemp(suffix='.json')
        process = exec_in_new_process(
            _run_and_dump, out_path, dataset_url, field_regex, warmup_cycles_count,
            measure_cycles_count, pool_type, loaders_count, read_method,
            shuffling_queue_size, min_after_dequeue, reader_extra_args,
            jax_batch_size, shape_policies, profile_threads)
        process.wait()
        with open(out_path) as f:
            payload = json.load(f)
        return BenchmarkResult(**payload)

    return _measure(dataset_url, field_regex, warmup_cycles_count,
                    measure_cycles_count, pool_type, loaders_count, read_method,
                    shuffling_queue_size, min_after_dequeue, reader_extra_args,
                    jax_batch_size, shape_policies, profile_threads)


def _run_and_dump(out_path, *args):
    import json
    result = _measure(*args)
    with open(out_path, 'w') as f:
        json.dump(result._asdict(), f)


def _measure(dataset_url, field_regex, warmup_cycles_count, measure_cycles_count,
             pool_type, loaders_count, read_method, shuffling_queue_size,
             min_after_dequeue, reader_extra_args, jax_batch_size, shape_policies,
             profile_threads=False):
    from petastorm_tpu import make_reader, make_tensor_reader

    extra = dict(reader_extra_args or {})
    extra.setdefault('num_epochs', None)
    factory = make_tensor_reader if read_method == 'tensor' else make_reader
    reader = factory(dataset_url, schema_fields=field_regex,
                     reader_pool_type=pool_type, workers_count=loaders_count,
                     pool_profiling=profile_threads, **extra)
    process = psutil.Process()
    try:
        if read_method == 'python':
            iterator = iter(reader)
            unit = 1
        elif read_method == 'tensor':
            # Chunk-sized samples; count real rows per chunk.
            iterator = iter(reader)
            unit = None
        elif read_method == 'tf':
            from petastorm_tpu.tf_utils import make_petastorm_dataset
            dataset = make_petastorm_dataset(reader)
            iterator = iter(dataset.as_numpy_iterator())
            unit = 1
        else:
            from petastorm_tpu.jax_loader import JaxLoader
            loader = JaxLoader(reader, jax_batch_size,
                               shuffling_queue_capacity=shuffling_queue_size,
                               min_after_dequeue=min_after_dequeue,
                               shape_policies=shape_policies)
            iterator = iter(loader)
            unit = jax_batch_size

        def consume(target):
            done = 0
            while done < target:
                sample = next(iterator)
                done += len(sample[0]) if unit is None else unit
            return done

        consume(max(1, warmup_cycles_count))
        process.cpu_percent()  # reset the CPU window
        start = time.perf_counter()
        samples = consume(max(1, measure_cycles_count))
        elapsed = time.perf_counter() - start
        cpu = process.cpu_percent()
        rss_mb = process.memory_info().rss / (1024 * 1024)
        return BenchmarkResult(time_mean=elapsed / samples,
                               samples_per_second=samples / elapsed,
                               memory_rss_mb=rss_mb, cpu_percent=cpu)
    finally:
        reader.stop()
        reader.join()
