"""``petastorm-tpu-throughput`` CLI (parity: reference ``petastorm/benchmark/cli.py``)."""

import argparse
import sys


def _parse_args(argv):
    parser = argparse.ArgumentParser(
        description='Measure petastorm_tpu reader throughput on a dataset')
    parser.add_argument('dataset_url', help='e.g. file:///tmp/ds or gs://bucket/ds')
    parser.add_argument('--field-regex', '-f', nargs='+', default=None,
                        help='Read only fields matching these regexes')
    parser.add_argument('--warmup-cycles', '-w', type=int, default=200)
    parser.add_argument('--measure-cycles', '-m', type=int, default=1000)
    parser.add_argument('--pool-type', '-p', choices=['thread', 'process', 'dummy'],
                        default='thread')
    parser.add_argument('--loaders-count', '-l', type=int, default=3)
    parser.add_argument('--read-method', '-d',
                        choices=['python', 'jax', 'tensor', 'tf'],
                        default='python')
    parser.add_argument('--shuffling-queue-size', '-q', type=int, default=500)
    parser.add_argument('--min-after-dequeue', type=int, default=400)
    parser.add_argument('--jax-batch-size', type=int, default=32)
    parser.add_argument('--spawn-new-process', action='store_true',
                        help='Measure in a fresh interpreter for clean memory stats')
    parser.add_argument('--profile-threads', action='store_true',
                        help='Per-worker cProfile, aggregated and printed on '
                             'pool join (thread pool only)')
    return parser.parse_args(argv)


def main(argv=None):
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    from petastorm_tpu.benchmark.throughput import reader_throughput

    result = reader_throughput(
        args.dataset_url, field_regex=args.field_regex,
        warmup_cycles_count=args.warmup_cycles,
        measure_cycles_count=args.measure_cycles,
        pool_type=args.pool_type, loaders_count=args.loaders_count,
        read_method=args.read_method,
        shuffling_queue_size=args.shuffling_queue_size,
        min_after_dequeue=args.min_after_dequeue,
        jax_batch_size=args.jax_batch_size,
        spawn_new_process=args.spawn_new_process,
        profile_threads=args.profile_threads)
    print('samples/sec: {:.2f}  time/sample: {:.6f}s  rss: {:.1f} MB  cpu: {:.1f}%'.format(
        result.samples_per_second, result.time_mean, result.memory_rss_mb,
        result.cpu_percent))
    return 0


if __name__ == '__main__':
    sys.exit(main())
