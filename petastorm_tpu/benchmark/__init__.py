"""Throughput benchmarking harness (parity: reference ``petastorm/benchmark/``)."""

from petastorm_tpu.benchmark.throughput import (BenchmarkResult,  # noqa: F401
                                                reader_throughput)
