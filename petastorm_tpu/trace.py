"""Input-pipeline tracing: chrome://tracing timelines for the loader.

The reference's observability stops at per-thread cProfile aggregates
(SURVEY §5.1 — "No distributed tracing"). This records *spans* — named,
timestamped durations per thread — and exports the Chrome trace-event JSON
that chrome://tracing / Perfetto render as a timeline, which is how you SEE
an input stall: the consumer's ``wait`` spans grow exactly when the staging
thread's ``device_put`` spans (or the workers' decode) stretch.

Usage::

    tracer = Tracer()
    with make_tensor_reader(url) as reader:
        with JaxLoader(reader, 1024, tracer=tracer) as loader:
            for batch in loader: ...
    tracer.export_chrome_trace('/tmp/input_pipeline.json')

Pure stdlib, thread-safe, bounded (drops oldest beyond ``max_events``).
"""

import json
import threading
import time
from collections import deque
from contextlib import contextmanager


class Tracer(object):
    """Thread-safe span recorder with Chrome trace-event export."""

    def __init__(self, max_events=100000):
        # deque(maxlen=...): O(1) drop-oldest — a full list.pop(0) buffer
        # would shift max_events pointers inside the hot-path lock.
        self._events = deque(maxlen=max_events)
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()

    @contextmanager
    def span(self, name, cat='pipeline'):
        start = time.perf_counter()
        try:
            yield
        finally:
            end = time.perf_counter()
            with self._lock:
                self._events.append({
                    'name': name, 'cat': cat, 'ph': 'X',
                    'ts': (start - self._t0) * 1e6,      # microseconds
                    'dur': (end - start) * 1e6,
                    'pid': 0, 'tid': threading.get_ident(),
                })

    def instant(self, name, cat='pipeline', args=None):
        """A zero-duration marker event. ``args`` (a JSON-safe dict)
        renders in the trace viewer's detail pane — the autotuner attaches
        each decision's knob changes so the timeline shows *what* changed
        at the marker, not just that something did."""
        event = {
            'name': name, 'cat': cat, 'ph': 'i', 's': 't',
            'ts': (time.perf_counter() - self._t0) * 1e6,
            'pid': 0, 'tid': threading.get_ident(),
        }
        if args:
            event['args'] = dict(args)
        with self._lock:
            self._events.append(event)

    def counter(self, name, value, cat='pipeline'):
        """A counter-track sample (chrome trace 'C' event): renders as a
        filled area chart. Used by the staging engine for arena-pool
        occupancy and the in-flight transfer window, so a timeline shows
        backpressure (pool pinned at 0 free) next to the spans it stalls."""
        with self._lock:
            self._events.append({
                'name': name, 'cat': cat, 'ph': 'C',
                'ts': (time.perf_counter() - self._t0) * 1e6,
                'pid': 0, 'tid': threading.get_ident(),
                'args': {name: value},
            })

    @property
    def events(self):
        with self._lock:
            return list(self._events)

    def summary(self):
        """Total seconds per span name (quick text view of the timeline)."""
        totals = {}
        for e in self.events:
            if e['ph'] == 'X':
                totals[e['name']] = totals.get(e['name'], 0.0) + e['dur'] / 1e6
        return {k: round(v, 4) for k, v in sorted(totals.items())}

    def export_chrome_trace(self, path):
        """Write the Chrome trace-event JSON (open in chrome://tracing)."""
        with open(path, 'w') as f:
            json.dump({'traceEvents': self.events,
                       'displayTimeUnit': 'ms'}, f)
        return path


_global_tracer = None


def set_global_tracer(tracer):
    """Install a process-wide tracer that instrumentation points with no
    Tracer argument (e.g. fault-injection sites in ``faults.py``) report to.
    Pass ``None`` to reset. Returns the previous global tracer."""
    global _global_tracer
    previous = _global_tracer
    _global_tracer = tracer
    return previous


def get_global_tracer():
    """The tracer installed by :func:`set_global_tracer`, or a shared
    :class:`NullTracer` when none is set (call sites never branch)."""
    return _global_tracer if _global_tracer is not None else _NULL_TRACER


class _NullSpan(object):
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class NullTracer(object):
    """No-op stand-in so call sites never branch."""

    _SPAN = _NullSpan()

    def span(self, name, cat='pipeline'):
        return self._SPAN

    def instant(self, name, cat='pipeline', args=None):
        pass

    def counter(self, name, value, cat='pipeline'):
        pass


_NULL_TRACER = NullTracer()
