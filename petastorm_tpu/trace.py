"""Input-pipeline tracing: chrome://tracing timelines across processes.

The reference's observability stops at per-thread cProfile aggregates
(SURVEY §5.1 — "No distributed tracing"). This records *spans* — named,
timestamped durations per thread — and exports the Chrome trace-event JSON
that chrome://tracing / Perfetto render as a timeline, which is how you SEE
an input stall: the consumer's ``wait`` spans grow exactly when the staging
thread's ``device_put`` spans (or the workers' decode) stretch.

Cross-process story (the piece a single in-memory tracer cannot give you —
worker-subprocess decode dominates the cold path, PROFILE_r05): every
:class:`Tracer` can additionally *spill* its events to a per-process JSONL
sidecar file. Setting the ``PETASTORM_TPU_TRACE_DIR`` environment variable
arms spill for every tracer built afterwards — including the ones the
process-pool worker bootstraps install (workers are spawned and inherit the
environment, the same activation channel ``faults.py`` uses). Sidecars are
append-only, line-buffered, and bounded: a worker that dies mid-write
leaves at most one torn trailing line, which :meth:`Tracer.
merge_process_files` (and the ``python -m petastorm_tpu.tools.trace_merge``
CLI) skip. After a run, merging folds every process's events — shifted
onto the parent's timebase via each sidecar's wall-clock anchor — into one
timeline where worker ``decode`` tracks (real pids) sit next to the
loader's ``assemble``/``stage``/``wait`` tracks.

Usage::

    os.environ['PETASTORM_TPU_TRACE_DIR'] = '/tmp/pst-trace'  # before reader
    tracer = Tracer()
    with make_tensor_reader(url, reader_pool_type='process') as reader:
        with JaxLoader(reader, 1024, tracer=tracer) as loader:
            for batch in loader: ...
    tracer.merge_process_files()
    tracer.export_chrome_trace('/tmp/input_pipeline.json')

Pure stdlib, thread-safe, bounded (drops oldest beyond ``max_events``;
sidecars stop at ``spill_max_events`` lines).
"""

import glob
import json
import logging
import os
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager

logger = logging.getLogger(__name__)

#: Directory that arms per-process sidecar spill for every Tracer built
#: while it is set (inherited by spawned worker processes).
TRACE_DIR_ENV = 'PETASTORM_TPU_TRACE_DIR'

_SIDECAR_GLOB = 'trace-*.jsonl'
_HEADER_KEY = '__pst_trace_sidecar__'


class Tracer(object):
    """Thread-safe span recorder with Chrome trace-event export.

    :param max_events: in-memory ring bound (oldest dropped past it).
    :param spill_dir: directory for this process's JSONL sidecar file.
        ``None`` (default) consults ``PETASTORM_TPU_TRACE_DIR``; ``False``
        disables spill even when the env var is set.
    :param role: human label for this process's track in merged timelines
        (``'main'`` for the default in-process tracer; worker bootstraps
        pass ``'worker-<id>'``).
    :param spill_max_events: sidecar line bound (defaults to
        ``max_events``); past it events keep landing in memory but the
        file stops growing (a truncation marker records the drop count).
    """

    def __init__(self, max_events=100000, spill_dir=None, role=None,
                 spill_max_events=None):
        # deque(maxlen=...): O(1) drop-oldest — a full list.pop(0) buffer
        # would shift max_events pointers inside the hot-path lock.
        self._events = deque(maxlen=max_events)
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        # Wall-clock anchor of t0: what lets merge align sidecars recorded
        # by other processes (perf_counter is process-local) onto one
        # timeline. Same-host clocks, so the alignment is ~exact.
        self._wall0 = time.time()
        self._pid = os.getpid()
        self.role = role or 'main'
        if spill_dir is None:
            spill_dir = os.environ.get(TRACE_DIR_ENV) or None
        elif spill_dir is False:
            spill_dir = None
        self._spill_dir = spill_dir
        self._spill_file = None
        self._spill_path = None
        self._spill_count = 0
        self._spill_dropped = 0
        self._spill_failed = False
        self._spill_max = (int(spill_max_events)
                           if spill_max_events is not None else max_events)
        self._merged = []            # events folded in from sidecar files
        self._roles = {}             # pid -> role (merged sidecar headers)

    # -- recording ---------------------------------------------------------

    @contextmanager
    def span(self, name, cat='pipeline'):
        start = time.perf_counter()
        try:
            yield
        finally:
            end = time.perf_counter()
            self._append({
                'name': name, 'cat': cat, 'ph': 'X',
                'ts': (start - self._t0) * 1e6,      # microseconds
                'dur': (end - start) * 1e6,
                'pid': self._pid, 'tid': threading.get_ident(),
            })

    def instant(self, name, cat='pipeline', args=None):
        """A zero-duration marker event. ``args`` (a JSON-safe dict)
        renders in the trace viewer's detail pane — the autotuner attaches
        each decision's knob changes so the timeline shows *what* changed
        at the marker, not just that something did."""
        event = {
            'name': name, 'cat': cat, 'ph': 'i', 's': 't',
            'ts': (time.perf_counter() - self._t0) * 1e6,
            'pid': self._pid, 'tid': threading.get_ident(),
        }
        if args:
            event['args'] = dict(args)
        self._append(event)

    def counter(self, name, value, cat='pipeline'):
        """A counter-track sample (chrome trace 'C' event): renders as a
        filled area chart. Used by the staging engine for arena-pool
        occupancy and the in-flight transfer window, so a timeline shows
        backpressure (pool pinned at 0 free) next to the spans it stalls."""
        self._append({
            'name': name, 'cat': cat, 'ph': 'C',
            'ts': (time.perf_counter() - self._t0) * 1e6,
            'pid': self._pid, 'tid': threading.get_ident(),
            'args': {name: value},
        })

    def _append(self, event):
        with self._lock:
            self._events.append(event)
            if self._spill_dir is not None:
                self._spill(event)

    # -- sidecar spill -----------------------------------------------------

    def _spill(self, event):
        """Append one event line to the sidecar (lock held). Line-buffered
        so a killed process leaves whole lines plus at most one torn tail;
        bounded so a long run cannot fill the disk."""
        if self._spill_failed:
            return
        if self._spill_file is None and not self._open_spill():
            return
        if self._spill_count >= self._spill_max:
            if self._spill_dropped == 0:
                try:
                    self._spill_file.write(json.dumps(
                        {'name': 'trace-spill-truncated', 'cat': 'trace',
                         'ph': 'i', 's': 't',
                         'ts': event.get('ts', 0.0),
                         'pid': self._pid,
                         'tid': threading.get_ident()}) + '\n')
                except OSError:
                    self._spill_failed = True
            self._spill_dropped += 1
            return
        try:
            self._spill_file.write(json.dumps(event) + '\n')
            self._spill_count += 1
        except (OSError, TypeError, ValueError):
            # Disk gone or an un-JSONable args payload: tracing is
            # advisory — never let it take the pipeline down.
            logger.warning('trace sidecar write failed; disabling spill',
                           exc_info=True)
            self._spill_failed = True

    def _open_spill(self):
        try:
            os.makedirs(self._spill_dir, exist_ok=True)
            path = os.path.join(self._spill_dir, 'trace-{}-{}.jsonl'.format(
                self._pid, uuid.uuid4().hex[:8]))
            # buffering=1: one flush per line — crash-tolerant (complete
            # lines survive a SIGKILL) at row-group event granularity.
            self._spill_file = open(path, 'w', buffering=1)
            self._spill_path = path
            self._spill_file.write(json.dumps(
                {_HEADER_KEY: 1, 'pid': self._pid, 'role': self.role,
                 'wall0': self._wall0}) + '\n')
            return True
        except OSError:
            logger.warning('cannot open trace sidecar in %r; disabling spill',
                           self._spill_dir, exc_info=True)
            self._spill_failed = True
            return False

    @property
    def spill_path(self):
        """This tracer's sidecar file (``None`` when spill is off or no
        event has been recorded yet)."""
        with self._lock:
            return self._spill_path

    def close(self):
        """Flush + close the sidecar file (worker bootstraps call this on
        shutdown; safe to call repeatedly, and spill-less tracers no-op)."""
        with self._lock:
            f, self._spill_file = self._spill_file, None
        if f is not None:
            try:
                f.flush()
                f.close()
            except OSError:  # pragma: no cover - disk already gone
                pass

    # -- merge -------------------------------------------------------------

    @property
    def wall0(self):
        """Wall-clock anchor of this tracer's t0 (the merge timebase)."""
        return self._wall0

    def merge_process_files(self, spill_dir=None, since_wall0=None):
        """Fold every sidecar file under ``spill_dir`` (default: this
        tracer's spill dir, else ``PETASTORM_TPU_TRACE_DIR``) into this
        tracer's timeline. Each file's events are shifted by its
        wall-clock anchor so worker tracks align with local spans; this
        tracer's own sidecar is skipped (its events are already in
        memory). Torn/corrupt lines (a worker killed mid-write) are
        skipped, not fatal. Returns the number of files merged.

        The directory is NOT run-scoped: sidecars from an earlier run
        left in the same directory merge too. Use a fresh directory per
        run (``tempfile.mkdtemp``), or pass ``since_wall0`` (e.g. this
        tracer's :attr:`wall0`, captured before the pipeline was built)
        to skip sidecar files whose anchor predates the run."""
        directory = spill_dir or self._spill_dir \
            or os.environ.get(TRACE_DIR_ENV)
        if not directory:
            raise ValueError('no spill directory: pass spill_dir or set '
                             '{}'.format(TRACE_DIR_ENV))
        own = self.spill_path
        merged_files = 0
        for path in sorted(glob.glob(os.path.join(directory, _SIDECAR_GLOB))):
            if own is not None and os.path.abspath(path) == os.path.abspath(own):
                continue
            header, events = read_sidecar_file(path)
            if header is None and not events:
                continue
            if since_wall0 is not None and header is not None \
                    and header.get('wall0', since_wall0) < since_wall0:
                continue        # a previous run's leftover sidecar
            offset_us = 0.0
            pid = None
            if header is not None:
                pid = header.get('pid')
                offset_us = (header.get('wall0', self._wall0)
                             - self._wall0) * 1e6
                if pid is not None and header.get('role'):
                    self._roles[pid] = header['role']
            adjusted = []
            for event in events:
                event = dict(event)
                event['ts'] = event.get('ts', 0.0) + offset_us
                if 'pid' not in event and pid is not None:
                    event['pid'] = pid
                adjusted.append(event)
            with self._lock:
                self._merged.extend(adjusted)
            merged_files += 1
        return merged_files

    # -- inspection / export -----------------------------------------------

    @property
    def events(self):
        with self._lock:
            return list(self._events) + list(self._merged)

    def summary(self):
        """Per-span-name latency digest — the quick-look view that makes a
        trace useful without opening Perfetto::

            {name: {'count': n, 'total_s': t, 'p50_s': m, 'p99_s': p}}
        """
        durations = {}
        for e in self.events:
            if e.get('ph') == 'X':
                durations.setdefault(e['name'], []).append(
                    e.get('dur', 0.0) / 1e6)
        out = {}
        for name, values in sorted(durations.items()):
            values.sort()
            out[name] = {'count': len(values),
                         'total_s': round(sum(values), 4),
                         'p50_s': round(_percentile(values, 0.50), 6),
                         'p99_s': round(_percentile(values, 0.99), 6)}
        return out

    def export_chrome_trace(self, path):
        """Write the Chrome trace-event JSON (open in chrome://tracing).

        Atomic (tmp file + rename): a watchdog dumping a trace while the
        process crashes — or two dumps racing — can never leave a torn
        JSON at ``path``. Distinct pids get ``process_name`` metadata so
        merged multi-process timelines render labeled tracks."""
        events = self.events
        roles = dict(self._roles)
        roles.setdefault(self._pid, self.role)
        metadata = []
        for pid in sorted({e.get('pid') for e in events if 'pid' in e}):
            metadata.append({
                'name': 'process_name', 'ph': 'M', 'pid': pid,
                'args': {'name': '{} (pid {})'.format(
                    roles.get(pid, 'process'), pid)}})
        # pid alone is not unique enough: two threads exporting to the
        # same path (periodic export racing a watchdog dump) must not
        # share — and truncate — one tmp file.
        tmp = '{}.tmp.{}.{}'.format(path, os.getpid(), uuid.uuid4().hex[:8])
        with open(tmp, 'w') as f:
            json.dump({'traceEvents': metadata + events,
                       'displayTimeUnit': 'ms'}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path


def _percentile(sorted_values, q):
    """Nearest-rank percentile of an ascending list (empty -> 0)."""
    if not sorted_values:
        return 0.0
    index = min(len(sorted_values) - 1,
                max(0, int(round(q * (len(sorted_values) - 1)))))
    return sorted_values[index]


def read_sidecar_file(path):
    """``(header_or_None, [events])`` from one sidecar JSONL file.

    Torn trailing lines and corrupt lines (a worker SIGKILLed mid-write)
    are skipped — the file stays readable even if its writer died."""
    header = None
    events = []
    try:
        with open(path, 'r') as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    record = json.loads(line)
                except ValueError:
                    continue        # torn/corrupt line: skip, keep reading
                if not isinstance(record, dict):
                    continue
                if record.get(_HEADER_KEY):
                    header = record
                else:
                    events.append(record)
    except OSError:
        logger.warning('cannot read trace sidecar %r', path, exc_info=True)
    return header, events


def install_worker_tracer(role=None):
    """Worker-bootstrap hook: when ``PETASTORM_TPU_TRACE_DIR`` is set
    (inherited from the parent through the spawn environment), build a
    spilling tracer, install it as this process's global tracer, and
    return it (the bootstrap ``close()``\\ s it on shutdown). Returns
    ``None`` when tracing is unarmed — instrumentation points then hit
    the shared :class:`NullTracer` at near-zero cost."""
    if not os.environ.get(TRACE_DIR_ENV):
        return None
    tracer = Tracer(role=role or 'worker-{}'.format(os.getpid()))
    set_global_tracer(tracer)
    return tracer


_global_tracer = None


def set_global_tracer(tracer):
    """Install a process-wide tracer that instrumentation points with no
    Tracer argument (e.g. fault-injection sites in ``faults.py`` and the
    worker-side read/decode/handoff spans) report to. Pass ``None`` to
    reset. Returns the previous global tracer."""
    global _global_tracer
    previous = _global_tracer
    _global_tracer = tracer
    return previous


def get_global_tracer():
    """The tracer installed by :func:`set_global_tracer`, or a shared
    :class:`NullTracer` when none is set (call sites never branch)."""
    return _global_tracer if _global_tracer is not None else _NULL_TRACER


class _NullSpan(object):
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class NullTracer(object):
    """No-op stand-in so call sites never branch."""

    _SPAN = _NullSpan()

    def span(self, name, cat='pipeline'):
        return self._SPAN

    def instant(self, name, cat='pipeline', args=None):
        pass

    def counter(self, name, value, cat='pipeline'):
        pass

    def close(self):
        pass


_NULL_TRACER = NullTracer()
