"""Stall flight recorder: post-mortem dumps that need no live process.

The watchdog (``health.py``) already *diagnoses* a stall — classification,
beat table, probe snapshots, all-thread stacks — but the evidence lived
only inside the dying process: by the time a human looked, the trace ring
and the metric counters were gone with it. The flight recorder keeps a
bounded ring of recent trace events (the :class:`~petastorm_tpu.trace.
Tracer`'s own ring) plus periodic metric samples, and on watchdog
escalation (the moment a :class:`~petastorm_tpu.errors.PipelineStallError`
is minted) dumps everything to a timestamped directory::

    <base_dir>/pst-flight-20260803-141557-dispatch-hung-ab12cd34/
        trace.json        # chrome://tracing timeline of the event ring
        metrics.prom      # Prometheus text exposition at dump time
        metrics_ring.json # recent periodic registry samples (wall-clocked)
        diagnosis.json    # classification, stage, detail, beats, probes
        stacks.txt        # the all-thread stack dump
        lineage.json      # every live provenance ring (petastorm_tpu.
                          # lineage): the exact rows in flight at the stall

Arm it process-wide by pointing the ``PETASTORM_TPU_FLIGHT_RECORDER``
environment variable at a directory (the watchdog-owning Reader/JaxLoader
builds one automatically), or pass a :class:`FlightRecorder` to
:class:`~petastorm_tpu.health.HealthMonitor` directly. Dumping is
best-effort by construction: a recorder failure must never worsen the
stall it is documenting.
"""

import json
import logging
import os
import threading
import time
import uuid
from collections import deque

logger = logging.getLogger(__name__)

#: Directory that arms a flight recorder for every supervised pipeline
#: built while it is set.
ENV_VAR = 'PETASTORM_TPU_FLIGHT_RECORDER'

DUMP_DIR_PREFIX = 'pst-flight-'


class FlightRecorder(object):
    """Bounded trace/metrics ring + timestamped post-mortem dumps.

    :param base_dir: where dump directories are created.
    :param tracer: the pipeline's :class:`~petastorm_tpu.trace.Tracer`
        (its bounded event ring IS the trace flight ring); a
        ``NullTracer`` yields an empty ``trace.json``.
    :param registry: the :class:`~petastorm_tpu.metrics.MetricsRegistry`
        to snapshot (default: the process-wide registry).
    :param metric_ring: periodic samples retained (oldest dropped).
    :param sample_min_interval_s: :meth:`sample` throttle — the watchdog
        calls it every supervision tick, which can be sub-100ms in tests.
    """

    def __init__(self, base_dir, tracer=None, registry=None, metric_ring=256,
                 sample_min_interval_s=0.25):
        self._base_dir = base_dir
        self._tracer = tracer
        if registry is None:
            from petastorm_tpu import metrics
            registry = metrics.get_registry()
        self._registry = registry
        self._lock = threading.Lock()
        self._samples = deque(maxlen=metric_ring)
        self._sample_min_interval_s = float(sample_min_interval_s)
        self._last_sample_t = 0.0
        self.dumps = []

    @property
    def base_dir(self):
        return self._base_dir

    def sample(self):
        """Append one wall-clocked registry snapshot to the metric ring
        (throttled; the watchdog calls this every check pass). Returns
        True when a sample was taken."""
        now = time.monotonic()
        with self._lock:
            if now - self._last_sample_t < self._sample_min_interval_s:
                return False
            self._last_sample_t = now
        try:
            snapshot = self._registry.collect()
        except Exception:  # noqa: BLE001 - recording must not hurt the pipeline
            logger.debug('flight recorder sample failed', exc_info=True)
            return False
        with self._lock:
            self._samples.append({'wall_time': time.time(),
                                  'metrics': snapshot})
        return True

    def dump(self, diagnosis=None, reason='stall'):
        """Write the rings + ``diagnosis`` to a fresh timestamped dump
        directory; returns its path (``None`` if even the mkdir failed —
        dumping is best-effort, a recorder error must never mask the
        stall it documents)."""
        stamp = time.strftime('%Y%m%d-%H%M%S')
        safe_reason = ''.join(c if c.isalnum() or c == '-' else '-'
                              for c in str(reason))[:48] or 'stall'
        path = os.path.join(self._base_dir, '{}{}-{}-{}'.format(
            DUMP_DIR_PREFIX, stamp, safe_reason, uuid.uuid4().hex[:8]))
        try:
            os.makedirs(path, exist_ok=True)
        except OSError:
            logger.warning('flight recorder cannot create dump dir under %r',
                           self._base_dir, exc_info=True)
            return None
        self._write_trace(os.path.join(path, 'trace.json'))
        self._write_metrics(path)
        self._write_diagnosis(path, diagnosis)
        self._write_lineage(path)
        with self._lock:
            self.dumps.append(path)
        logger.warning('flight recorder dumped stall evidence to %s', path)
        return path

    # -- pieces (each best-effort, isolated) -------------------------------

    def _write_trace(self, path):
        try:
            export = getattr(self._tracer, 'export_chrome_trace', None)
            if export is not None:
                export(path)
            else:   # NullTracer / no tracer: an empty-but-valid timeline
                with open(path, 'w') as f:
                    json.dump({'traceEvents': [], 'displayTimeUnit': 'ms'}, f)
        except Exception:  # noqa: BLE001
            logger.debug('flight recorder trace dump failed', exc_info=True)

    def _write_metrics(self, dump_dir):
        try:
            self._registry.write_textfile(
                os.path.join(dump_dir, 'metrics.prom'))
        except Exception:  # noqa: BLE001
            logger.debug('flight recorder metrics dump failed', exc_info=True)
        try:
            with self._lock:
                samples = list(self._samples)
            with open(os.path.join(dump_dir, 'metrics_ring.json'), 'w') as f:
                json.dump(samples, f, default=repr)
        except Exception:  # noqa: BLE001
            logger.debug('flight recorder ring dump failed', exc_info=True)

    def _write_lineage(self, dump_dir):
        """Every live tracker's provenance ring (the last N batch records,
        with their reader contexts) — what names the exact rows that were
        in flight when the pipeline stalled. Trackers register themselves
        process-wide (``lineage.live_rings``), so no construction-order
        coupling with the watchdog; an unarmed pipeline writes ``[]``."""
        try:
            from petastorm_tpu import lineage
            rings = lineage.live_rings()
            with open(os.path.join(dump_dir, 'lineage.json'), 'w') as f:
                json.dump(rings, f, default=repr)
        except Exception:  # noqa: BLE001
            logger.debug('flight recorder lineage dump failed', exc_info=True)

    def _write_diagnosis(self, dump_dir, diagnosis):
        if diagnosis is None:
            return
        try:
            stacks = diagnosis.get('stacks') if hasattr(diagnosis, 'get') \
                else None
            summary = {k: v for k, v in dict(diagnosis).items()
                       if k != 'stacks'}
            with open(os.path.join(dump_dir, 'diagnosis.json'), 'w') as f:
                # default=repr: probe snapshots may carry numpy scalars or
                # exception objects; a post-mortem wants them legible, not
                # a serializer crash.
                json.dump(summary, f, default=repr, indent=1)
            if stacks:
                with open(os.path.join(dump_dir, 'stacks.txt'), 'w') as f:
                    f.write(stacks)
        except Exception:  # noqa: BLE001
            logger.debug('flight recorder diagnosis dump failed',
                         exc_info=True)


def maybe_from_env(tracer=None, registry=None):
    """A :class:`FlightRecorder` when ``PETASTORM_TPU_FLIGHT_RECORDER``
    names a directory, else ``None`` (the Reader/JaxLoader watchdog
    wiring calls this so supervised pipelines record automatically)."""
    base_dir = os.environ.get(ENV_VAR, '').strip()
    if not base_dir:
        return None
    return FlightRecorder(base_dir, tracer=tracer, registry=registry)
