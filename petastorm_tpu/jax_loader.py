"""JAX loader: the TPU-native framework adapter (the point of the project).

The reference feeds TF via ``tf_utils.py`` and torch via ``pytorch.py``
(SURVEY.md §2.6). This module is their TPU equivalent, designed per
SURVEY.md §7.6:

  * fixed-size batch re-chunking of row-group output (the reference's
    ``BatchingTableQueue`` idea, ``pyarrow_helpers/batching_table_queue.py``),
  * optional seeded row-level shuffling (``RandomShufflingBuffer``),
  * dtype sanitization to TPU-supported dtypes (cf. ``pytorch.py:36-66`` /
    ``tf_utils.py:58-97``),
  * ragged-field shape policies (pad/crop) because XLA needs static shapes —
    a decision the reference never had to make (SURVEY.md §7 "Hard parts"),
  * device staging onto a ``Mesh``-sharded layout (each pod host
    contributes its disjoint reader shard — ``make_pod_reader`` maps
    ``cur_shard`` to ``jax.process_index()``): per-device sharded
    assembly by default — zero-copy batch-dim sub-slices dispatched on
    one overlapped ``device_put`` stream per addressable device and
    stitched with ``jax.make_array_from_single_device_arrays`` — with
    ``jax.make_array_from_process_local_data`` as the one-shot fallback
    for shardings that split non-batch dims; plain ``device_put``
    single-chip,
  * a pipelined staging engine (``staging.py``): batch assembly into
    recycled host arenas overlapped with a bounded window of in-flight
    ``device_put``s, so collate of batch N+1 hides under the transfer of
    batch N and host->HBM transfer of batch N+1 hides under XLA step N.
"""

import contextlib
import logging
import queue
import threading
import time
import warnings
from collections import deque

import numpy as np

from petastorm_tpu.utils import cached_namedtuple

logger = logging.getLogger(__name__)

_END = object()


def _never_ready():
    """Fallback readiness probe for array types without ``is_ready`` —
    the engine then waits via the blocking ``ready_fn`` instead."""
    return False

# Fields smaller than this stage as one put even under stage_chunks>1:
# chunking a 1KB label column costs k round trips for nothing.
_STAGE_CHUNK_MIN_BYTES = 4 << 20


# --------------------------------------------------------------------------
# shape policies
# --------------------------------------------------------------------------

class ShapePolicy(object):
    """How to give a ragged field a static shape."""

    def apply(self, array):
        raise NotImplementedError


class PadTo(ShapePolicy):
    """Pad (and clip) every sample to ``target_shape`` with ``fill_value``."""

    def __init__(self, target_shape, fill_value=0):
        self.target_shape = tuple(target_shape)
        self.fill_value = fill_value

    def apply(self, array):
        array = np.asarray(array)
        if array.shape == self.target_shape:
            return array
        out = np.full(self.target_shape, self.fill_value, dtype=array.dtype)
        slices = tuple(slice(0, min(a, t)) for a, t in zip(array.shape, self.target_shape))
        out[slices] = array[slices]
        return out


class CropTo(ShapePolicy):
    """Center-crop every sample to ``target_shape`` (must fit)."""

    def __init__(self, target_shape):
        self.target_shape = tuple(target_shape)

    def apply(self, array):
        array = np.asarray(array)
        if array.shape == self.target_shape:
            return array
        starts = [(a - t) // 2 for a, t in zip(array.shape, self.target_shape)]
        if any(s < 0 for s in starts):
            raise ValueError('CropTo{}: sample shape {} too small'.format(
                self.target_shape, array.shape))
        slices = tuple(slice(s, s + t) for s, t in zip(starts, self.target_shape))
        return array[slices]


# --------------------------------------------------------------------------
# dtype sanitization
# --------------------------------------------------------------------------

def _sanitize_dtype(np_dtype, x64=False):
    """Map a numpy dtype to its TPU-friendly dtype (or None if unsupported).

    Parity role: reference ``pytorch.py:36-66`` / ``tf_utils.py:58-97``.
    """
    np_dtype = np.dtype(np_dtype)
    if np_dtype.kind in ('O', 'U', 'S'):
        return None
    if np_dtype.kind == 'M':
        # datetime64 -> ns-epoch int64. Without x64 the values cannot be
        # represented (int32 would wrap) — treat as unsupported rather than
        # silently corrupt.
        return np.dtype('int64') if x64 else None
    if not x64:
        if np_dtype == np.float64:
            return np.dtype('float32')
        if np_dtype == np.int64:
            return np.dtype('int32')
        if np_dtype == np.uint64:
            return np.dtype('uint32')
    return np_dtype


def _sanitize_array(array, x64=False):
    array = np.asarray(array)
    target = _sanitize_dtype(array.dtype, x64)
    if target is None:
        return None
    if array.dtype.kind == 'M':
        array = array.astype('datetime64[ns]').astype(np.int64)
    return np.ascontiguousarray(array.astype(target, copy=False))


# --------------------------------------------------------------------------
# host-side batch assembly (no jax dependency — independently testable)
# --------------------------------------------------------------------------

#: Optional on-device image decode op (``register_device_decode``): when a
#: backend exposes a real JPEG->tensor op inside XLA, registering it here
#: makes the loader ship raw bytes all the way to the device. No such op
#: exists on stock CPU/TPU jax — the staging step then host-decodes via
#: the native batched codec (the documented fallback), which still moves
#: decode OFF the worker pool and NEXT to the transfer.
_DEVICE_DECODE_HOOK = None


def register_device_decode(fn):
    """Register ``fn(encoded_column, shape, dtype) -> device array`` as the
    on-device image decode op (``encoded_column`` is an object ndarray of
    JPEG/PNG bytes; the result must be a ``[N, *shape]`` device array).
    Pass ``None`` to clear. Returns the previously registered hook."""
    global _DEVICE_DECODE_HOOK
    previous, _DEVICE_DECODE_HOOK = _DEVICE_DECODE_HOOK, fn
    return previous


def _build_shuffling_buffer(capacity, min_after_dequeue, seed):
    """The one shuffling-buffer construction shared by ``JaxLoader`` and
    standalone ``iter_numpy_batches`` callers — same decorrelation floor
    default (4/5 of capacity) and add-overshoot headroom either way."""
    from petastorm_tpu.shuffling_buffer import RandomShufflingBuffer
    if min_after_dequeue is None:
        min_after_dequeue = capacity * 4 // 5
    return RandomShufflingBuffer(capacity, min_after_dequeue, seed=seed,
                                 extra_capacity=100000)


def iter_numpy_batches(reader, batch_size, shape_policies=None,
                       shuffling_queue_capacity=0, min_after_dequeue=None,
                       seed=None, last_batch='drop', x64=False,
                       strict_fields=False, batch_buffers=None, views_ok=True,
                       lineage=None, shuffler=None, commit_rows=None,
                       raw_fields=None):
    """Yield dicts of numpy arrays with exact leading dim ``batch_size``.

    Works over both row readers (``make_reader``) and batch readers
    (``make_batch_reader``); re-chunks row-group-sized output into fixed
    batches. ``last_batch``: 'drop' | 'pad' (repeat-pad the final partial
    batch) | 'partial' (yield it short). ``strict_fields=True`` raises
    instead of warn-and-drop when a selected field cannot batch (e.g. a
    nullable-declared field that is never actually null) — pass
    ``schema_fields`` excluding it, or a TransformSpec redeclaring it
    non-nullable, to proceed.

    ``batch_buffers`` (the staging engine's arena hookup): a callable
    ``spec -> dict of arrays or None`` (``spec``: {name: (shape, dtype)})
    providing preallocated output buffers; batches are then collated into
    those buffers in place (``np.copyto``/``out=``) instead of allocating
    with ``np.stack``/``np.concatenate``, and the provider pairs each
    yielded batch with its backing arena (``ArenaPool.claim_pending``).
    ``views_ok=False`` additionally forces batches that would be zero-copy
    chunk views into the buffers — transfer backends that don't alias host
    memory prefer stable recycled buffers over views.

    ``lineage`` (a :class:`petastorm_tpu.lineage.LineageCollector`): batch
    provenance capture — each arriving chunk's segment metadata is pushed
    and each emitted batch pops the FIFO spans composing it (exact without
    a shuffling buffer; a shuffling buffer flags records inexact).

    ``shuffler``: a pre-built (possibly checkpoint-restored)
    :class:`~petastorm_tpu.shuffling_buffer.RandomShufflingBuffer` to use
    instead of constructing one from ``shuffling_queue_capacity`` — the
    JaxLoader owns its buffer this way so ``state_dict()`` can snapshot
    buffered-but-undelivered rows.
    """
    if last_batch not in ('drop', 'pad', 'partial'):
        raise ValueError("last_batch must be drop|pad|partial, got {!r}".format(last_batch))
    shape_policies = dict(shape_policies or {})
    raw_fields = tuple(raw_fields
                       if raw_fields is not None
                       else getattr(reader, 'raw_image_fields', ()) or ())

    field_names = None
    dropped = set()
    columns = {}
    count = 0

    if shuffler is None and shuffling_queue_capacity \
            and shuffling_queue_capacity > 0:
        shuffler = _build_shuffling_buffer(shuffling_queue_capacity,
                                           min_after_dequeue, seed)
    if shuffler is not None and lineage is not None:
        # Row-level shuffling breaks the FIFO chunk->batch mapping:
        # records still name the contributing chunks, but row spans
        # are no longer exact (replay refuses such records).
        lineage.mark_inexact()

    def _is_tensor_like(probe, name):
        """True if a sample value can become a TPU tensor (possibly via policy)."""
        if probe is None:
            # Field with None values cannot batch; dropped with a warning.
            # (A later None in a kept field raises a clear error in
            # _stack_column.) Fill nullables via TransformSpec to keep them.
            return False
        arr = np.asarray(probe)
        if arr.dtype.kind not in ('O', 'U', 'S'):
            return True
        # Object values may still be numeric ndarrays (ragged) — keep when a
        # shape policy exists, or when the payload itself is numeric.
        if isinstance(probe, np.ndarray) and probe.dtype.kind not in ('O', 'U', 'S'):
            return True
        return name in shape_policies

    schema = getattr(reader, 'transformed_schema', None)

    def _declared_nullable(name):
        # Row readers carry a deliberate Unischema: its nullable flag is
        # authoritative (batch readers infer schemas where arrow marks nearly
        # everything nullable, so probing is used there instead). A
        # TransformSpec that fills nulls can redeclare the field with
        # nullable=False via edit_fields to keep it.
        return (not reader.batched_output and schema is not None
                and name in schema.fields and schema.fields[name].nullable)

    def select_fields(sample):
        nonlocal field_names
        names = []
        for name in sample._fields:
            value = getattr(sample, name)
            if reader.batched_output:
                column = np.asarray(value)
                probe = column[0] if (column.dtype.kind == 'O' and len(column)) else column
            else:
                probe = value
            if not _declared_nullable(name) and _is_tensor_like(probe, name):
                names.append(name)
            else:
                dropped.add(name)
        if dropped:
            if strict_fields:
                raise ValueError(
                    'jax loader cannot batch fields: {} (nullable-declared or '
                    'non-tensor). With strict_fields=True this is an error; '
                    'narrow schema_fields, fill nulls via a TransformSpec that '
                    'redeclares the field nullable=False, or pass '
                    'strict_fields=False to drop them with a warning.'.format(
                        sorted(dropped)))
            warnings.warn('jax loader dropping non-tensor fields: {} '
                          '(select fields explicitly or add a TransformSpec '
                          'to keep them)'.format(sorted(dropped)))
        field_names = names
        if shuffler is not None:
            # Ride the checkpoint: the buffered row tuples are ordered by
            # this selection, and a resumed reader may yield zero samples
            # to re-learn it from (see the drain below).
            shuffler.field_names = list(names)

    def to_rows(sample):
        """Batched sample -> per-row tuples (reference pytorch.py:166-175)."""
        cols = [getattr(sample, n) for n in field_names]
        return list(zip(*cols))

    def add_sample_columns(sample):
        nonlocal count
        for name in field_names:
            value = getattr(sample, name)
            columns.setdefault(name, []).append(value)
        count += 1

    batch_spec = None     # learned from the first emitted batch (arena hookup)
    arenas_effective = True   # until a whole batch proves un-stackable

    def emit_batches(final=False):
        nonlocal columns, count, batch_spec, arenas_effective
        while count >= batch_size:
            out_bufs = (batch_buffers(batch_spec)
                        if batch_buffers is not None and batch_spec
                        and arenas_effective else None)
            batch = {}
            for name in field_names:
                buf = out_bufs.get(name) if out_bufs is not None else None
                batch[name] = _stack_column(columns[name][:batch_size], name,
                                            shape_policies, x64, out=buf)
                columns[name] = columns[name][batch_size:]
            count -= batch_size
            if batch_spec is None:
                batch_spec = {name: (arr.shape, arr.dtype)
                              for name, arr in batch.items()}
            elif out_bufs is not None:
                # Row dtypes that always need a sanitize conversion (e.g.
                # int64 rows into an int32 spec) can never stack into the
                # arena: if no field used its buffer, claiming an arena per
                # batch is pure overhead — stop asking for them.
                arenas_effective = any(batch[name] is out_bufs[name]
                                       for name in field_names)
            if lineage is not None:
                lineage.on_batch(batch_size, batch=batch)
            yield batch
        if final and count:
            if last_batch == 'drop':
                columns = {}
                count = 0
            elif last_batch in ('pad', 'partial'):
                batch = {}
                source_rows = count
                for name in field_names:
                    col = columns[name]
                    if last_batch == 'pad':
                        col = col + [col[-1]] * (batch_size - len(col))
                    batch[name] = _stack_column(col, name, shape_policies, x64)
                columns = {}
                count = 0
                if lineage is not None:
                    lineage.on_batch(source_rows, batch=batch,
                                     padded=(batch_size - source_rows
                                             if last_batch == 'pad' else 0))
                yield batch

    if getattr(reader, 'batched_output', False) and shuffler is None:
        # Block fast path: batched readers (tensor/arrow) without row-level
        # shuffling never transpose to per-row tuples — column blocks are
        # sliced/concatenated directly, one memcpy per batch at most (zero
        # when a batch lies inside one chunk). This is the decoded-columnar
        # hot path (VERDICT r2 #1); the reference's closest analog is the
        # unused BatchingTableQueue re-chunker
        # (``pyarrow_helpers/batching_table_queue.py:20-79``).
        yield from _iter_block_batches(reader, batch_size, shape_policies,
                                       last_batch, x64, strict_fields,
                                       batch_buffers=batch_buffers,
                                       views_ok=views_ok, lineage=lineage,
                                       raw_fields=raw_fields)
        return

    if raw_fields:
        raise ValueError(
            'raw image fields {} require the block fast path: a row-level '
            'shuffling buffer (shuffling_queue_capacity) re-rows encoded '
            'byte columns the staging-step decode cannot follow — shuffle '
            'with shuffle_row_groups/shuffle_rows_in_chunk instead'.format(
                sorted(raw_fields)))

    for sample in reader:
        if field_names is None:
            select_fields(sample)
        if reader.batched_output:
            rows = to_rows(sample)
        else:
            rows = [tuple(getattr(sample, n) for n in field_names)]
        if lineage is not None:
            lineage.on_chunk(getattr(reader, 'last_chunk_lineage', None),
                             len(rows))
        if shuffler is not None:
            if commit_rows is not None:
                # Loader-supplied atomic commit: buffer insert + checkpoint
                # attribution under one lock (see JaxLoader._commit_rows).
                commit_rows(rows)
            else:
                shuffler.add_many(rows)
            while shuffler.can_retrieve():
                row = shuffler.retrieve()
                for name, value in zip(field_names, row):
                    columns.setdefault(name, []).append(value)
                count += 1
                if count >= batch_size:
                    yield from emit_batches()
        else:
            for row in rows:
                for name, value in zip(field_names, row):
                    columns.setdefault(name, []).append(value)
                count += 1
            yield from emit_batches()

    if shuffler is not None:
        shuffler.finish()
        if field_names is None and shuffler.can_retrieve():
            # The reader yielded nothing — every remaining row was already
            # buffered at checkpoint time, so the selection was never
            # learned from a sample. The snapshot carried it.
            field_names = getattr(shuffler, 'field_names', None)
            if field_names is None:
                raise ValueError(
                    'restored shuffling buffer holds rows but the resumed '
                    'reader yielded no samples and the snapshot predates '
                    'field-name capture — the rows cannot be attributed '
                    'to fields (re-checkpoint with this version)')
        while shuffler.can_retrieve():
            row = shuffler.retrieve()
            for name, value in zip(field_names, row):
                columns.setdefault(name, []).append(value)
            count += 1
        yield from emit_batches(final=True)
    else:
        yield from emit_batches(final=True)


def _iter_block_batches(reader, batch_size, shape_policies, last_batch, x64,
                        strict_fields, batch_buffers=None, views_ok=True,
                        lineage=None, raw_fields=()):
    """Fixed-size batches assembled from column blocks (no per-row Python).

    Chunks (one per row-group) are sanitized once on arrival; batches are
    built from leading-dim slices — a contiguous view when one chunk covers
    the batch (``views_ok``), else collated into a recycled arena slice
    (``batch_buffers``) or, without an arena provider, one
    ``np.concatenate``-equivalent memcpy into a fresh buffer.

    Ownership: each chunk carries the reader's block-handoff marker
    (``last_chunk_private`` — see ``TensorWorker``). Shared (cache-
    resident) blocks are only ever *copied from*; a whole private chunk
    that exactly covers a batch may instead be handed out directly (its
    buffer is unshared, so downstream may keep or alias it freely without
    ever corrupting the cache).

    ``raw_fields`` names encoded-bytes columns (the on-device decode
    handoff, ``make_tensor_reader(raw_image_fields=...)``): object-dtype
    columns of raw JPEG/PNG bytes that flow through batching as O(1)
    reference slices — never sanitized, never arena-collated (an arena is
    a pixel buffer; these are pointers) — and leave this iterator still
    encoded for the loader's staging step to decode.
    """
    shape_policies = dict(shape_policies or {})
    raw_fields = frozenset(raw_fields or ())
    overlap = raw_fields & set(shape_policies)
    if overlap:
        raise ValueError(
            'shape policies on raw image fields {} are impossible: the '
            'column holds encoded bytes until the staging-step decode'
            .format(sorted(overlap)))
    field_names = None
    dropped = []
    chunks = []   # list of [dict name -> sanitized array, private_bool]
    have = 0

    def densify(name, arr):
        """Object (ragged) columns become dense via per-row policy+stack;
        a policy on an already-dense column still applies per row (same
        semantics as the per-row ``_stack_column`` path)."""
        arr = np.asarray(arr)
        policy = shape_policies.get(name)
        if arr.dtype.kind != 'O':
            if policy is None:
                return arr
            return np.stack([policy.apply(v) for v in arr])
        values = [policy.apply(v) for v in arr] if policy is not None else list(arr)
        if any(v is None for v in values):
            raise ValueError(
                'Field {!r} contains None (nullable) values; fill or drop them '
                'with a TransformSpec before batching for TPU'.format(name))
        try:
            return np.stack([np.asarray(v) for v in values])
        except ValueError as e:
            raise ValueError(
                'Field {!r} has ragged shapes and no shape policy; pass '
                "shape_policies={{'{}': PadTo(...)}} or CropTo(...): {}".format(
                    name, name, e)) from e

    def select(sample):
        names = []
        for name in sample._fields:
            if name in raw_fields:
                names.append(name)
                continue
            column = np.asarray(getattr(sample, name))
            probe = column[0] if (column.dtype.kind == 'O' and len(column)) else column
            arr = np.asarray(probe)
            ok = arr.dtype.kind not in ('O', 'U', 'S') or name in shape_policies
            if ok:
                names.append(name)
            else:
                dropped.append(name)
        if dropped:
            if strict_fields:
                raise ValueError(
                    'jax loader cannot batch fields: {} (non-tensor). Narrow '
                    'schema_fields or pass strict_fields=False to drop them '
                    'with a warning.'.format(sorted(dropped)))
            warnings.warn('jax loader dropping non-tensor fields: {}'.format(
                sorted(dropped)))
        if not names:
            raise ValueError('No batchable fields left (all dropped: {})'.format(
                sorted(dropped)))
        return names

    def out_buffers(n, head):
        """A destination for ``n`` collated rows: an arena from the
        provider when available (recycled, zero allocations), else fresh.
        Raw (encoded-bytes) columns never ride arenas — their cells are
        object references, not pixels — and always get a fresh tiny
        object array."""
        spec = {name: ((n,) + head[name].shape[1:], head[name].dtype)
                for name in field_names if name not in raw_fields}
        out = (batch_buffers(spec)
               if batch_buffers is not None and spec else None)
        if out is None:
            out = {name: np.empty(shape, dtype)
                   for name, (shape, dtype) in spec.items()}
        for name in raw_fields:
            if name in field_names:
                out[name] = np.empty(n, dtype=object)
        return out

    def take(n):
        """Pop ``n`` leading rows across chunks -> dict of arrays.

        Zero-copy single-chunk fast paths first (a leading-dim view when
        ``views_ok``; whole-chunk handout when the chunk is private);
        otherwise collate into ``out_buffers`` slice by slice via
        ``np.copyto`` — shared chunks are only ever read.
        """
        nonlocal have
        head, head_private = chunks[0]
        rows = len(head[field_names[0]])
        if rows == n and (views_ok or head_private):
            chunks.pop(0)
            have -= n
            return head
        if rows > n and views_ok:
            chunks[0][0] = {name: head[name][n:] for name in field_names}
            have -= n
            return {name: head[name][:n] for name in field_names}
        out = out_buffers(n, head)
        pos, need = 0, n
        while need > 0:
            head, _ = chunks[0]
            rows = len(head[field_names[0]])
            k = min(rows, need)
            for name in field_names:
                np.copyto(out[name][pos:pos + k], head[name][:k])
            if k == rows:
                chunks.pop(0)
            else:
                chunks[0][0] = {name: head[name][k:] for name in field_names}
            pos += k
            need -= k
        have -= n
        return out

    for sample in reader:
        if field_names is None:
            field_names = select(sample)
        private = bool(getattr(reader, 'last_chunk_private', False))
        chunk = {}
        all_copied = True
        for name in field_names:
            source = np.asarray(getattr(sample, name))
            if name in raw_fields:
                # Encoded bytes pass through untouched (decoded at the
                # staging step); slicing an object column copies refs,
                # so treat it like any shared block.
                chunk[name] = source
                all_copied = False
                continue
            arr = _sanitize_array(densify(name, source), x64)
            if arr is None:
                raise ValueError('Field {!r} dtype is not TPU-compatible'.format(name))
            chunk[name] = arr
            all_copied = all_copied and arr is not source
        # densify/sanitize copies (dtype conversion, ragged stack) make the
        # blocks private even when the reader's came out of a cache.
        if not all_copied and not private:
            # Cache-shared views may be chunk-store mmaps: hint the kernel
            # to fault their extents in now, while earlier batches collate,
            # instead of paying major faults inside the copy loop below.
            from petastorm_tpu.staging import willneed_arrays
            willneed_arrays(chunk.values())
        chunks.append([chunk, private or all_copied])
        chunk_rows = len(chunk[field_names[0]]) if field_names else 0
        have += chunk_rows
        if lineage is not None:
            lineage.on_chunk(getattr(reader, 'last_chunk_lineage', None),
                             chunk_rows)
        while have >= batch_size:
            batch = take(batch_size)
            if lineage is not None:
                lineage.on_batch(batch_size, batch=batch)
            yield batch

    if have and field_names:
        if last_batch == 'partial':
            source_rows = have
            batch = take(have)
            if lineage is not None:
                lineage.on_batch(source_rows, batch=batch)
            yield batch
        elif last_batch == 'pad':
            # Repeat-pad the tail into a full-size buffer. Never in place:
            # the tail chunk may be a cache-shared block, which is strictly
            # copy-from (see the ownership marker above).
            out = out_buffers(batch_size, chunks[0][0])
            pos = 0
            while chunks:
                head, _ = chunks.pop(0)
                k = len(head[field_names[0]])
                for name in field_names:
                    np.copyto(out[name][pos:pos + k], head[name])
                pos += k
            for name in field_names:
                out[name][pos:] = out[name][pos - 1]
            source_rows, have = have, 0
            if lineage is not None:
                lineage.on_batch(source_rows, batch=out,
                                 padded=batch_size - source_rows)
            yield out


def _stack_column(values, name, shape_policies, x64, out=None):
    if any(v is None for v in values):
        raise ValueError(
            'Field {!r} contains None (nullable) values; fill or drop them with a '
            'TransformSpec before batching for TPU'.format(name))
    policy = shape_policies.get(name)
    if policy is not None:
        values = [policy.apply(v) for v in values]
    if out is not None:
        # Arena fast path: when the rows already match the sanitized target
        # dtype/shape, stack straight into the recycled buffer — no
        # allocation, and the later sanitize pass is a no-op by
        # construction. Any mismatch (e.g. int64 rows headed for an int32
        # buffer) falls through to the allocating path below (reusing the
        # converted rows).
        rows = [np.asarray(v) for v in values]
        if (len(rows) == out.shape[0]
                and all(r.dtype == out.dtype and r.shape == out.shape[1:]
                        for r in rows)):
            np.stack(rows, out=out)
            return out
        values = rows
    try:
        stacked = np.stack([np.asarray(v) for v in values])
    except ValueError as e:
        raise ValueError(
            'Field {!r} has ragged shapes and no shape policy; pass '
            "shape_policies={{'{}': PadTo(...)}} or CropTo(...): {}".format(
                name, name, e)) from e
    sanitized = _sanitize_array(stacked, x64)
    if sanitized is None:
        raise ValueError('Field {!r} dtype {} is not TPU-compatible'.format(
            name, stacked.dtype))
    return sanitized


# --------------------------------------------------------------------------
# device staging + prefetch
# --------------------------------------------------------------------------

class _BatchedShardWave(object):
    """One field's whole per-device wave, submitted as a SINGLE stream
    item: the stream-side put issues one C++ batched transfer over every
    shard view and returns the stitched global array, so DMA-scale fields
    get the cheap dispatch of the inline tier AND land against the
    per-device in-flight windows (fence pipelining) instead of blocking
    the dispatch thread. ``pst_self_accounting`` tells the stream loop
    the put_fn records the true per-device byte/shard breakdown itself
    (``record_inline_wave``) — the submitting stream must not claim the
    whole wave's bytes as its own."""

    __slots__ = ('sharding', 'plan', 'streams', 'views', 'from_arena',
                 'nbytes')
    pst_self_accounting = True

    def __init__(self, sharding, plan, streams, views, from_arena):
        self.sharding = sharding
        self.plan = plan
        self.streams = streams
        self.views = views
        self.from_arena = from_arena
        self.nbytes = sum(v.nbytes for v in views)


class JaxLoader(object):
    """Iterates mesh-sharded ``jax.Array`` batches off a Reader.

    :param reader: a ``make_reader``/``make_batch_reader`` Reader (each pod
        host should construct it with ``cur_shard=jax.process_index()``).
    :param batch_size: **global** batch size when ``mesh``/``sharding`` is
        given (each host contributes ``batch_size / process_count`` rows);
        plain host batch size otherwise.
    :param mesh: ``jax.sharding.Mesh`` — batches are sharded over its 'data'
        axis (override via ``sharding``).
    :param sharding: explicit ``NamedSharding`` (or dict field->sharding).
    :param prefetch: device batches staged ahead (double-buffering default 2).
        ``prefetch > 0`` runs the pipelined staging engine — an assemble
        thread collating into recycled host arenas plus a dispatch thread
        keeping ``inflight`` transfers in the air (see ``staging.py``).
        ``0`` disables the staging threads entirely: host batches are
        assembled ahead by the reader's worker pool as usual, but the
        ``device_put`` happens inline in the consumer thread. Use on
        interconnects where background transfers interleaved with compute
        are pathological (see docs/troubleshoot.rst).
    :param shape_policies: dict field -> ShapePolicy for ragged fields.
    :param last_batch: 'drop' (pod-safe default) | 'pad' | 'partial'.
    :param strict_fields: raise (instead of warn-and-drop) when a selected
        field cannot batch — e.g. declared nullable but never actually null.
    :param tracer: a ``trace.Tracer`` to record assemble/stage/wait spans
        into a chrome://tracing timeline (default ``NullTracer``, no-op).
    :param echo: data echoing (Choi et al., "Faster Neural Network Training
        with Data Echoing"): deliver each staged batch ``echo`` times. When
        the pipeline is input-bound (``input_stall_frac`` high) echoed
        repeats trade statistical efficiency for step throughput — the chip
        trains instead of idling. Epoch/checkpoint accounting counts source
        rows once; ``stats['batches']`` counts echoed deliveries.
    :param stage_chunks: split each ``>=4MB`` field into this many
        ``device_put`` events along the batch dim and concatenate on device.
        On high-latency host<->device links (device tunnels) several ~5MB
        puts sustain ~2x the bandwidth of one ~20MB put (measured on an
        axon-tunneled v5e); on direct PCIe hosts leave it at 1. Applies
        per target device: single-device loaders chunk the whole batch,
        and the per-device sharded path chunks each device's shard on its
        own dispatch stream.
    :param arena_depth: host-batch arenas in the staging engine's pool
        (``prefetch > 0`` only). Batches are collated into these recycled
        preallocated buffers instead of allocating every batch; an arena
        returns to the pool once its transfer completed and (on zero-copy
        backends) the consumer dropped its arrays. Default sizes the pool
        to ``max(2, prefetch) + inflight + 2``; an exhausted pool briefly
        backpressures the assembler, then grows (visible as
        ``stats['arena_alloc']``) rather than deadlocking a consumer that
        holds many batches (e.g. ``superbatches(k)``).
    :param inflight: staged batches whose transfers may be in flight
        before the dispatch stage blocks on the oldest — the window that
        lets collate of batch N+1 overlap the transfer of batch N
        (``stats['overlap_frac']``).
    :param per_device_dispatch: the per-device sharded staging path
        (mesh/sharding targets only). When the batch sharding partitions
        just the leading batch dim, each field's per-device shards are
        zero-copy contiguous sub-slices of the host batch
        (:func:`petastorm_tpu.parallel.mesh.device_shard_plan`, computed
        once per schema); dispatch runs one overlapped ``device_put``
        stream per addressable device (``staging.DeviceStager``,
        ``pst-device-put-*`` threads with per-device in-flight windows
        and donated arena-backed shards) and stitches the global array
        with ``jax.make_array_from_single_device_arrays`` — so collate
        of shard k+1 hides under the transfer of shard k on *every*
        device. ``None`` (default) auto-enables for eligible shardings,
        falling back to the one-shot
        ``jax.make_array_from_process_local_data`` per ineligible field
        (e.g. a sequence-sharded dim); ``False`` forces the one-shot
        path everywhere (the pre-ISSUE-14 behavior, kept for A/B
        benching); ``True`` additionally raises when no addressable
        device is found.
    :param device_inflight: per-device in-flight transfer window of the
        per-device dispatch streams (each stream blocks on its own
        oldest transfer past this) — the autotuner's ``device_inflight``
        knob; dispatch-bound ticks widen it before the batch-level
        ``inflight`` window.
    :param device_stream_min_bytes: per-shard size at which a field's
        shards route through the per-device *stream threads* (issue-side
        overlap pays when each transfer is DMA-scale). Smaller shards
        are issued inline on the dispatch thread as ONE batched
        per-device transfer (``pxla.batched_device_put`` over the
        precomputed zero-copy shard views — faster than the one-shot
        path because the shard layout is never recomputed per batch);
        both tiers produce the identical per-device-sharded global
        array. Default 8MB; ``0`` forces every shard through the
        streams. DMA-scale fields above the threshold still go out as
        one batched transfer when the API is available — issued FROM a
        stream thread as a single wave item so the transfer lands
        against the per-device in-flight window instead of blocking
        dispatch (the streamed-batched tier).
    :param pinned_arenas: allocate the host staging arenas as
        DMA-friendly pinned slabs (page-aligned, pre-faulted,
        best-effort ``mlock`` — see ``native/pinned.py``); falls back
        to plain buffers when no pinned tier is available. ``None``
        defers to ``PETASTORM_TPU_PINNED_ARENAS=1``; the autotuner's
        ``arena_pinned`` knob and the memory governor's advisory rung
        can flip it at runtime.
    :param watchdog: enable the pipeline health supervisor
        (``petastorm_tpu.health``): every stage beats a heartbeat and a
        watchdog thread classifies stalls (reader-starved / assemble-stuck
        / dispatch-hung / consumer-not-draining / arena-pool-wedged /
        remote-server-dead), records a diagnosis (thread stacks, beat
        table, stage counters) into ``stats['watchdog']``, runs soft
        recovery, and escalates a persistent stall to a
        :class:`~petastorm_tpu.errors.PipelineStallError` raised from
        ``__next__`` instead of an anonymous hang. ``None`` defers to the
        ``PETASTORM_TPU_WATCHDOG`` environment variable (off when unset).
    :param stall_timeout_s: per-stage stall deadlines for the watchdog —
        a number (applies to every stage) or a dict mapping stage name
        (``'assemble'``, ``'dispatch'``, ``'consumer'``, ``'remote-recv'``,
        ``'worker-pool'``, ...) or ``'default'`` to seconds. Default 60s.
    :param autotune: enable the adaptive pipeline autotuner
        (``petastorm_tpu.autotune``): a control thread classifies the
        dominant bottleneck each tick from the wait counters above and
        retunes prefetch depth, the in-flight transfer window, arena
        depth, the reader's live worker count, and the ventilation
        watermark within bounded ranges. ``True`` for defaults, an
        :class:`~petastorm_tpu.autotune.AutotuneConfig` for custom clamps
        and pacing; ``None`` defers to ``PETASTORM_TPU_AUTOTUNE``. The
        decision log and knob trajectory ride ``stats['autotune']``.
    :param lineage: batch provenance ledger (``petastorm_tpu.lineage``):
        every delivered batch gets a record — monotonic batch id, the
        ordered (parquet file, row-group, row-range) spans composing it,
        producing worker + serving tier per span, shuffle state, and a
        per-field CRC32 content digest — kept in a ring (dumped by the
        stall flight recorder) and spilled to a crash-tolerant JSONL
        ledger replayable with ``python -m petastorm_tpu.tools.replay``.
        ``True`` arms it (ledger dir from ``PETASTORM_TPU_LINEAGE_DIR``
        or a fresh temp dir); a string is the ledger directory; a
        :class:`~petastorm_tpu.lineage.LineageTracker` is adopted as-is;
        ``None`` defers to the environment variable; ``False`` disables.
        The record of the latest batch is ``last_batch_provenance``;
        counters ride ``stats['lineage']``.
    :param on_device_augment: the decode/augment-at-staging path. A
        callable ``batch_dict -> batch_dict`` is jit-compiled and applied
        to every staged device batch INSIDE the XLA step (augmentation
        composes with ``ops.train_augment``/``imagenet_train_augment``);
        ``True`` arms the staging-step decode without an augment. Pairs
        with ``make_tensor_reader(raw_image_fields=...)``: workers then
        ship raw JPEG/PNG bytes and the staging step runs JPEG->tensor —
        through a registered on-device decode op
        (:func:`register_device_decode`) when the backend has one, else
        the host batched decoder right next to the transfer (the
        fallback) — cutting the worker pool's decode CPU out of the
        steady state. With a plain (decoded) reader the augment still
        applies; the decode step is a no-op.
    """

    def __init__(self, reader, batch_size, mesh=None, sharding=None,
                 batch_axis='data', prefetch=2, shape_policies=None,
                 shuffling_queue_capacity=0, min_after_dequeue=None, seed=None,
                 last_batch='drop', strict_fields=False, echo=1, tracer=None,
                 stage_chunks=1, arena_depth=None, inflight=2,
                 watchdog=None, stall_timeout_s=None, autotune=None,
                 lineage=None, resume_state=None, on_device_augment=None,
                 per_device_dispatch=None, device_inflight=2,
                 device_stream_min_bytes=None, pinned_arenas=None):
        import jax

        # Fail a typo'd memory budget before any staging thread starts or
        # governor registration happens (mirrors Reader.__init__).
        from petastorm_tpu import membudget as membudget_mod
        membudget_mod.validate_env_budget()

        if tracer is None:
            from petastorm_tpu.trace import NullTracer
            tracer = NullTracer()
        self._tracer = tracer

        self._reader = reader
        self._mesh = mesh
        self._sharding = sharding
        self._batch_axis = batch_axis
        self._jax = jax
        x64 = bool(jax.config.jax_enable_x64)

        # On-device decode/augment (see the on_device_augment param): raw
        # image fields the reader ships encoded, decoded at the staging
        # step; an optional jitted augment applied to every staged batch.
        self._raw_specs = {}
        raw_fields = tuple(getattr(reader, 'raw_image_fields', ()) or ())
        if raw_fields:
            if shuffling_queue_capacity:
                raise ValueError(
                    'raw image fields {} require the block fast path; a '
                    'row-level shuffling buffer cannot carry encoded byte '
                    'columns — shuffle with shuffle_row_groups/'
                    'shuffle_rows_in_chunk instead'.format(sorted(raw_fields)))
            for name in raw_fields:
                self._raw_specs[name] = reader.schema.fields[name]
            # Staging-decode thread sizing: when raw fields cover EVERY
            # image field the worker pool decodes nothing and the staging
            # thread may spend the whole process budget; a partial
            # selection leaves workers decoding the rest, so the staging
            # thread takes a fair share like any other decoder.
            from petastorm_tpu.codecs import CompressedImageCodec
            image_fields = {n for n, f in reader.schema.fields.items()
                            if isinstance(f.resolved_codec(),
                                          CompressedImageCodec)}
            self._staging_owns_budget = set(raw_fields) >= image_fields
        self._augment_fn = None
        if callable(on_device_augment):
            self._augment_fn = jax.jit(on_device_augment)
        self._stage_decode_s = 0.0

        if mesh is not None or sharding is not None:
            n_proc = jax.process_count()
            if batch_size % n_proc:
                raise ValueError('global batch_size {} not divisible by process_count {}'
                                 .format(batch_size, n_proc))
            local_batch = batch_size // n_proc
        else:
            local_batch = batch_size
        self._global_batch = batch_size
        self._local_batch = local_batch

        if last_batch == 'partial' and (mesh is not None or sharding is not None):
            raise ValueError("last_batch='partial' breaks fixed global shapes on a mesh; "
                             "use 'drop' or 'pad'")

        # Without a row-level shuffle, rows are consumed in exact delivery
        # order, so checkpoint accounting can be deferred to actual batch
        # delivery (rows sitting in the prefetch queue at checkpoint time are
        # NOT counted consumed and re-deliver on resume).
        self._row_granular_ckpt = False
        self._defer_rows_consumed = False   # superbatches() group accounting
        self._pending_fresh_rows = 0        # fresh rows fetched but not yet
                                            # attributed (deferred mode)
        if not shuffling_queue_capacity and hasattr(reader, 'enable_row_granular_checkpoint'):
            self._row_granular_ckpt = reader.enable_row_granular_checkpoint()

        # The loader OWNS its shuffling buffer (rather than letting
        # iter_numpy_batches build one): state_dict() then snapshots
        # buffered-but-undelivered rows + the RNG state, so a checkpoint
        # with a row-level shuffle engaged no longer forces a drain —
        # restore them via JaxLoader(resume_state=the same dict handed to
        # the reader factory).
        self._shuffler = None
        self._ckpt_lock = threading.Lock()
        self._buffer_entry_ckpt = False
        if shuffling_queue_capacity and shuffling_queue_capacity > 0:
            self._shuffler = _build_shuffling_buffer(
                shuffling_queue_capacity, min_after_dequeue, seed)
            if isinstance(resume_state, dict) \
                    and resume_state.get('shuffling_buffer'):
                self._shuffler.restore(resume_state['shuffling_buffer'])
            # Rows drawn into staged-but-undelivered batches must ride the
            # snapshot too (they are in neither the buffer nor the
            # trainer's hands at checkpoint time); mark_delivered below
            # releases them batch-by-batch as batches actually arrive.
            self._shuffler.track_pending()
            # Buffer-entry attribution: defer the reader's checkpoint
            # cursor and advance it only when a chunk's rows actually land
            # in the buffer — _commit_rows does both under _ckpt_lock, and
            # state_dict() snapshots cursor + buffer under the same lock.
            # Without this, rows moving reader->buffer between the two
            # snapshots would be counted by neither (lost) or both
            # (duplicated) on resume.
            if hasattr(reader, 'enable_row_granular_checkpoint'):
                self._buffer_entry_ckpt = \
                    reader.enable_row_granular_checkpoint()
        elif isinstance(resume_state, dict) \
                and (resume_state.get('shuffling_buffer') or {}).get('rows'):
            # The snapshot's rows were already counted consumed by the
            # reader cursor at checkpoint time; with no buffer to restore
            # them into they would silently never be delivered.
            raise ValueError(
                'resume_state carries a shuffling-buffer snapshot of {} '
                'row(s) but the loader was rebuilt without '
                'shuffling_queue_capacity; those rows would be lost — '
                'resume with the same shuffling_queue_capacity the '
                'checkpoint was taken under'.format(
                    len(resume_state['shuffling_buffer']['rows'])))

        if echo < 1:
            raise ValueError('echo must be >= 1, got {}'.format(echo))
        self._echo = int(echo)
        self._echo_left = 0
        self._echo_item = None
        self._consumer_staging = prefetch == 0
        # Inline-staging stage split (prefetch=0): the consumer runs the
        # whole pipeline, so its blocked time alone can't say WHICH stage
        # is slow — these bracket the reader pull vs the device dispatch
        # for the autotuner's classification (and they are interesting
        # stats in their own right).
        self._inline_reader_s = 0.0
        self._inline_dispatch_s = 0.0
        # `prefetch` bounds staged-but-undelivered batches (device memory).
        # The consumer's batched pop moves queued batches into its local
        # buffer, so the bound is enforced over BOTH: the queue's live
        # maxsize is always target - len(_ready) (floor 1) — a drained
        # slot does NOT become capacity the dispatch thread may refill,
        # or the ceiling would double.
        self._prefetch_target = max(1, prefetch)
        self._queue = queue.Queue(maxsize=self._prefetch_target)
        # Consumer-local drain buffer: __next__ moves every already-staged
        # batch here under one queue-mutex acquisition instead of paying a
        # lock round trip per batch (the warm-cache chunk rate is queue-pop
        # bound — PROFILE_r05 §2). Consumer thread only.
        self._ready = deque()
        self._stop = threading.Event()
        self._exhausted = False
        # Pipeline health supervisor (petastorm_tpu.health), armed through
        # the shared control-plane lifecycle: heartbeats on every stage +
        # a watchdog that classifies stalls, runs soft recovery, and
        # escalates to PipelineStallError instead of hanging. Deferred
        # start (start_health below) — staging stages register later.
        from petastorm_tpu.fleet import control_plane as control_plane_mod
        self._supervisor = control_plane_mod.PipelineSupervisor()
        self._hb_consumer = None
        self._stall_error = None

        def attach_stages(registry):
            self._hb_consumer = registry.register('consumer')
            registry.register_probe(
                'consumer', lambda: {'queue_depth': (self._queue.qsize()
                                                     + len(self._ready)),
                                     'queue_capacity': self._prefetch_target,
                                     'exhausted': self._exhausted})
            attach = getattr(reader, 'attach_health', None)
            if attach is not None:
                attach(registry)
            # Memory-pressure classification (health.classify_stall): the
            # governor's ladder state rides every diagnosis, and a stall
            # while degradation is active classifies as memory-pressure
            # (soft) instead of blaming a deliberately-shrunk stage.
            from petastorm_tpu import membudget as membudget_mod
            registry.register_probe(
                'memory', membudget_mod.get_governor().probe)

        self._health = self._supervisor.arm_health(
            watchdog, stall_timeout_s, self._deliver_stall,
            tracer=self._tracer, attach_fn=attach_stages, start=False)
        # Batch provenance (petastorm_tpu.lineage): ring + ledger of what
        # exactly composed every delivered batch. Collector hooks ride the
        # host-batch iterators; records are minted at delivery in __next__.
        from petastorm_tpu import lineage as lineage_mod
        self._lineage = None
        self._lineage_owned = False
        self._last_provenance = None
        if isinstance(lineage, lineage_mod.LineageTracker):
            # Adopted as-is: lifecycle stays with the caller (stop()
            # flushes but must not close — the caller may ledger several
            # loaders through one tracker).
            self._lineage = lineage
        elif lineage_mod.lineage_enabled(lineage):
            ctx_fn = getattr(reader, 'lineage_context', None)
            ctx = ctx_fn() if ctx_fn is not None else {'mode': None}
            ctx['x64'] = x64
            ctx['batch_size'] = local_batch
            ctx['last_batch'] = last_batch
            ctx['shape_policies'] = sorted(shape_policies) \
                if shape_policies else None
            ctx['shuffling_queue_capacity'] = int(shuffling_queue_capacity or 0)
            self._lineage = lineage_mod.LineageTracker(
                ctx,
                ledger_dir=lineage_mod.resolve_ledger_dir(
                    lineage if isinstance(lineage, str) else None),
                state_fn=getattr(reader, 'lineage_state', None))
            self._lineage_owned = True
        self._namedtuple_cache = {}
        # Metrics-registry instruments (petastorm_tpu.metrics): the
        # machine-scrapable mirror of the `stats` dict. Cached here — one
        # registry lookup at construction, one small lock per batch.
        from petastorm_tpu import metrics as metrics_mod
        self._m_batches = metrics_mod.counter(
            'pst_loader_batches_total', 'Device batches delivered to the '
            'training loop (echoed re-deliveries included)')
        self._m_batch_wait = metrics_mod.histogram(
            'pst_batch_wait_seconds', 'Consumer-side blocked time per '
            'fetch (the input-stall signal; includes the end-of-stream '
            'fetch)')
        self._m_staged_bytes = metrics_mod.counter(
            'pst_staged_bytes_total', 'Host bytes handed to device staging')
        # input-stall accounting (BASELINE.json targets <5% input stall)
        self._batches_delivered = 0
        self._wait_s = 0.0
        self._first_get_t = None
        # staging accounting (VERDICT r1 #4: measure copy/transfer cost).
        # Written by the staging thread, reset by the consumer — lock both.
        self._stats_lock = threading.Lock()
        self._stage_s = 0.0
        self._staged_bytes = 0
        # Latest staged batch's bytes (membudget prefetch-queue pool =
        # depth x this). Initialized BEFORE the staging engine starts:
        # a stage thread may record a size before __init__ finishes, and
        # a later zeroing would blank the accounting at spin-up.
        self._last_batch_nbytes = 0
        try:
            self._dlpack_staging = jax.default_backend() == 'cpu'
        except Exception:  # noqa: BLE001 - backend probe must not kill init
            self._dlpack_staging = False
        # Transport optimization for high-latency host<->device links (the
        # axon tunnel sustains ~2x the throughput at ~5MB transfers vs one
        # ~20MB put — measured, PROFILE_r05 §6): split each field along the
        # batch dim into `stage_chunks` device_puts and concatenate on
        # device. Applies per target device: single-device loaders chunk
        # the whole batch; the per-device sharded path chunks each
        # device's shard on its own stream (_put_shard).
        self._stage_chunks = max(1, int(stage_chunks))
        self._stage_concat = None
        if self._stage_chunks > 1:
            import jax.numpy as jnp
            self._stage_concat = jax.jit(lambda *xs: jnp.concatenate(xs))

        # Zero-copy backends (CPU) hand out device arrays that ALIAS host
        # memory; recycling/accounting decisions below key off this once.
        from petastorm_tpu.staging import staging_aliases_host
        self._staging_aliasing = (self._dlpack_staging
                                  or staging_aliases_host(jax))

        # Per-device sharded staging (the ISSUE-14 tentpole): one
        # overlapped device_put stream per addressable device; batch-dim
        # shards are zero-copy contiguous sub-slices of the host batch
        # and the global jax.Array is stitched with
        # make_array_from_single_device_arrays. Shard layouts are planned
        # once per (field, shape) in _device_shard_plan; ineligible
        # fields keep the one-shot path per field.
        self._stager = None
        self._stager_devices = ()
        self._shard_plans = {}
        # Device-resident dataset tier (device_cache.DeviceDatasetCache
        # attaches itself here so loader stats surface the HBM tier).
        self._device_cache = None
        self._donate_supported = None   # probed on first donated put
        self._device_stream_min_bytes = (
            8 << 20 if device_stream_min_bytes is None
            else max(0, int(device_stream_min_bytes)))
        # Inline assembly tier: one C++ batched per-device transfer per
        # field (jax's own make_array_from_callback substrate) fed the
        # precomputed zero-copy shard views directly — no per-batch index
        # wrangling, no per-shard Python dispatch. Internal API, so probe
        # once and fall back to per-shard puts through the streams.
        self._batched_put = None
        self._shaped_array = None
        try:
            from jax._src import core as jax_core
            from jax._src.interpreters import pxla
            self._batched_put = pxla.batched_device_put
            self._shaped_array = jax_core.ShapedArray
        except Exception:  # noqa: BLE001 - stream tier covers everything
            pass
        if (mesh is not None or sharding is not None) \
                and per_device_dispatch is not False:
            devices = self._collect_stager_devices()
            if devices:
                from petastorm_tpu.staging import DeviceStager, OverlapMeter
                self._stager_devices = devices
                # Stream threads start LAZILY on the first streamed wave
                # (DeviceStager.start via put_shards): a constructor
                # failure below must not leak parked pst-device-put
                # threads with no reachable stop path, and the inline
                # tier never needs them running.
                # The stager gets its OWN OverlapMeter: the loader tracks
                # 'host' around _stage on it, the stager tracks one
                # logical 'h2d' lane over its in-flight windows, and
                # their co-activity IS the streamed-path h2d_overlap_frac
                # (satellite: the bench probe used to report 0.0 here).
                self._stager = DeviceStager(
                    stream_keys=[str(getattr(d, 'id', i))
                                 for i, d in enumerate(devices)],
                    put_fn=self._put_shard,
                    inflight=device_inflight,
                    ready_fn=jax.block_until_ready,
                    stop_event=self._stop,
                    tracer=self._tracer,
                    meter=OverlapMeter())
            elif per_device_dispatch:
                raise ValueError(
                    'per_device_dispatch=True but the mesh/sharding has no '
                    'addressable device on this process')

        # Pipelined staging engine (prefetch > 0): an assemble stage that
        # collates batches into recycled host arenas and a dispatch stage
        # holding a bounded window of in-flight puts, so collate of batch
        # N+1 overlaps the transfer of batch N (see ``staging.py``).
        # ``prefetch == 0`` keeps the inline consumer-staging path: plain
        # allocation, no arenas, no extra threads.
        self._thread = None       # kept for back-compat introspection
        self._engine = None
        self._arena_pool = None
        self._metered_reader = None
        arena_buffers = None
        views_ok = True
        host_reader = reader
        if not self._consumer_staging:
            from petastorm_tpu.staging import (ArenaPool, MeteredReader,
                                               OverlapMeter, StagingEngine)
            # Zero-copy backends (CPU) hand out device arrays that ALIAS
            # host memory: staged chunk views stay the fastest path
            # (views_ok), and arena recycling must additionally wait for
            # the consumer to drop its arrays (holds_mode). Copying
            # backends (real TPU h2d) prefer every batch in a stable
            # recycled arena — transfers re-use warmed buffers and the
            # arena is free the moment the put completes.
            aliasing = self._staging_aliasing
            views_ok = aliasing
            inflight = max(1, int(inflight))
            if arena_depth is None:
                arena_depth = max(2, prefetch) + inflight + 2
            # Blocked time — reader pulls and arena backpressure — reports
            # as PAUSED assemble time so the overlap metric covers collate
            # work only (an input- or arena-bound run must not read as
            # perfect pipelining).
            meter = OverlapMeter()
            hb_assemble = (self._health.registry.register('assemble')
                           if self._health is not None else None)
            host_reader = MeteredReader(reader, meter, heartbeat=hb_assemble)
            self._metered_reader = host_reader
            self._arena_pool = ArenaPool(arena_depth, stop_event=self._stop,
                                         tracer=self._tracer, meter=meter,
                                         heartbeat=hb_assemble,
                                         pinned=pinned_arenas)
            arena_buffers = self._arena_pool.get_buffers
            if self._health is not None:
                self._health.registry.register_probe('arena-pool',
                                                     self._arena_pool.stats)

        self._host_iter = iter_numpy_batches(
            host_reader, local_batch, shape_policies=shape_policies,
            shuffling_queue_capacity=shuffling_queue_capacity,
            min_after_dequeue=min_after_dequeue, seed=seed,
            last_batch=last_batch, x64=x64, strict_fields=strict_fields,
            batch_buffers=arena_buffers, views_ok=views_ok,
            lineage=(self._lineage.collector
                     if self._lineage is not None else None),
            shuffler=self._shuffler,
            commit_rows=(self._commit_rows if self._shuffler is not None
                         else None))

        # Start the engine LAST: it touches the state above immediately.
        if not self._consumer_staging:
            def ready_fn(staged):
                jax.block_until_ready(list(staged.values()))

            def is_ready_fn(staged):
                return all(getattr(v, 'is_ready', _never_ready)()
                           for v in staged.values())

            self._engine = StagingEngine(
                host_iter=self._host_iter, stage_fn=self._stage,
                out_queue=self._queue, stop_event=self._stop,
                end_sentinel=_END, pool=self._arena_pool, inflight=inflight,
                ready_fn=ready_fn, is_ready_fn=is_ready_fn,
                holds_mode=aliasing, tracer=self._tracer,
                meter=meter,
                # The device-sharded stage reuses the arena's memoized
                # per-device sub-slice views (zero re-layout per batch).
                stage_with_arena=True,
                health=self._health.registry
                if self._health is not None else None,
                # Provenance accounting is FIFO-paired with delivered
                # batches: a batch the engine assembles but drops at stop
                # time must retract its pending record too.
                on_drop=(self._lineage.drop_newest
                         if self._lineage is not None else None)).start()
        # The watchdog starts only once every stage had the chance to
        # register, so its first classification sees the full beat table.
        self._supervisor.start_health()

        # Host memory governor (petastorm_tpu.membudget): the loader's
        # byte-holding pools register for unified accounting — the arena
        # pool (which also covers the staging in-flight window: staged
        # batches are arena-backed), the prefetch queue (staged batches x
        # the latest batch's bytes), and the shuffling buffer. Arming is
        # env-driven (PETASTORM_TPU_HOST_MEM_BUDGET) and refcounted;
        # breaches are delivered into the consumer queue exactly like a
        # watchdog hard stall — the trainer raises HostMemoryExceededError
        # with a flight dump instead of eating a kernel SIGKILL.
        from petastorm_tpu import membudget as membudget_mod
        governor = membudget_mod.get_governor()
        self._mem_handles = []
        if self._arena_pool is not None:
            pool = self._arena_pool
            self._arena_pinned_before_advisory = False

            def arena_advisory(active):
                # mlocked slabs are exactly the pages the kernel cannot
                # reclaim under pressure — the advisory rung unpins new
                # arena allocations (live slabs recycle out naturally)
                # and the release restores the configured mode.
                if active:
                    self._arena_pinned_before_advisory = pool.pinned
                    pool.set_pinned(False)
                elif self._arena_pinned_before_advisory:
                    pool.set_pinned(True)

            self._mem_handles.append(governor.register_pool(
                'arena-pool', lambda: pool.nbytes,
                advisory_fn=arena_advisory))
        def prefetch_queue_nbytes():
            # Arena-backed staging (the prefetch>0 engine path): every
            # queued batch's HOST bytes are already accounted by the
            # arena pool (zero-copy backends alias the arena; copying
            # backends queue device arrays that hold no host memory) —
            # reporting them here too would double-count the same bytes
            # and walk the ladder on phantom pressure. Only batches that
            # bypassed the arena pool are this pool's to count.
            if self._arena_pool is not None:
                return 0
            return ((self._queue.qsize() + len(self._ready))
                    * self._last_batch_nbytes)

        self._mem_handles.append(governor.register_pool(
            'prefetch-queue', prefetch_queue_nbytes))
        if self._stager is not None:
            stager = self._stager

            def device_window_nbytes():
                # Per-device in-flight windows are accountable bytes —
                # but only once: on aliasing backends the windowed shards
                # point into arena buffers the arena pool already counts
                # (donated, no host-side copy to double-account), and on
                # copying backends the window holds device memory, not
                # host bytes. Only windows over non-arena host batches
                # (zero-copy chunk views, consumer staging) are this
                # pool's to report.
                if not self._staging_aliasing \
                        or self._arena_pool is not None:
                    return 0
                return stager.window_nbytes

            self._mem_handles.append(governor.register_pool(
                'device-put-window', device_window_nbytes))
        if self._shuffler is not None:
            shuffler = self._shuffler
            degrade = None
            if getattr(reader, 'deterministic', None) is False:
                # Halving the buffer changes the draw sequence — only
                # readers that EXPLICITLY report non-deterministic register
                # the hook. Fail closed on readers without the property
                # (RemoteReader may be carrying a deterministic stream):
                # the deterministic contract outranks memory relief, and
                # the other rungs still apply.
                degrade = shuffler.shrink_capacity
            self._mem_handles.append(governor.register_pool(
                'shuffling-buffer', lambda: shuffler.nbytes,
                degrade_fn=degrade))
        self._mem_breach_sink = governor.add_breach_sink(self._deliver_stall)
        self._mem_armed = membudget_mod.maybe_arm_from_env()

        # Adaptive autotuning (petastorm_tpu.autotune): one controller for
        # the whole pipeline — the loader's knobs (prefetch depth, in-flight
        # transfer window, arena depth) merged with the reader tier's
        # (worker-pool size, ventilation watermark), which the reader hands
        # over via adopt_autotune (stopping any controller of its own).
        from petastorm_tpu import autotune as autotune_mod
        self._reader_telemetry = None

        def build_knobs(cfg):
            knobs = {}
            if not self._consumer_staging:
                knobs['prefetch'] = autotune_mod.Knob(
                    'prefetch', lambda: self._prefetch_target,
                    self.set_prefetch, lo=cfg.min_prefetch,
                    hi=cfg.max_prefetch)
                knobs['inflight'] = autotune_mod.Knob(
                    'inflight', lambda: self._engine.inflight_window,
                    self._engine.set_inflight, lo=cfg.min_inflight,
                    hi=cfg.max_inflight)
                knobs['arena_depth'] = autotune_mod.Knob(
                    'arena_depth', lambda: self._arena_pool.depth,
                    self._arena_pool.set_depth, lo=cfg.min_arena_depth,
                    hi=cfg.max_arena_depth)
                # DMA-friendly host slabs: a dispatch-bound pipeline grows
                # into pinned mode (faster transfers from page-aligned /
                # mlocked buffers); the memory-shrink ladder steps it back
                # off first — mlocked pages are unreclaimable.
                arena_pool = self._arena_pool
                knobs['arena_pinned'] = autotune_mod.Knob(
                    'arena_pinned', lambda: int(arena_pool.pinned),
                    lambda v: arena_pool.set_pinned(bool(v)), lo=0, hi=1)
            if self._stager is not None:
                # Per-device window: the dispatch-bound classification
                # steps this BEFORE the global inflight window (see
                # autotune._GROW_ACTIONS) — widening every device's
                # stream attacks the transfer backlog where it forms.
                stager = self._stager
                knobs['device_inflight'] = autotune_mod.Knob(
                    'device_inflight', lambda: stager.inflight_window,
                    stager.set_inflight, lo=cfg.min_device_inflight,
                    hi=cfg.max_device_inflight)
                if self._batched_put is not None:
                    # Growing the inline/batched threshold routes MORE
                    # fields through the single C++ batched transfer per
                    # wave — the cheapest dispatch path when the pipeline
                    # is dispatch-bound.
                    knobs['device_stream_min_mb'] = autotune_mod.Knob(
                        'device_stream_min_mb',
                        lambda: self._device_stream_min_bytes >> 20,
                        self.set_device_stream_min_mb,
                        lo=cfg.min_device_stream_mb,
                        hi=cfg.max_device_stream_mb)
            adopt = getattr(reader, 'adopt_autotune', None)
            if adopt is not None:
                reader_knobs, self._reader_telemetry = adopt(cfg)
                knobs.update(reader_knobs)
            return knobs

        watchdog_active = None
        if self._health is not None:
            watchdog_obj = self._health.watchdog
            watchdog_active = lambda: watchdog_obj.episode_active  # noqa: E731
        listeners = []
        store = getattr(reader, 'chunk_store', None)
        if store is not None:
            # Epoch-0 spill throttling (the reader's own controller is
            # stopped by adopt_autotune inside build_knobs): pause the
            # NVMe write-behind whenever the pipeline itself is the
            # classified bottleneck.
            listeners.append(autotune_mod.writer_throttle_listener(store))
        self._autotuner = self._supervisor.arm_autotune(
            autotune, build_knobs, self._autotune_telemetry,
            autotune_mod.classify_loader,
            watchdog_active_fn=watchdog_active,
            # Advisory rung of the memory ladder: the tuner stops
            # growing and steps every knob down instead.
            memory_state_fn=governor.pressure_level,
            tracer=self._tracer, listeners=listeners)

    # -- autotune hookups --------------------------------------------------

    def set_device_stream_min_mb(self, mb):
        """Retarget the inline-batched-put threshold at runtime (autotune
        hookup). Fields whose per-shard bytes fall below the threshold go
        out as one C++ batched transfer; at or above it they stream
        through the per-device windows as one batched wave item."""
        self._device_stream_min_bytes = max(0, int(mb)) << 20

    def set_prefetch(self, n):
        """Retarget the staged-batch bound at runtime (autotune hookup).
        Growing wakes a dispatch thread blocked on the bounded put;
        shrinking takes effect as the consumer drains below the new cap
        (no staged batch is dropped). The live queue capacity is the
        target minus the consumer's drain buffer (see ``__init__``)."""
        n = max(1, int(n))
        staging_queue = self._queue
        with staging_queue.mutex:
            self._prefetch_target = n
            staging_queue.maxsize = max(1, n - len(self._ready))
            staging_queue.not_full.notify_all()

    def _autotune_telemetry(self):
        """Cumulative per-stage wait counters + queue gauges — the inputs
        of :func:`petastorm_tpu.autotune.classify_loader`. Cheap enough
        for a sub-second tick: attribute reads plus two small locks."""
        out = {'batches': self._batches_delivered,
               'wait_s': self._wait_s,
               'queue_depth': self._queue.qsize() + len(self._ready),
               'queue_capacity': self._prefetch_target}
        if self._consumer_staging:
            # Inline staging: the consumer's blocked time IS the pipeline
            # running, so the stage split above supplies the per-stage
            # signals — without them every slow tick would classify as
            # input-bound and ratchet the worker pool to its clamp even
            # when the device dispatch is the bottleneck.
            out['reader_wait_s'] = self._inline_reader_s
            out['ready_wait_s'] = self._inline_dispatch_s
        if self._metered_reader is not None:
            out['reader_wait_s'] = self._metered_reader.reader_wait_s
        if self._arena_pool is not None:
            out['arena_wait_s'] = self._arena_pool.wait_seconds
        if self._engine is not None:
            out['ready_wait_s'] = self._engine.ready_wait_seconds
        if self._stager is not None:
            # Per-device window fences are dispatch-bound signal exactly
            # like the engine's batch-level fence — fold them together so
            # the classifier sees transfer backpressure wherever it forms.
            out['ready_wait_s'] = (out.get('ready_wait_s', 0.0)
                                   + self._stager.ready_wait_seconds)
        if self._reader_telemetry is not None:
            reader_tel = self._reader_telemetry()
            # The reader tier reports its own delivery counter under
            # 'batches' (its rate signal when tuned standalone); here the
            # throughput guard must judge actions by DELIVERED loader
            # batches, not upstream chunk pulls — keep ours.
            reader_tel.pop('batches', None)
            out.update(reader_tel)
        return out

    # -- staging thread --------------------------------------------------

    def _field_sharding(self, name):
        if self._sharding is not None:
            if isinstance(self._sharding, dict):
                return self._sharding[name]
            return self._sharding
        from petastorm_tpu.parallel.mesh import batch_sharding
        return batch_sharding(self._mesh, self._batch_axis)

    def _chunked_put(self, array, sharding=None, device=None, donate=False):
        """Split along the batch dim, put each piece, concatenate on
        device — the ONE implementation of the ``stage_chunks`` transport
        optimization (wins ~2x on high-latency tunnels). ``device`` is
        the per-device-stream form (each shard chunks on its own stream,
        optionally donated); ``sharding``/neither are the no-mesh and
        fallback forms. ``stage_chunks`` is a minimum: pieces are further
        split to stay under ~8MB each — single ~39MB puts have been
        observed to wedge device tunnels permanently, and a bigger batch
        or f32 field must not silently cross that line."""
        jax = self._jax
        n_chunks = max(self._stage_chunks, -(-array.nbytes // (8 << 20)))
        parts = np.array_split(array, min(n_chunks, len(array)))
        if device is not None:
            staged = [self._device_put(p, device, donate) for p in parts]
        elif sharding is not None:
            staged = [jax.device_put(p, sharding) for p in parts]
        else:
            staged = [jax.device_put(p) for p in parts]
        return self._stage_concat(*staged)

    # -- per-device sharded staging ---------------------------------------

    def _collect_stager_devices(self):
        """Addressable devices of the loader's mesh/sharding(s), sorted by
        id — one :class:`~petastorm_tpu.staging.DeviceStager` stream each."""
        jax = self._jax
        devices = set()
        if self._mesh is not None:
            try:
                process = jax.process_index()
                devices.update(d for d in self._mesh.devices.flat
                               if d.process_index == process)
            except Exception:  # noqa: BLE001 - a probe failure just disables the path
                logger.debug('mesh device probe failed', exc_info=True)
        shardings = []
        if isinstance(self._sharding, dict):
            shardings.extend(self._sharding.values())
        elif self._sharding is not None:
            shardings.append(self._sharding)
        for sharding in shardings:
            try:
                devices.update(sharding.addressable_devices)
            except Exception:  # noqa: BLE001
                continue
        return tuple(sorted(devices, key=lambda d: getattr(d, 'id', 0)))

    def _device_shard_plan(self, name, sharding, shape):
        """``(plan, stream_indices, donate_ok)`` for a batch-dim-sharded
        field, or ``None`` (ineligible: keep the one-shot path). Memoized
        per (field, host shape) — shard boundaries are computed from the
        ``NamedSharding`` exactly once per schema, and the arena pool
        learns the layout so arenas can hand out memoized per-device
        sub-slice views (zero re-layout at dispatch time). ``donate_ok``
        marks the shards whose bound no replica shares — only those may
        be donated outright (donating one replica's buffer would
        invalidate it under its sibling's transfer)."""
        key = (name, tuple(shape))
        cached = self._shard_plans.get(key)
        if cached is not None:
            return cached if cached is not False else None
        from petastorm_tpu.parallel.mesh import device_shard_plan
        plan = device_shard_plan(sharding, shape)
        if plan is None or not set(plan.devices) <= set(self._stager_devices):
            self._shard_plans[key] = False
            return None
        index_of = {d: i for i, d in enumerate(self._stager_devices)}
        entry = (plan, tuple(index_of[d] for d in plan.devices),
                 tuple(plan.bounds.count(b) == 1 for b in plan.bounds))
        self._shard_plans[key] = entry
        if self._arena_pool is not None:
            self._arena_pool.learn_shard_layout({name: plan.bounds})
        return entry

    def _shard_arrays(self, name, array, arena, plan):
        """``(views, from_arena)`` for one field: the arena's memoized
        contiguous sub-slices when the batch collated into an arena
        buffer (``from_arena=True`` — recycling is transfer-and-GC-gated,
        so handing them over copy-free is safe), else fresh leading-dim
        views of whatever array arrived (e.g. a staging-step-decoded
        block, whose lifetime is NOT arena-gated). Both are zero-copy."""
        if arena is not None:
            buf = arena.buffers.get(name)
            if buf is not None and buf.shape == array.shape \
                    and np.may_share_memory(array, buf):
                try:
                    # The pool-learned layout (learn_shard_layout, written
                    # when the plan was computed) — per-arena memoized.
                    return arena.shard_views(name), True
                except KeyError:
                    return arena.shard_views(name, plan.bounds), True
        return tuple(array[start:stop]
                     for start, stop in plan.bounds), False

    def _device_put(self, array, device, donate):
        """One shard onto one device. ``donate`` hands the (arena-backed)
        host buffer to the backend without a defensive copy — safe because
        arena recycling is already gated on transfer completion plus, on
        aliasing backends, consumer GC holds."""
        jax = self._jax
        if donate and self._donate_supported is not False:
            try:
                staged = jax.device_put(array, device, donate=True)
                self._donate_supported = True
                return staged
            except TypeError:
                # jax predating the donate kwarg: plain puts are correct,
                # just never a zero-copy handoff. Probe once.
                self._donate_supported = False
        return jax.device_put(array, device)

    def _put_shard(self, array, stream_index, donate):
        """DeviceStager ``put_fn``: issue one shard's transfer on its
        device's stream — through :meth:`_chunked_put` when
        ``stage_chunks`` asks (the transport optimization now applies
        per device, so multi-device shardings ride it too). A
        :class:`_BatchedShardWave` item carries a whole field's wave and
        goes out as one batched transfer."""
        if isinstance(array, _BatchedShardWave):
            return self._batched_stream_put(array)
        device = self._stager_devices[stream_index]
        if (self._stage_chunks > 1
                and array.nbytes >= _STAGE_CHUNK_MIN_BYTES
                and len(array) >= self._stage_chunks):
            return self._chunked_put(array, device=device, donate=donate)
        return self._device_put(array, device, donate)

    def _batched_stream_put(self, wave):
        """Streamed-batched tier (runs ON a device-put stream thread):
        one C++ batched transfer for the whole field's wave, stitched
        into the global array before it enters the in-flight window.
        Falls back to serial per-shard puts inside this same call when
        the internal API refuses — the stream item must still deliver a
        global array — and records the wave's true per-device breakdown
        either way (``pst_self_accounting``: the stream loop skipped its
        own accounting for this item)."""
        t0 = time.perf_counter()
        batched = self._batched_put
        staged = None
        if batched is not None:
            try:
                aval = self._shaped_array(wave.plan.global_shape,
                                          wave.views[0].dtype)
                staged = batched(aval, wave.sharding, list(wave.views),
                                 list(wave.plan.devices))
            except Exception:  # noqa: BLE001 - internal API drifted
                logger.warning(
                    'pxla.batched_device_put failed on the stream tier; '
                    'falling back to per-shard device_put for the rest of '
                    'this run', exc_info=True)
                self._batched_put = None
        if staged is None:
            shards = [self._device_put(v, self._stager_devices[s], False)
                      for s, v in zip(wave.streams, wave.views)]
            staged = self._jax.make_array_from_single_device_arrays(
                wave.plan.global_shape, wave.sharding, shards)
        self._stager.record_inline_wave(
            wave.streams, [v.nbytes for v in wave.views],
            time.perf_counter() - t0, wave.from_arena)
        return staged

    def _stage_pending_shards(self, pending, out, arena):
        """Dispatch every planned field's per-device shards, then stitch
        each field's global ``jax.Array``. Three tiers, same result:

        * **inline** (small shards): ONE batched per-device transfer per
          field on the dispatch thread — the precomputed zero-copy shard
          views go straight into ``pxla.batched_device_put``, so dispatch
          pays no per-batch layout work and no per-shard Python
          round-trips (measurably faster than the one-shot
          ``make_array_from_process_local_data``, which re-wrangles
          indices every call);
        * **streamed-batched** (DMA-scale shards): the same single C++
          batched transfer, but issued FROM a stream thread as one
          :class:`_BatchedShardWave` item so it lands against the
          per-device in-flight windows (fence pipelining) instead of
          blocking the dispatch thread for the whole transfer;
        * **streams** (chunked puts, or no batched-put API): the wave is
          submitted shard-by-shard across the per-device stream threads
          before gathering, so every device issues concurrently; the
          field stitches with
          ``jax.make_array_from_single_device_arrays``.
        """
        jax = self._jax
        streamed = []
        waves = []
        for name, sharding, plan, streams, donate_ok, array in pending:
            views, from_arena = self._shard_arrays(name, array, arena, plan)
            shard_nbytes = views[0].nbytes if views else 0
            chunked = (self._stage_chunks > 1
                       and shard_nbytes >= _STAGE_CHUNK_MIN_BYTES)
            if self._batched_put is not None and not chunked:
                if shard_nbytes < self._device_stream_min_bytes:
                    staged = self._batched_assemble(sharding, plan, streams,
                                                    views, from_arena)
                    if staged is not None:
                        out[name] = staged
                        continue
                else:
                    waves.append((name, _BatchedShardWave(
                        sharding, plan, streams, views, from_arena)))
                    continue
            streamed.append((name, sharding, plan, streams, donate_ok,
                             views, from_arena))
        if not waves and not streamed:
            return
        items = []
        for i, (_name, wave) in enumerate(waves):
            # Round-robin the submitting stream over the wave's own
            # devices so concurrent fields issue from different threads
            # (the batched put covers every device either way).
            items.append((wave.streams[i % len(wave.streams)], wave, False))
        for _name, _sh, _plan, streams, donate_ok, views, from_arena \
                in streamed:
            for stream, view, unique in zip(streams, views, donate_ok):
                items.append((stream, view, from_arena and unique))
        staged_flat = self._stager.put_shards(items)
        for k, (name, _wave) in enumerate(waves):
            out[name] = staged_flat[k]
        pos = len(waves)
        for name, sharding, plan, streams, _ok, views, _fa in streamed:
            count = len(streams)
            out[name] = jax.make_array_from_single_device_arrays(
                plan.global_shape, sharding,
                list(staged_flat[pos:pos + count]))
            pos += count

    def _batched_assemble(self, sharding, plan, streams, views, from_arena):
        """Inline tier: the global per-device-sharded array in one C++
        batched transfer over the precomputed shard views. ``from_arena``
        feeds the donation accounting (arena sub-slices handed over with
        no loader-side copy; the batched API itself never donates).
        ``None`` means the internal API refused — the caller falls back
        to the stream tier (and we stop asking)."""
        t0 = time.perf_counter()
        try:
            aval = self._shaped_array(plan.global_shape, views[0].dtype)
            staged = self._batched_put(aval, sharding, list(views),
                                       list(plan.devices))
        except Exception:  # noqa: BLE001 - internal API drifted: fall back
            logger.warning(
                'pxla.batched_device_put failed; falling back to per-shard '
                'device_put streams for the rest of this run', exc_info=True)
            self._batched_put = None
            return None
        self._stager.record_inline_wave(
            streams, [v.nbytes for v in views],
            time.perf_counter() - t0, from_arena)
        return staged

    def _decode_raw_columns(self, host_batch):
        """Staging-step JPEG->tensor for raw (encoded-bytes) columns: the
        registered on-device decode op when the backend has one (falling
        back on any failure), else ONE host batched-native call per
        column — spending the WHOLE process decode-thread budget when the
        raw selection covers every image field (the workers then decode
        nothing), else a fair share alongside the still-decoding
        workers."""
        from petastorm_tpu import decode_budget
        from petastorm_tpu.codecs import decode_image_batch_into
        budget = decode_budget.get_budget()
        decode_threads = (budget.total if self._staging_owns_budget
                          else budget.share())
        out = dict(host_batch)
        t0 = time.perf_counter()
        for name, field in self._raw_specs.items():
            column = out.get(name)
            if column is None or getattr(column, 'dtype', None) != np.dtype(object):
                continue   # already dense (e.g. a custom pipeline decoded it)
            hook = _DEVICE_DECODE_HOOK
            if hook is not None:
                try:
                    out[name] = hook(column, tuple(field.shape),
                                     np.dtype(field.numpy_dtype))
                    continue
                except Exception:  # noqa: BLE001 - fall back to host decode
                    logger.warning(
                        'on-device decode hook failed for field %r; host-'
                        'decoding this batch', name, exc_info=True)
            block = np.empty((len(column),) + tuple(field.shape),
                             dtype=field.numpy_dtype)
            decode_image_batch_into(
                field, block, lambda i, _c=column: _c[i],
                decode_threads=decode_threads)
            out[name] = block
        with self._stats_lock:
            self._stage_decode_s += time.perf_counter() - t0
        return out

    def _stage(self, host_batch, arena=None):
        from petastorm_tpu.faults import maybe_inject
        maybe_inject('device-put-delay')
        jax = self._jax
        if self._raw_specs:
            host_batch = self._decode_raw_columns(host_batch)
        out = {}
        pending = []   # per-device sharded fields, dispatched as one wave
        t0 = time.perf_counter()
        nbytes = 0
        # The stager's OverlapMeter: staging batch N+1 counts as 'host'
        # work; its co-activity with the stager's in-flight 'h2d' windows
        # (transfers of batch N still unfenced) is the streamed-path
        # h2d_overlap_frac.
        host_span = (self._stager.meter.track('host')
                     if self._stager is not None
                     and self._stager.meter is not None
                     else contextlib.nullcontext())
        with self._tracer.span('stage', 'device'), host_span:
            for name, array in host_batch.items():
                nbytes += array.nbytes
                if hasattr(array, 'is_ready'):
                    # A device-decode hook already produced a committed
                    # jax array: any re-staging path (process-local-data
                    # assembly, chunked puts, dlpack import) would at
                    # best round-trip it through the host.
                    out[name] = array
                    continue
                chunkable = (self._stage_chunks > 1
                             and array.nbytes >= _STAGE_CHUNK_MIN_BYTES
                             and len(array) >= self._stage_chunks)
                if self._mesh is not None or self._sharding is not None:
                    sharding = self._field_sharding(name)
                    planned = (self._device_shard_plan(name, sharding,
                                                       array.shape)
                               if self._stager is not None else None)
                    if planned is not None:
                        # Per-device sharded path: zero-copy shard views
                        # dispatched on per-device streams (chunked puts
                        # included — _put_shard splits per device), then
                        # stitched into the global array below.
                        plan, streams, donate_ok = planned
                        pending.append((name, sharding, plan, streams,
                                        donate_ok, array))
                    elif chunkable and sharding.num_devices == 1:
                        # No stager (per_device_dispatch=False A/B mode,
                        # or no addressable device): single-device
                        # shardings keep the pre-per-device chunked-put
                        # transport optimization — a one-shot ~39MB put
                        # can wedge a device tunnel permanently.
                        out[name] = self._chunked_put(array, sharding)
                    else:
                        out[name] = jax.make_array_from_process_local_data(
                            sharding, array)
                elif chunkable and not self._dlpack_staging:
                    out[name] = self._chunked_put(array, None)
                elif self._dlpack_staging:
                    # CPU backend: import the host buffer zero-copy via
                    # DLPack. Aliasing is safe because recycling is
                    # deferred until the staged arrays are dropped: arena-
                    # backed batches get GC holds (StagingEngine holds_mode
                    # — an arena is never refilled while any staged array
                    # of it is alive), and non-arena batches (chunk views,
                    # consumer staging) are never written again at all. TPU
                    # backends need the real h2d transfer and take the
                    # device_put branch.
                    try:
                        out[name] = jax.dlpack.from_dlpack(array)
                    except BufferError:
                        # This buffer is unexportable (e.g. read-only):
                        # fall back for THIS array only — one such batch
                        # must not disable zero-copy for the whole run.
                        out[name] = jax.device_put(array)
                    except (TypeError, RuntimeError):
                        self._dlpack_staging = False
                        out[name] = jax.device_put(array)
                else:
                    out[name] = jax.device_put(array)
            if pending:
                self._stage_pending_shards(pending, out, arena)
            if self._augment_fn is not None:
                # Inside the XLA step: the jitted augment consumes the
                # just-staged device arrays asynchronously — its compute
                # overlaps the consumer's step exactly like the transfer.
                out = dict(self._augment_fn(out))
        # Dispatch time only (device_put is async); the transfer itself
        # overlaps the consumer's step. Block-to-measure lives in bench.py.
        with self._stats_lock:
            self._stage_s += time.perf_counter() - t0
            self._staged_bytes += nbytes
        # Prefetch-queue byte accounting (membudget): depth x the latest
        # batch's bytes. Int rebind is atomic; staging thread only.
        self._last_batch_nbytes = nbytes
        self._m_staged_bytes.inc(nbytes)
        return out

    def _next_host_batch(self):
        with self._tracer.span('assemble', 'host'):
            return next(self._host_iter)

    # The staging threads themselves live in ``staging.StagingEngine``
    # (assemble + dispatch); their stop-aware queue discipline — never
    # block indefinitely on a consumer that may already be gone — is
    # inherited from the single-loop stager this engine replaced (a leaked
    # stager holds reader/file objects whose teardown races its final
    # reads; observed as a pyarrow segfault under load).

    # -- consumer --------------------------------------------------------

    def _deliver_stall(self, error):
        """Hard-stall sink (watchdog thread): make the consumer raise the
        diagnosed :class:`PipelineStallError` instead of blocking forever.
        The error rides the staging queue (the consumer is typically parked
        in an untimed ``get()``); a full queue — the consumer-not-draining
        shape — has one stale batch evicted to make room."""
        self._stall_error = error
        for _ in range(2):
            try:
                self._queue.put_nowait(error)
                return
            except queue.Full:
                try:
                    self._queue.get_nowait()
                except queue.Empty:
                    pass
        logger.error('could not deliver PipelineStallError into the staging '
                     'queue; it will surface on the next __next__ call')

    def __iter__(self):
        return self

    def __next__(self):
        if self._exhausted:
            raise StopIteration
        if self._stall_error is not None:
            # Consumer-staging mode (or a failed queue delivery): the
            # watchdog's hard diagnosis still surfaces here.
            self._exhausted = True
            error, self._stall_error = self._stall_error, None
            raise error
        if self._hb_consumer is not None:
            self._hb_consumer.beat('queue-wait')
        t0 = time.perf_counter()
        if self._first_get_t is None:
            self._first_get_t = t0
        fresh = True
        if self._echo_left > 0:
            self._echo_left -= 1
            item = self._echo_item
            fresh = False   # source rows already counted on first delivery
        else:
            if self._consumer_staging:
                # Inline staging (prefetch=0): the consumer thread IS the
                # pipeline, so its heartbeat states must distinguish a
                # starved reader from a hung device_put here too — without
                # the brackets a wedged inline transfer would read as
                # 'queue-wait' (an innocent state) and never classify.
                try:
                    if self._hb_consumer is not None:
                        self._hb_consumer.beat('reader-wait')
                    t_inline = time.perf_counter()
                    host_batch = self._next_host_batch()
                    t_staged = time.perf_counter()
                    self._inline_reader_s += t_staged - t_inline
                    if self._hb_consumer is not None:
                        self._hb_consumer.beat('device_put')
                    item = self._stage(host_batch)
                    self._inline_dispatch_s += time.perf_counter() - t_staged
                except StopIteration:
                    item = _END
                except Exception as e:  # noqa: BLE001 - match staged path
                    item = e
            elif self._ready:
                # Batched pop: a previous fetch drained the staging queue
                # into this consumer-local buffer. Consuming one gives a
                # capacity slot back to the dispatch thread (the drain
                # below converted queue slots into buffer debt, not into
                # refillable capacity).
                item = self._ready.popleft()
                staging_queue = self._queue
                with staging_queue.mutex:
                    staging_queue.maxsize = max(
                        1, self._prefetch_target - len(self._ready))
                    staging_queue.not_full.notify()
            else:
                with self._tracer.span('wait', 'consumer'):
                    item = self._queue.get()
                # Batched pop: move every staged batch into the local
                # buffer under ONE mutex acquisition (vs one Queue.get
                # lock round trip per batch — the warm-cache rate is
                # queue-pop bound, PROFILE_r05 §2). The queue's live
                # maxsize shrinks by the same count (no notify): drained
                # slots must NOT become capacity the dispatch thread
                # refills, or staged-but-undelivered device batches would
                # reach ~2x the documented `prefetch` bound.
                staging_queue = self._queue
                with staging_queue.mutex:
                    while staging_queue.queue:
                        self._ready.append(staging_queue.queue.popleft())
                    staging_queue.maxsize = max(
                        1, self._prefetch_target - len(self._ready))
            if self._echo > 1 and isinstance(item, dict):
                self._echo_item = item
                self._echo_left = self._echo - 1
        batch_wait = time.perf_counter() - t0
        self._wait_s += batch_wait
        self._m_batch_wait.observe(batch_wait)
        if item is _END:
            self._exhausted = True
            if self._hb_consumer is not None:
                self._hb_consumer.beat('idle')   # exhausted, not stalled
            raise StopIteration
        if isinstance(item, Exception):
            self._exhausted = True
            raise item
        names = tuple(sorted(item))
        nt = cached_namedtuple(self._namedtuple_cache, 'JaxBatch', names)
        self._batches_delivered += 1
        self._m_batches.inc()
        if self._lineage is not None and fresh:
            # Mint this batch's provenance record (FIFO against the host-
            # batch iterator's collector pushes — the staging engine
            # preserves delivery order). Echoed re-deliveries reuse the
            # source batch's record.
            self._last_provenance = self._lineage.deliver()
        if self._hb_consumer is not None:
            # 'delivered' + stale = the training loop took this batch and
            # never came back (consumer-not-draining, never escalated).
            self._hb_consumer.beat('delivered')
        # A delivered batch IS recovery: a hard stall diagnosed while this
        # call was in flight (inline staging sleeping through its own
        # escalation) must not kill the pipeline that has since come back.
        # (Staged-path hard stalls ride the queue and still terminate.)
        self._stall_error = None
        if self._row_granular_ckpt and fresh:
            # A padded final batch over-reports by the pad amount; the
            # attribution FIFO simply drains empty, which is correct (the
            # padded copies duplicate rows already attributed). Echoed
            # re-deliveries are not fresh source rows and are never counted.
            if self._defer_rows_consumed:
                # superbatches(): attribution happens when the full group is
                # yielded, and only for the fresh rows actually in it.
                self._pending_fresh_rows += self._local_batch
            else:
                self._reader.rows_consumed(self._local_batch)
        elif self._shuffler is not None and fresh:
            # This batch's draws reached the trainer: release them from
            # the buffer's pending FIFO so only genuinely undelivered
            # draws fold into a checkpoint snapshot. (A padded/short
            # final batch over-reports; mark_delivered drains empty.)
            self._shuffler.mark_delivered(self._local_batch)
        return nt(**{k: item[k] for k in names})

    def superbatches(self, k):
        """Yield ``k``-batch on-device concatenations (for scan training).

        Pairs with ``models.train.make_scan_train_step(microbatches=k)``:
        transfers stay at the per-batch size (large single h2d events can be
        pathological on some interconnects) while the training loop pays one
        Python dispatch per ``k`` optimizer steps. The final incomplete
        group (fewer than ``k`` batches at end of data) is dropped — sizes
        stay static for XLA. Checkpoint row accounting happens per *yielded
        group*, so a dropped partial group's rows are NOT counted consumed
        and re-deliver on resume (exactly-once holds here too).
        """
        if k <= 1:
            yield from self
            return
        jax = self._jax
        # NOT jnp.concatenate: this jaxlib's SPMD concat lowering sums
        # replicas on partially-replicated meshes (see
        # parallel.mesh.replica_safe_concat).
        from petastorm_tpu.parallel.mesh import replica_safe_concat
        concat = jax.jit(lambda *xs: replica_safe_concat(xs))
        it = iter(self)

        def fetch():
            # Deferral is scoped to this call alone, so interleaved direct
            # loader iteration (or an abandoned generator) keeps normal
            # immediate accounting.
            self._defer_rows_consumed = True
            try:
                return next(it)
            finally:
                self._defer_rows_consumed = False

        while True:
            parts = []
            try:
                for _ in range(k):
                    parts.append(fetch())
            except StopIteration:
                # Partial tail group: dropped, and its fresh rows stay
                # unattributed — they re-deliver on resume.
                return
            if self._row_granular_ckpt and self._pending_fresh_rows:
                self._reader.rows_consumed(self._pending_fresh_rows)
                self._pending_fresh_rows = 0
            yield parts[0]._replace(
                **{f: concat(*[getattr(p, f) for p in parts])
                   for f in parts[0]._fields})

    def reset_stats(self):
        """Zero the stall counters — call after warmup so ``stats`` reflects
        the steady-state window, not reader-pool spin-up."""
        self._batches_delivered = 0
        self._wait_s = 0.0
        self._inline_reader_s = 0.0
        self._inline_dispatch_s = 0.0
        self._first_get_t = None
        with self._stats_lock:
            self._stage_s = 0.0
            self._staged_bytes = 0
            self._stage_decode_s = 0.0
        if self._engine is not None:
            self._engine.reset_stats()
        if self._stager is not None:
            self._stager.reset_stats()
        if self._arena_pool is not None:
            self._arena_pool.reset_stats()
        if self._metered_reader is not None:
            # Unlocked against the assembler's += (a concurrent pull could
            # resurrect one pre-reset sample) — stats noise, not state.
            self._metered_reader.reader_wait_s = 0.0

    @property
    def stats(self):
        """Input-pipeline health: delivered batches, seconds spent blocked
        waiting for the staging thread, and the stall fraction (blocked time /
        wall time since the first fetch). A training loop with
        ``input_stall_frac`` above ~0.05 is input-bound (BASELINE.json's
        <5% target) — raise ``workers_count``/``prefetch`` or speed up decode.

        ``reader_diagnostics`` carries the reader's robustness state through
        to the training loop: ``worker_respawns`` (dead pool workers that
        were respawned) and ``quarantined_rowgroups`` (poison row-groups
        skipped under ``error_budget`` — see ``docs/failure_model.rst``).
        """
        elapsed = (time.perf_counter() - self._first_get_t
                   if self._first_get_t is not None else 0.0)
        with self._stats_lock:
            stage_s, staged_bytes = self._stage_s, self._staged_bytes
            stage_decode_s = self._stage_decode_s
        out = {'batches': self._batches_delivered,
               'wait_s': round(self._wait_s, 4),
               'input_stall_frac': round(self._wait_s / elapsed, 4) if elapsed else 0.0,
               'stage_dispatch_s': round(stage_s, 4),
               'staged_bytes': staged_bytes,
               'reader_diagnostics': self._reader.diagnostics}
        if self._raw_specs:
            # Staging-step decode seconds of the on-device path (host
            # fallback; 0 when a device decode op carried the batches).
            out['stage_decode_s'] = round(stage_decode_s, 4)
        if self._engine is not None:
            # Pipeline shape of the staging engine: per-stage busy seconds,
            # how much of the smaller stage ran concurrently with the other
            # (overlap_frac — the software-pipelining win), and time spent
            # fenced on the oldest in-flight transfer (ready_wait_s).
            out.update(self._engine.stats())
        if self._stager is not None:
            # Per-device dispatch health: stream count (n_devices — the
            # real data-parallel fan-out, not a dryrun), per-device put
            # seconds/bytes (the bench's per-device h2d_GBps basis),
            # shards donated (zero-copy handoffs), and per-stream window
            # fences.
            stager_stats = self._stager.stats()
            stager_stats['device_put_leaked_threads'] = \
                stager_stats.pop('leaked_threads')
            out.update(stager_stats)
        if self._metered_reader is not None:
            # Seconds the assembler spent blocked pulling from the reader —
            # the reader-starved signal (pairs with arena_wait_s /
            # ready_wait_s to name the bottleneck stage).
            out['reader_wait_s'] = round(self._metered_reader.reader_wait_s, 4)
        if self._arena_pool is not None:
            # Arena recycling health: after warmup ``arena_alloc`` should
            # stay flat (near-zero new allocations) with ``arena_reuse``
            # climbing; ``arena_wait_s`` is assembler backpressure.
            out.update(self._arena_pool.stats())
        store = getattr(self._reader, 'chunk_store', None)
        if store is not None:
            # NVMe decoded-chunk tier health: hits/misses/fills say whether
            # epoch-N decode is actually dead; write-behind counters
            # (writes, skipped, throttled) cover the epoch-0 spill.
            out['chunk_store'] = store.stats()
        worker_timings = getattr(self._reader, 'stage_timings', None)
        if worker_timings:
            out['worker_stage_timings'] = {
                k: (round(v, 4) if isinstance(v, float) else v)
                for k, v in worker_timings.items()}
        if self._health is not None:
            # Stall supervision: detections/recoveries/hard escalations and
            # the latest diagnosis (classification, stage, beat table,
            # probes — the stack dump stays on the error object).
            out['watchdog'] = self._health.stats()
        if self._autotuner is not None:
            # Feedback control: current knob values, the full decision log
            # (grow/shrink/revert/pause with bottleneck classifications),
            # and the knob trajectory over time.
            out['autotune'] = self._autotuner.stats()
        if self._lineage is not None:
            # Provenance ledger health: records minted vs dropped, the
            # write-behind lag, and where the ledger landed on disk.
            out['lineage'] = self._lineage.stats()
        if self._device_cache is not None:
            # HBM-resident dataset tier (device_cache.DeviceDatasetCache
            # attached itself): cached bytes/superbatches, hit/eviction
            # counts, and whether the governor paused or stopped the fill.
            out['device_cache'] = self._device_cache.stats()
        from petastorm_tpu import membudget as membudget_mod
        governor = membudget_mod.get_governor()
        if governor.armed:
            # Memory governor: budget, ladder position + peaks, per-pool
            # bytes, degrade-action counts (the bench's `mem` block).
            out['mem'] = governor.stats()
        return out

    @property
    def last_batch_provenance(self):
        """The provenance record of the most recently delivered batch
        (``None`` when ``lineage`` is unarmed): batch id, source spans,
        serving tiers, shuffle state, content digest. See
        ``petastorm_tpu.lineage``."""
        return self._last_provenance

    @property
    def lineage_tracker(self):
        """The loader's :class:`~petastorm_tpu.lineage.LineageTracker`
        (``None`` when unarmed) — ring access for tests and the bench's
        replay self-check."""
        return self._lineage

    def state_dict(self):
        """Mid-epoch resume state (see ``Reader.state_dict``).

        Capture at a batch boundary and rebuild via
        ``make_reader(..., resume_state=state)`` + a new JaxLoader. Resume
        never replays a delivered batch. Row accounting depends on the
        pipeline shape:

        * **Batched reader, no shuffling buffer** (the TPU default): the
          loader enables row-granular accounting — rows still sitting in the
          prefetch queue at checkpoint time are NOT counted consumed and
          re-deliver on resume. Exactly-once AND no loss, any epoch count.
        * **Shuffling buffer engaged**: rows buffered in it count as
          consumed, but the buffer itself rides the state
          (``state['shuffling_buffer']``: rows + RNG state — binary-safe
          through ``JobCheckpointer``, which pickles non-JSON loader
          states): rebuild the loader with ``resume_state=`` the same dict
          and the buffered rows re-deliver with the draw sequence intact.
          Rows inside a partially-assembled batch (fewer than
          ``batch_size``) still follow chunk-level semantics.
        * **Per-row readers without a buffer**: rows buffered downstream
          count as consumed; with ``num_epochs=None`` they come around on
          a later epoch.
        """
        if self._shuffler is not None \
                and hasattr(self._shuffler, 'state_dict'):
            # Atomic against _commit_rows: without the lock, rows moving
            # reader->buffer between the two snapshots would appear in
            # both (re-delivered twice on resume) or neither (lost).
            with self._ckpt_lock:
                state = dict(self._reader.state_dict())
                state['shuffling_buffer'] = self._shuffler.state_dict()
            return state
        return self._reader.state_dict()

    def _commit_rows(self, rows):
        """Move one chunk's rows into the shuffling buffer and advance the
        reader's checkpoint cursor as one atomic step (the assemble
        thread's side of the ``state_dict`` lock)."""
        with self._ckpt_lock:
            self._shuffler.add_many(rows)
            if self._buffer_entry_ckpt:
                self._reader.rows_consumed(len(rows))

    def stop(self):
        from petastorm_tpu import membudget as membudget_mod
        governor = membudget_mod.get_governor()
        for handle in self._mem_handles:
            handle.close()
        governor.remove_breach_sink(self._mem_breach_sink)
        if self._mem_armed:
            self._mem_armed = False
            governor.release()
        # Tuner first (a tuner firing mid-teardown would retune stages
        # that are being joined), then the watchdog (which would misread
        # the deliberately silent stages as a stall) — the order the
        # shared supervisor owns.
        # _health/_autotuner stay referenced: stats() remains readable
        # post-stop (post-mortems read stats['watchdog'] after teardown).
        self._supervisor.stop()
        self._stop.set()
        self._exhausted = True
        # Drain so the staging threads' bounded puts can exit.
        self._ready.clear()
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        if self._engine is not None:
            self._engine.stop()
        if self._stager is not None:
            # After the engine: the dispatch thread must stop submitting
            # waves before the per-device streams join.
            self._stager.stop()
        if self._thread is not None:
            self._thread.join(timeout=10)
        if self._lineage is not None:
            if self._lineage_owned:
                # Drain + close the ledger write-behind (don't leave a
                # daemon writer spilling into a directory the caller may
                # be deleting).
                self._lineage.close()
            else:
                # Adopted tracker: the caller owns its lifecycle (it may
                # ledger another loader next) — just drain what this
                # loader produced.
                self._lineage.flush()
        self._reader.stop()
        self._reader.join()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        return False


def make_jax_loader(reader, batch_size, **kwargs):
    """Factory mirroring the reference adapter entry points
    (``tf_utils.tf_tensors`` / ``pytorch.DataLoader``)."""
    return JaxLoader(reader, batch_size, **kwargs)
