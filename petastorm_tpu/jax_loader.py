"""JAX loader: the TPU-native framework adapter (the point of the project).

The reference feeds TF via ``tf_utils.py`` and torch via ``pytorch.py``
(SURVEY.md §2.6). This module is their TPU equivalent, designed per
SURVEY.md §7.6:

  * fixed-size batch re-chunking of row-group output (the reference's
    ``BatchingTableQueue`` idea, ``pyarrow_helpers/batching_table_queue.py``),
  * optional seeded row-level shuffling (``RandomShufflingBuffer``),
  * dtype sanitization to TPU-supported dtypes (cf. ``pytorch.py:36-66`` /
    ``tf_utils.py:58-97``),
  * ragged-field shape policies (pad/crop) because XLA needs static shapes —
    a decision the reference never had to make (SURVEY.md §7 "Hard parts"),
  * device staging: ``jax.make_array_from_process_local_data`` onto a
    ``Mesh``-sharded layout (each pod host contributes its disjoint reader
    shard), or plain ``device_put`` single-chip,
  * a double-buffered background prefetcher so host->HBM transfer of batch
    N+1 hides under XLA step N.
"""

import logging
import queue
import threading
import time
import warnings

import numpy as np

from petastorm_tpu.utils import cached_namedtuple

logger = logging.getLogger(__name__)

_END = object()

# Fields smaller than this stage as one put even under stage_chunks>1:
# chunking a 1KB label column costs k round trips for nothing.
_STAGE_CHUNK_MIN_BYTES = 4 << 20


# --------------------------------------------------------------------------
# shape policies
# --------------------------------------------------------------------------

class ShapePolicy(object):
    """How to give a ragged field a static shape."""

    def apply(self, array):
        raise NotImplementedError


class PadTo(ShapePolicy):
    """Pad (and clip) every sample to ``target_shape`` with ``fill_value``."""

    def __init__(self, target_shape, fill_value=0):
        self.target_shape = tuple(target_shape)
        self.fill_value = fill_value

    def apply(self, array):
        array = np.asarray(array)
        if array.shape == self.target_shape:
            return array
        out = np.full(self.target_shape, self.fill_value, dtype=array.dtype)
        slices = tuple(slice(0, min(a, t)) for a, t in zip(array.shape, self.target_shape))
        out[slices] = array[slices]
        return out


class CropTo(ShapePolicy):
    """Center-crop every sample to ``target_shape`` (must fit)."""

    def __init__(self, target_shape):
        self.target_shape = tuple(target_shape)

    def apply(self, array):
        array = np.asarray(array)
        if array.shape == self.target_shape:
            return array
        starts = [(a - t) // 2 for a, t in zip(array.shape, self.target_shape)]
        if any(s < 0 for s in starts):
            raise ValueError('CropTo{}: sample shape {} too small'.format(
                self.target_shape, array.shape))
        slices = tuple(slice(s, s + t) for s, t in zip(starts, self.target_shape))
        return array[slices]


# --------------------------------------------------------------------------
# dtype sanitization
# --------------------------------------------------------------------------

def _sanitize_dtype(np_dtype, x64=False):
    """Map a numpy dtype to its TPU-friendly dtype (or None if unsupported).

    Parity role: reference ``pytorch.py:36-66`` / ``tf_utils.py:58-97``.
    """
    np_dtype = np.dtype(np_dtype)
    if np_dtype.kind in ('O', 'U', 'S'):
        return None
    if np_dtype.kind == 'M':
        # datetime64 -> ns-epoch int64. Without x64 the values cannot be
        # represented (int32 would wrap) — treat as unsupported rather than
        # silently corrupt.
        return np.dtype('int64') if x64 else None
    if not x64:
        if np_dtype == np.float64:
            return np.dtype('float32')
        if np_dtype == np.int64:
            return np.dtype('int32')
        if np_dtype == np.uint64:
            return np.dtype('uint32')
    return np_dtype


def _sanitize_array(array, x64=False):
    array = np.asarray(array)
    target = _sanitize_dtype(array.dtype, x64)
    if target is None:
        return None
    if array.dtype.kind == 'M':
        array = array.astype('datetime64[ns]').astype(np.int64)
    return np.ascontiguousarray(array.astype(target, copy=False))


# --------------------------------------------------------------------------
# host-side batch assembly (no jax dependency — independently testable)
# --------------------------------------------------------------------------

def iter_numpy_batches(reader, batch_size, shape_policies=None,
                       shuffling_queue_capacity=0, min_after_dequeue=None,
                       seed=None, last_batch='drop', x64=False,
                       strict_fields=False):
    """Yield dicts of numpy arrays with exact leading dim ``batch_size``.

    Works over both row readers (``make_reader``) and batch readers
    (``make_batch_reader``); re-chunks row-group-sized output into fixed
    batches. ``last_batch``: 'drop' | 'pad' (repeat-pad the final partial
    batch) | 'partial' (yield it short). ``strict_fields=True`` raises
    instead of warn-and-drop when a selected field cannot batch (e.g. a
    nullable-declared field that is never actually null) — pass
    ``schema_fields`` excluding it, or a TransformSpec redeclaring it
    non-nullable, to proceed.
    """
    if last_batch not in ('drop', 'pad', 'partial'):
        raise ValueError("last_batch must be drop|pad|partial, got {!r}".format(last_batch))
    shape_policies = dict(shape_policies or {})

    field_names = None
    dropped = set()
    columns = {}
    count = 0

    shuffler = None
    if shuffling_queue_capacity and shuffling_queue_capacity > 0:
        from petastorm_tpu.shuffling_buffer import RandomShufflingBuffer
        if min_after_dequeue is None:
            min_after_dequeue = shuffling_queue_capacity * 4 // 5
        shuffler = RandomShufflingBuffer(shuffling_queue_capacity,
                                         min_after_dequeue, seed=seed,
                                         extra_capacity=100000)

    def _is_tensor_like(probe, name):
        """True if a sample value can become a TPU tensor (possibly via policy)."""
        if probe is None:
            # Field with None values cannot batch; dropped with a warning.
            # (A later None in a kept field raises a clear error in
            # _stack_column.) Fill nullables via TransformSpec to keep them.
            return False
        arr = np.asarray(probe)
        if arr.dtype.kind not in ('O', 'U', 'S'):
            return True
        # Object values may still be numeric ndarrays (ragged) — keep when a
        # shape policy exists, or when the payload itself is numeric.
        if isinstance(probe, np.ndarray) and probe.dtype.kind not in ('O', 'U', 'S'):
            return True
        return name in shape_policies

    schema = getattr(reader, 'transformed_schema', None)

    def _declared_nullable(name):
        # Row readers carry a deliberate Unischema: its nullable flag is
        # authoritative (batch readers infer schemas where arrow marks nearly
        # everything nullable, so probing is used there instead). A
        # TransformSpec that fills nulls can redeclare the field with
        # nullable=False via edit_fields to keep it.
        return (not reader.batched_output and schema is not None
                and name in schema.fields and schema.fields[name].nullable)

    def select_fields(sample):
        nonlocal field_names
        names = []
        for name in sample._fields:
            value = getattr(sample, name)
            if reader.batched_output:
                column = np.asarray(value)
                probe = column[0] if (column.dtype.kind == 'O' and len(column)) else column
            else:
                probe = value
            if not _declared_nullable(name) and _is_tensor_like(probe, name):
                names.append(name)
            else:
                dropped.add(name)
        if dropped:
            if strict_fields:
                raise ValueError(
                    'jax loader cannot batch fields: {} (nullable-declared or '
                    'non-tensor). With strict_fields=True this is an error; '
                    'narrow schema_fields, fill nulls via a TransformSpec that '
                    'redeclares the field nullable=False, or pass '
                    'strict_fields=False to drop them with a warning.'.format(
                        sorted(dropped)))
            warnings.warn('jax loader dropping non-tensor fields: {} '
                          '(select fields explicitly or add a TransformSpec '
                          'to keep them)'.format(sorted(dropped)))
        field_names = names

    def to_rows(sample):
        """Batched sample -> per-row tuples (reference pytorch.py:166-175)."""
        cols = [getattr(sample, n) for n in field_names]
        return list(zip(*cols))

    def add_sample_columns(sample):
        nonlocal count
        for name in field_names:
            value = getattr(sample, name)
            columns.setdefault(name, []).append(value)
        count += 1

    def emit_batches(final=False):
        nonlocal columns, count
        while count >= batch_size:
            batch = {}
            for name in field_names:
                batch[name] = _stack_column(columns[name][:batch_size], name,
                                            shape_policies, x64)
                columns[name] = columns[name][batch_size:]
            count -= batch_size
            yield batch
        if final and count:
            if last_batch == 'drop':
                columns = {}
                count = 0
            elif last_batch in ('pad', 'partial'):
                batch = {}
                for name in field_names:
                    col = columns[name]
                    if last_batch == 'pad':
                        col = col + [col[-1]] * (batch_size - len(col))
                    batch[name] = _stack_column(col, name, shape_policies, x64)
                columns = {}
                count = 0
                yield batch

    if getattr(reader, 'batched_output', False) and shuffler is None:
        # Block fast path: batched readers (tensor/arrow) without row-level
        # shuffling never transpose to per-row tuples — column blocks are
        # sliced/concatenated directly, one memcpy per batch at most (zero
        # when a batch lies inside one chunk). This is the decoded-columnar
        # hot path (VERDICT r2 #1); the reference's closest analog is the
        # unused BatchingTableQueue re-chunker
        # (``pyarrow_helpers/batching_table_queue.py:20-79``).
        yield from _iter_block_batches(reader, batch_size, shape_policies,
                                       last_batch, x64, strict_fields)
        return

    for sample in reader:
        if field_names is None:
            select_fields(sample)
        if reader.batched_output:
            rows = to_rows(sample)
        else:
            rows = [tuple(getattr(sample, n) for n in field_names)]
        if shuffler is not None:
            shuffler.add_many(rows)
            while shuffler.can_retrieve():
                row = shuffler.retrieve()
                for name, value in zip(field_names, row):
                    columns.setdefault(name, []).append(value)
                count += 1
                if count >= batch_size:
                    yield from emit_batches()
        else:
            for row in rows:
                for name, value in zip(field_names, row):
                    columns.setdefault(name, []).append(value)
                count += 1
            yield from emit_batches()

    if shuffler is not None:
        shuffler.finish()
        while shuffler.can_retrieve():
            row = shuffler.retrieve()
            for name, value in zip(field_names, row):
                columns.setdefault(name, []).append(value)
            count += 1
        yield from emit_batches(final=True)
    else:
        yield from emit_batches(final=True)


def _iter_block_batches(reader, batch_size, shape_policies, last_batch, x64,
                        strict_fields):
    """Fixed-size batches assembled from column blocks (no per-row Python).

    Chunks (one per row-group) are sanitized once on arrival; batches are
    built from leading-dim slices — a contiguous view when one chunk covers
    the batch, else one ``np.concatenate`` memcpy.
    """
    shape_policies = dict(shape_policies or {})
    field_names = None
    dropped = []
    chunks = []          # list of dicts name -> array (sanitized, same length)
    have = 0

    def densify(name, arr):
        """Object (ragged) columns become dense via per-row policy+stack;
        a policy on an already-dense column still applies per row (same
        semantics as the per-row ``_stack_column`` path)."""
        arr = np.asarray(arr)
        policy = shape_policies.get(name)
        if arr.dtype.kind != 'O':
            if policy is None:
                return arr
            return np.stack([policy.apply(v) for v in arr])
        values = [policy.apply(v) for v in arr] if policy is not None else list(arr)
        if any(v is None for v in values):
            raise ValueError(
                'Field {!r} contains None (nullable) values; fill or drop them '
                'with a TransformSpec before batching for TPU'.format(name))
        try:
            return np.stack([np.asarray(v) for v in values])
        except ValueError as e:
            raise ValueError(
                'Field {!r} has ragged shapes and no shape policy; pass '
                "shape_policies={{'{}': PadTo(...)}} or CropTo(...): {}".format(
                    name, name, e)) from e

    def select(sample):
        names = []
        for name in sample._fields:
            column = np.asarray(getattr(sample, name))
            probe = column[0] if (column.dtype.kind == 'O' and len(column)) else column
            arr = np.asarray(probe)
            ok = arr.dtype.kind not in ('O', 'U', 'S') or name in shape_policies
            if ok:
                names.append(name)
            else:
                dropped.append(name)
        if dropped:
            if strict_fields:
                raise ValueError(
                    'jax loader cannot batch fields: {} (non-tensor). Narrow '
                    'schema_fields or pass strict_fields=False to drop them '
                    'with a warning.'.format(sorted(dropped)))
            warnings.warn('jax loader dropping non-tensor fields: {}'.format(
                sorted(dropped)))
        if not names:
            raise ValueError('No batchable fields left (all dropped: {})'.format(
                sorted(dropped)))
        return names

    def take(n):
        """Pop ``n`` leading rows across chunks -> dict of arrays."""
        nonlocal have
        parts = {name: [] for name in field_names}
        need = n
        while need > 0:
            head = chunks[0]
            rows = len(head[field_names[0]])
            if rows <= need:
                for name in field_names:
                    parts[name].append(head[name])
                chunks.pop(0)
                need -= rows
            else:
                for name in field_names:
                    parts[name].append(head[name][:need])
                chunks[0] = {name: head[name][need:] for name in field_names}
                need = 0
        have -= n
        return {name: (p[0] if len(p) == 1 else np.concatenate(p))
                for name, p in ((name, parts[name]) for name in field_names)}

    for sample in reader:
        if field_names is None:
            field_names = select(sample)
        chunk = {}
        for name in field_names:
            arr = densify(name, getattr(sample, name))
            arr = _sanitize_array(arr, x64)
            if arr is None:
                raise ValueError('Field {!r} dtype is not TPU-compatible'.format(name))
            chunk[name] = arr
        chunks.append(chunk)
        have += len(chunk[field_names[0]]) if field_names else 0
        while have >= batch_size:
            yield take(batch_size)

    if have and field_names:
        if last_batch == 'partial':
            yield take(have)
        elif last_batch == 'pad':
            short = take(have)
            pad = batch_size - len(short[field_names[0]])
            yield {name: np.concatenate(
                [arr] + [arr[-1:]] * pad) for name, arr in short.items()}


def _stack_column(values, name, shape_policies, x64):
    if any(v is None for v in values):
        raise ValueError(
            'Field {!r} contains None (nullable) values; fill or drop them with a '
            'TransformSpec before batching for TPU'.format(name))
    policy = shape_policies.get(name)
    if policy is not None:
        values = [policy.apply(v) for v in values]
    try:
        stacked = np.stack([np.asarray(v) for v in values])
    except ValueError as e:
        raise ValueError(
            'Field {!r} has ragged shapes and no shape policy; pass '
            "shape_policies={{'{}': PadTo(...)}} or CropTo(...): {}".format(
                name, name, e)) from e
    sanitized = _sanitize_array(stacked, x64)
    if sanitized is None:
        raise ValueError('Field {!r} dtype {} is not TPU-compatible'.format(
            name, stacked.dtype))
    return sanitized


# --------------------------------------------------------------------------
# device staging + prefetch
# --------------------------------------------------------------------------

class JaxLoader(object):
    """Iterates mesh-sharded ``jax.Array`` batches off a Reader.

    :param reader: a ``make_reader``/``make_batch_reader`` Reader (each pod
        host should construct it with ``cur_shard=jax.process_index()``).
    :param batch_size: **global** batch size when ``mesh``/``sharding`` is
        given (each host contributes ``batch_size / process_count`` rows);
        plain host batch size otherwise.
    :param mesh: ``jax.sharding.Mesh`` — batches are sharded over its 'data'
        axis (override via ``sharding``).
    :param sharding: explicit ``NamedSharding`` (or dict field->sharding).
    :param prefetch: device batches staged ahead (double-buffering default 2).
        ``0`` disables the background staging thread entirely: host batches
        are assembled ahead by the reader's worker pool as usual, but the
        ``device_put`` happens inline in the consumer thread. Use on
        interconnects where background transfers interleaved with compute
        are pathological (see docs/troubleshoot.rst).
    :param shape_policies: dict field -> ShapePolicy for ragged fields.
    :param last_batch: 'drop' (pod-safe default) | 'pad' | 'partial'.
    :param strict_fields: raise (instead of warn-and-drop) when a selected
        field cannot batch — e.g. declared nullable but never actually null.
    :param tracer: a ``trace.Tracer`` to record assemble/stage/wait spans
        into a chrome://tracing timeline (default ``NullTracer``, no-op).
    :param echo: data echoing (Choi et al., "Faster Neural Network Training
        with Data Echoing"): deliver each staged batch ``echo`` times. When
        the pipeline is input-bound (``input_stall_frac`` high) echoed
        repeats trade statistical efficiency for step throughput — the chip
        trains instead of idling. Epoch/checkpoint accounting counts source
        rows once; ``stats['batches']`` counts echoed deliveries.
    :param stage_chunks: split each ``>=4MB`` field into this many
        ``device_put`` events along the batch dim and concatenate on device.
        On high-latency host<->device links (device tunnels) several ~5MB
        puts sustain ~2x the bandwidth of one ~20MB put (measured on an
        axon-tunneled v5e); on direct PCIe hosts leave it at 1. Single-
        device targets only — multi-device shardings keep the one-shot
        ``make_array_from_process_local_data`` path.
    """

    def __init__(self, reader, batch_size, mesh=None, sharding=None,
                 batch_axis='data', prefetch=2, shape_policies=None,
                 shuffling_queue_capacity=0, min_after_dequeue=None, seed=None,
                 last_batch='drop', strict_fields=False, echo=1, tracer=None,
                 stage_chunks=1):
        import jax

        if tracer is None:
            from petastorm_tpu.trace import NullTracer
            tracer = NullTracer()
        self._tracer = tracer

        self._reader = reader
        self._mesh = mesh
        self._sharding = sharding
        self._batch_axis = batch_axis
        self._jax = jax
        x64 = bool(jax.config.jax_enable_x64)

        if mesh is not None or sharding is not None:
            n_proc = jax.process_count()
            if batch_size % n_proc:
                raise ValueError('global batch_size {} not divisible by process_count {}'
                                 .format(batch_size, n_proc))
            local_batch = batch_size // n_proc
        else:
            local_batch = batch_size
        self._global_batch = batch_size
        self._local_batch = local_batch

        if last_batch == 'partial' and (mesh is not None or sharding is not None):
            raise ValueError("last_batch='partial' breaks fixed global shapes on a mesh; "
                             "use 'drop' or 'pad'")

        # Without a row-level shuffle, rows are consumed in exact delivery
        # order, so checkpoint accounting can be deferred to actual batch
        # delivery (rows sitting in the prefetch queue at checkpoint time are
        # NOT counted consumed and re-deliver on resume).
        self._row_granular_ckpt = False
        self._defer_rows_consumed = False   # superbatches() group accounting
        self._pending_fresh_rows = 0        # fresh rows fetched but not yet
                                            # attributed (deferred mode)
        if not shuffling_queue_capacity and hasattr(reader, 'enable_row_granular_checkpoint'):
            self._row_granular_ckpt = reader.enable_row_granular_checkpoint()

        self._host_iter = iter_numpy_batches(
            reader, local_batch, shape_policies=shape_policies,
            shuffling_queue_capacity=shuffling_queue_capacity,
            min_after_dequeue=min_after_dequeue, seed=seed,
            last_batch=last_batch, x64=x64, strict_fields=strict_fields)

        if echo < 1:
            raise ValueError('echo must be >= 1, got {}'.format(echo))
        self._echo = int(echo)
        self._echo_left = 0
        self._echo_item = None
        self._consumer_staging = prefetch == 0
        self._queue = queue.Queue(maxsize=max(1, prefetch))
        self._stop = threading.Event()
        self._exhausted = False
        self._namedtuple_cache = {}
        # input-stall accounting (BASELINE.json targets <5% input stall)
        self._batches_delivered = 0
        self._wait_s = 0.0
        self._first_get_t = None
        # staging accounting (VERDICT r1 #4: measure copy/transfer cost).
        # Written by the staging thread, reset by the consumer — lock both.
        self._stats_lock = threading.Lock()
        self._stage_s = 0.0
        self._staged_bytes = 0
        try:
            self._dlpack_staging = jax.default_backend() == 'cpu'
        except Exception:  # noqa: BLE001 - backend probe must not kill init
            self._dlpack_staging = False
        # Transport optimization for high-latency host<->device links (the
        # axon tunnel sustains ~2x the throughput at ~5MB transfers vs one
        # ~20MB put — measured, PROFILE_r05 §6): split each field along the
        # batch dim into `stage_chunks` device_puts and concatenate on
        # device. Only taken when the target is a single device (multi-
        # device shardings keep the one-shot path — real pod hosts move
        # h2d over PCIe where one large transfer is optimal).
        self._stage_chunks = max(1, int(stage_chunks))
        self._stage_concat = None
        if self._stage_chunks > 1:
            import jax.numpy as jnp
            self._stage_concat = jax.jit(lambda *xs: jnp.concatenate(xs))
        # Start the stager LAST: it touches the state above immediately.
        if self._consumer_staging:
            self._thread = None
        else:
            self._thread = threading.Thread(target=self._stage_loop, daemon=True)
            self._thread.start()

    # -- staging thread --------------------------------------------------

    def _field_sharding(self, name):
        if self._sharding is not None:
            if isinstance(self._sharding, dict):
                return self._sharding[name]
            return self._sharding
        from petastorm_tpu.parallel.mesh import batch_sharding
        return batch_sharding(self._mesh, self._batch_axis)

    def _chunked_put(self, array, sharding):
        """Split along the batch dim, put each piece, concatenate on device.
        Wins ~2x on high-latency tunnels (see ``stage_chunks``); only called
        for single-device targets where per-piece puts are trivially valid.
        ``stage_chunks`` is a minimum: pieces are further split to stay
        under ~8MB each — single ~39MB puts have been observed to wedge
        device tunnels permanently, and a bigger batch or f32 field must
        not silently cross that line."""
        jax = self._jax
        n_chunks = max(self._stage_chunks, -(-array.nbytes // (8 << 20)))
        parts = np.array_split(array, min(n_chunks, len(array)))
        if sharding is not None:
            staged = [jax.device_put(p, sharding) for p in parts]
        else:
            staged = [jax.device_put(p) for p in parts]
        return self._stage_concat(*staged)

    def _stage(self, host_batch):
        jax = self._jax
        out = {}
        t0 = time.perf_counter()
        nbytes = 0
        with self._tracer.span('stage', 'device'):
            for name, array in host_batch.items():
                nbytes += array.nbytes
                chunkable = (self._stage_chunks > 1
                             and array.nbytes >= _STAGE_CHUNK_MIN_BYTES
                             and len(array) >= self._stage_chunks)
                if self._mesh is not None or self._sharding is not None:
                    sharding = self._field_sharding(name)
                    if chunkable and sharding.num_devices == 1:
                        out[name] = self._chunked_put(array, sharding)
                    else:
                        out[name] = jax.make_array_from_process_local_data(
                            sharding, array)
                elif chunkable and not self._dlpack_staging:
                    out[name] = self._chunked_put(array, None)
                elif self._dlpack_staging:
                    # CPU backend: import the host buffer zero-copy via
                    # DLPack (batch buffers are freshly assembled, never
                    # mutated after staging, so aliasing is safe). TPU
                    # backends need the real h2d transfer and take the
                    # device_put branch.
                    try:
                        out[name] = jax.dlpack.from_dlpack(array)
                    except (TypeError, BufferError, RuntimeError):
                        self._dlpack_staging = False
                        out[name] = jax.device_put(array)
                else:
                    out[name] = jax.device_put(array)
        # Dispatch time only (device_put is async); the transfer itself
        # overlaps the consumer's step. Block-to-measure lives in bench.py.
        with self._stats_lock:
            self._stage_s += time.perf_counter() - t0
            self._staged_bytes += nbytes
        return out

    def _next_host_batch(self):
        with self._tracer.span('assemble', 'host'):
            return next(self._host_iter)

    def _stage_loop(self):
        try:
            while True:
                try:
                    host_batch = self._next_host_batch()
                except StopIteration:
                    break
                if self._stop.is_set():
                    return
                staged = self._stage(host_batch)
                while not self._stop.is_set():
                    try:
                        self._queue.put(staged, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._stop.is_set():
                    return  # don't fetch another batch into a stopping pipe
        except Exception as e:  # noqa: BLE001 - surfaced to consumer
            self._put_stop_aware(e)
            return
        self._put_stop_aware(_END)

    def _put_stop_aware(self, obj):
        # NEVER block indefinitely on the consumer queue: if the consumer is
        # gone (stop() already drained and moved on) an unbounded put leaks
        # this staging thread forever — it then holds reader/file objects
        # whose teardown races its final reads (observed as a pyarrow
        # segfault under load).
        while not self._stop.is_set():
            try:
                self._queue.put(obj, timeout=0.1)
                return
            except queue.Full:
                continue
        # Stopping: still attempt one non-blocking put — a consumer already
        # parked in an untimed queue.get() (stop() called from another
        # thread) needs the sentinel to wake up; if the queue is full the
        # consumer isn't blocked and the exhausted flag ends it instead.
        try:
            self._queue.put_nowait(obj)
        except queue.Full:
            pass

    # -- consumer --------------------------------------------------------

    def __iter__(self):
        return self

    def __next__(self):
        if self._exhausted:
            raise StopIteration
        t0 = time.perf_counter()
        if self._first_get_t is None:
            self._first_get_t = t0
        fresh = True
        if self._echo_left > 0:
            self._echo_left -= 1
            item = self._echo_item
            fresh = False   # source rows already counted on first delivery
        else:
            if self._consumer_staging:
                try:
                    item = self._stage(self._next_host_batch())
                except StopIteration:
                    item = _END
                except Exception as e:  # noqa: BLE001 - match staged path
                    item = e
            else:
                with self._tracer.span('wait', 'consumer'):
                    item = self._queue.get()
            if self._echo > 1 and isinstance(item, dict):
                self._echo_item = item
                self._echo_left = self._echo - 1
        self._wait_s += time.perf_counter() - t0
        if item is _END:
            self._exhausted = True
            raise StopIteration
        if isinstance(item, Exception):
            self._exhausted = True
            raise item
        names = tuple(sorted(item))
        nt = cached_namedtuple(self._namedtuple_cache, 'JaxBatch', names)
        self._batches_delivered += 1
        if self._row_granular_ckpt and fresh:
            # A padded final batch over-reports by the pad amount; the
            # attribution FIFO simply drains empty, which is correct (the
            # padded copies duplicate rows already attributed). Echoed
            # re-deliveries are not fresh source rows and are never counted.
            if self._defer_rows_consumed:
                # superbatches(): attribution happens when the full group is
                # yielded, and only for the fresh rows actually in it.
                self._pending_fresh_rows += self._local_batch
            else:
                self._reader.rows_consumed(self._local_batch)
        return nt(**{k: item[k] for k in names})

    def superbatches(self, k):
        """Yield ``k``-batch on-device concatenations (for scan training).

        Pairs with ``models.train.make_scan_train_step(microbatches=k)``:
        transfers stay at the per-batch size (large single h2d events can be
        pathological on some interconnects) while the training loop pays one
        Python dispatch per ``k`` optimizer steps. The final incomplete
        group (fewer than ``k`` batches at end of data) is dropped — sizes
        stay static for XLA. Checkpoint row accounting happens per *yielded
        group*, so a dropped partial group's rows are NOT counted consumed
        and re-deliver on resume (exactly-once holds here too).
        """
        if k <= 1:
            yield from self
            return
        jax = self._jax
        import jax.numpy as jnp
        concat = jax.jit(lambda *xs: jnp.concatenate(xs))
        it = iter(self)

        def fetch():
            # Deferral is scoped to this call alone, so interleaved direct
            # loader iteration (or an abandoned generator) keeps normal
            # immediate accounting.
            self._defer_rows_consumed = True
            try:
                return next(it)
            finally:
                self._defer_rows_consumed = False

        while True:
            parts = []
            try:
                for _ in range(k):
                    parts.append(fetch())
            except StopIteration:
                # Partial tail group: dropped, and its fresh rows stay
                # unattributed — they re-deliver on resume.
                return
            if self._row_granular_ckpt and self._pending_fresh_rows:
                self._reader.rows_consumed(self._pending_fresh_rows)
                self._pending_fresh_rows = 0
            yield parts[0]._replace(
                **{f: concat(*[getattr(p, f) for p in parts])
                   for f in parts[0]._fields})

    def reset_stats(self):
        """Zero the stall counters — call after warmup so ``stats`` reflects
        the steady-state window, not reader-pool spin-up."""
        self._batches_delivered = 0
        self._wait_s = 0.0
        self._first_get_t = None
        with self._stats_lock:
            self._stage_s = 0.0
            self._staged_bytes = 0

    @property
    def stats(self):
        """Input-pipeline health: delivered batches, seconds spent blocked
        waiting for the staging thread, and the stall fraction (blocked time /
        wall time since the first fetch). A training loop with
        ``input_stall_frac`` above ~0.05 is input-bound (BASELINE.json's
        <5% target) — raise ``workers_count``/``prefetch`` or speed up decode.

        ``reader_diagnostics`` carries the reader's robustness state through
        to the training loop: ``worker_respawns`` (dead pool workers that
        were respawned) and ``quarantined_rowgroups`` (poison row-groups
        skipped under ``error_budget`` — see ``docs/failure_model.rst``).
        """
        elapsed = (time.perf_counter() - self._first_get_t
                   if self._first_get_t is not None else 0.0)
        with self._stats_lock:
            stage_s, staged_bytes = self._stage_s, self._staged_bytes
        out = {'batches': self._batches_delivered,
               'wait_s': round(self._wait_s, 4),
               'input_stall_frac': round(self._wait_s / elapsed, 4) if elapsed else 0.0,
               'stage_dispatch_s': round(stage_s, 4),
               'staged_bytes': staged_bytes,
               'reader_diagnostics': self._reader.diagnostics}
        worker_timings = getattr(self._reader, 'stage_timings', None)
        if worker_timings:
            out['worker_stage_timings'] = {
                k: (round(v, 4) if isinstance(v, float) else v)
                for k, v in worker_timings.items()}
        return out

    def state_dict(self):
        """Mid-epoch resume state (see ``Reader.state_dict``).

        Capture at a batch boundary and rebuild via
        ``make_reader(..., resume_state=state)`` + a new JaxLoader. Resume
        never replays a delivered batch. Row accounting depends on the
        pipeline shape:

        * **Batched reader, no shuffling buffer** (the TPU default): the
          loader enables row-granular accounting — rows still sitting in the
          prefetch queue at checkpoint time are NOT counted consumed and
          re-deliver on resume. Exactly-once AND no loss, any epoch count.
        * **Shuffling buffer engaged, or per-row readers**: rows buffered
          downstream count as consumed. With ``num_epochs=None`` they come
          around on a later epoch; with a finite epoch count they are lost
          to the resumed run — checkpoint between epochs (or drain the
          loader) if finite-epoch completeness matters there.
        """
        return self._reader.state_dict()

    def stop(self):
        self._stop.set()
        self._exhausted = True
        # Drain so the stager can exit.
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        if self._thread is not None:
            self._thread.join(timeout=10)
        self._reader.stop()
        self._reader.join()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        return False


def make_jax_loader(reader, batch_size, **kwargs):
    """Factory mirroring the reference adapter entry points
    (``tf_utils.tf_tensors`` / ``pytorch.DataLoader``)."""
    return JaxLoader(reader, batch_size, **kwargs)
