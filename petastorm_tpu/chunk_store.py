"""Mmap-backed decoded-chunk store: the NVMe cache tier.

PROFILE_r05 shows the pipeline is jpeg-decode-bound cold (~845 img/s) and
memcpy-bound warm (~5.5k img/s), and the pre-existing tiers leave a hole:
``DeviceDatasetCache`` needs the dataset in HBM, ``MemoryCache`` needs it
in RAM *per process* (no sharing across a process pool), and
``LocalDiskCache`` historically stored **encoded** bytes behind pickle, so
every epoch re-paid decode plus a deserialize copy (the reference
petastorm's ``local_disk_cache.py`` has the same shape). tf.data's
snapshot/cache and NVIDIA DALI's decoded-cache design (PAPERS.md) both
show that persisting *post-decode* tensors in their final memory layout is
the tier that actually removes the CPU from steady-state epochs.

:class:`DecodedChunkStore` is that tier, TPU-host-native:

* **Epoch 0 (fill)**: decoded column blocks coming off the
  ``TensorWorker`` path are handed to a background writer thread
  (write-behind — the decode hot path never blocks on NVMe) which
  serializes them into one file per (dataset fingerprint, row-group,
  schema hash) key: a small JSON header with per-field dtype/shape/offset
  records plus a CRC32 per field, then the raw field buffers, 64-byte
  aligned, written to a temp file and **atomically renamed** into place
  under an ``flock``'d lock file — concurrent writers from a process pool
  produce exactly one entry and a reader can never observe a torn chunk.
* **Epoch >= 1 (serve)**: the entry is ``mmap``'d (validated once per
  process per entry) and the store hands out numpy views straight over the
  mapping. The views travel the existing ``reader.last_chunk_private=False``
  shared-block protocol, so the staging engine's block fast path copies
  once, mmap -> arena, with no decode, no pickle, and no per-process
  duplication: every pool worker and every training process shares the
  same page-cache pages. A dataset bigger than RAM but smaller than NVMe
  trains at memcpy speed served by the page cache.
* **Robustness**: a corrupt or truncated entry (bad magic, short file,
  CRC mismatch — or the ``store-read-corrupt`` fault site) is quarantined
  (renamed to ``*.corrupt``) and transparently refilled by re-decode; a
  re-decode failure flows into the PR-1 ``error_budget`` quarantine
  machinery instead of crashing the epoch.
* **Autotune hookup**: :meth:`set_writer_throttled` pauses the write-behind
  writer; the autotuner arms it while the pipeline itself is the
  bottleneck (see :func:`petastorm_tpu.autotune.writer_throttle_listener`)
  so epoch-0 spill never steals decode throughput. Dropped writes are
  self-healing — the chunk misses again next epoch and re-enqueues.

The on-disk layout (:func:`pack_tensor_chunk`) is shared with
``LocalDiskCache``'s ndarray-dict fast path so both tiers speak one
format::

    magic 'PSTC' | u16 version | u32 header_len | u64 data_start
    header JSON {fields: [{name, dtype, shape, offset, nbytes, crc32}]}
    ...padding to 64-byte alignment...
    field payloads (each 64-byte aligned, offsets relative to data_start)

Activation: ``cache_type='chunk-store'`` on the reader factories (location
from ``cache_location`` or the ``PETASTORM_TPU_CHUNK_STORE`` environment
variable), or set the env var alone — ``make_tensor_reader`` with the
default ``cache_type`` then adopts the store without a code change.

Offline pre-fill: ``python -m petastorm_tpu.tools.transcode`` walks a
dataset through the tensor decode path once and publishes every chunk via
this module's flock'd single-writer protocol, so a production job's
epoch 0 already serves from the store (``decode_s`` = 0) — the
``pre-transcoded`` row of the decode-paths table (docs/tpu_guide.rst).
"""

import hashlib
import json
import logging
import mmap
import os
import queue
import shutil
import struct
import tempfile
import threading
import time
import zlib
from collections import OrderedDict

import numpy as np

from petastorm_tpu.cache import CacheBase
from petastorm_tpu.errors import CorruptChunkError

logger = logging.getLogger(__name__)

ENV_VAR = 'PETASTORM_TPU_CHUNK_STORE'

#: Temp-dir prefix for stores created without an explicit directory (bench
#: sweeps); the conftest ``chunkstore`` guard deletes leaked matches.
TEMP_DIR_PREFIX = 'pst-chunk-store-'

_MAGIC = b'PSTC'
_VERSION = 1
_PREAMBLE = struct.Struct('<4sHIQ')   # magic, version, header_len, data_start
_ALIGN = 64                           # per-field payload alignment
_ENTRY_SUFFIX = '.chunk'

#: Age past which an orphaned ``*.tmp``/``*.lock`` file cannot belong to a
#: live write (a write holds its temp file for seconds): swept at store
#: init so killed workers (chaos/respawn paths) don't leak chunk-sized
#: invisible-to-eviction files forever.
_STALE_SCRATCH_S = 600

_STOP = object()


def _file_fingerprint(path):
    """size+mtime of the row-group's parquet file — the content component
    of the store key. An epoch-persistent store outlives sessions, so a
    dataset *regenerated in place* (same URL, same file names) must miss
    and refill, never serve stale decoded tensors; size+mtime_ns changes
    on any rewrite. Remote stores (no local stat) get a constant — for
    them only URL/field drift invalidates (documented limitation)."""
    try:
        st = os.stat(path)
        return '{}-{}'.format(st.st_size, st.st_mtime_ns)
    except (OSError, ValueError):
        return 'nofp'


def tensor_chunk_key(dataset_path_hash, piece_path, row_group, schema):
    """The cache key of one decoded row-group chunk: (dataset fingerprint,
    row-group id, parquet-file content fingerprint, schema hash). Shared
    between ``TensorWorker`` (store lookup ahead of decode) and ``Reader``
    (ventilation-order readahead) so the two sides can never drift apart.
    Chunks are cached *pre-transform*, so a TransformSpec does not enter
    the key — the same store serves any transform over the same decoded
    fields."""
    schema_digest = hashlib.md5(
        ','.join(sorted(schema.fields)).encode()).hexdigest()[:8]
    return 'tensor:{}:{}:{}:{}:{}'.format(
        dataset_path_hash, piece_path, row_group,
        _file_fingerprint(str(piece_path)), schema_digest)


def _align(offset):
    return (offset + _ALIGN - 1) // _ALIGN * _ALIGN


def conforms_tensor_chunk(value):
    """True when ``value`` is storable in the raw-buffer layout: a
    non-empty dict of numpy arrays with plain buffer-protocol dtypes.
    Object columns (decoded string scalars) cannot be mmapped back, and
    structured/void dtypes don't survive the ``dtype.str`` round trip
    (field names would silently drop) — both fall back to pickle in
    ``LocalDiskCache`` / pass through uncached here."""
    if not isinstance(value, dict) or not value:
        return False
    for v in value.values():
        if not isinstance(v, np.ndarray) or v.dtype.kind in ('O', 'V'):
            return False
    return True


def _field_records(cols):
    """Per-field header records + the contiguous buffers to write, with
    payload offsets relative to the data section."""
    records, buffers = [], []
    offset = 0
    for name in sorted(cols):
        arr = np.ascontiguousarray(cols[name])
        if arr.dtype.kind in ('M', 'm'):
            # The buffer protocol refuses datetime64/timedelta64 exports,
            # but their bytes are plain int64 ticks — view them as raw
            # bytes for the write; the header dtype string ('<M8[ns]')
            # restores the real dtype on read (np.frombuffer accepts it).
            mv = memoryview(arr.view(np.uint8)).cast('B')
        else:
            mv = memoryview(arr).cast('B')
        offset = _align(offset)
        records.append({'name': name,
                        'dtype': arr.dtype.str,
                        'shape': list(arr.shape),
                        'offset': offset,
                        'nbytes': arr.nbytes,
                        'crc32': zlib.crc32(mv) & 0xFFFFFFFF})
        buffers.append(mv)
        offset += arr.nbytes
    return records, buffers


def write_tensor_chunk(f, cols):
    """Serialize ``{name: ndarray}`` into open binary file ``f`` in the
    store layout. Returns the total bytes written."""
    records, buffers = _field_records(cols)
    header = json.dumps({'fields': records}).encode('utf-8')
    data_start = _align(_PREAMBLE.size + len(header))
    f.write(_PREAMBLE.pack(_MAGIC, _VERSION, len(header), data_start))
    f.write(header)
    pos = _PREAMBLE.size + len(header)
    for record, mv in zip(records, buffers):
        target = data_start + record['offset']
        if target > pos:
            f.write(b'\0' * (target - pos))
            pos = target
        f.write(mv)
        pos += record['nbytes']
    return pos


def pack_tensor_chunk(cols):
    """:func:`write_tensor_chunk` into bytes (the ``LocalDiskCache``
    ndarray-dict serialization path)."""
    import io
    sink = io.BytesIO()
    write_tensor_chunk(sink, cols)
    return sink.getvalue()


def is_tensor_chunk(blob):
    """True when ``blob`` (bytes-like) starts with the store layout magic."""
    return bytes(blob[:4]) == _MAGIC


def read_tensor_chunk(buf, validate=True, source='<buffer>'):
    """Parse the store layout over ``buf`` (bytes or mmap) into a dict of
    numpy views — zero-copy; the arrays alias ``buf``. Raises
    :class:`~petastorm_tpu.errors.CorruptChunkError` on any structural or
    checksum mismatch (truncation, bit rot, torn write of a non-atomic
    copy)."""
    size = len(buf)
    if size < _PREAMBLE.size:
        raise CorruptChunkError('{}: short preamble ({} bytes)'.format(source, size))
    magic, version, header_len, data_start = _PREAMBLE.unpack_from(buf, 0)
    if magic != _MAGIC:
        raise CorruptChunkError('{}: bad magic {!r}'.format(source, magic))
    if version != _VERSION:
        raise CorruptChunkError('{}: unsupported version {}'.format(source, version))
    if _PREAMBLE.size + header_len > size or data_start > size:
        raise CorruptChunkError('{}: truncated header'.format(source))
    try:
        header = json.loads(bytes(buf[_PREAMBLE.size:_PREAMBLE.size + header_len])
                            .decode('utf-8'))
        fields = header['fields']
    except (ValueError, KeyError, UnicodeDecodeError) as e:
        raise CorruptChunkError('{}: unparsable header: {}'.format(source, e))
    cols = {}
    for record in fields:
        # The CRCs cover payloads only; a bit-flip in the header itself can
        # keep the JSON parseable while mangling dtype/shape/offset — every
        # header-derived value must validate into CorruptChunkError, never
        # escape as TypeError/ValueError (that would crash the epoch the
        # quarantine machinery exists to save).
        try:
            name = record['name']
            dtype = np.dtype(str(record['dtype']))
            shape = tuple(int(d) for d in record['shape'])
            nbytes = int(record['nbytes'])
            start = data_start + int(record['offset'])
            crc = int(record['crc32'])
        except (TypeError, ValueError, KeyError) as e:
            raise CorruptChunkError('{}: bad field record: {}'.format(source, e))
        if dtype.hasobject or dtype.itemsize == 0:
            # An unluckily-mangled dtype string can still parse (e.g. '|O',
            # 'V0'); frombuffer would raise ValueError/ZeroDivisionError.
            raise CorruptChunkError('{}: field {!r} has non-buffer dtype {}'
                                    .format(source, name, dtype))
        expected = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        if expected != nbytes or nbytes < 0 or min(shape, default=0) < 0:
            raise CorruptChunkError(
                '{}: field {!r} shape {} x {} does not cover {} bytes'
                .format(source, name, shape, dtype, nbytes))
        if start < 0 or start + nbytes > size:
            raise CorruptChunkError('{}: field {!r} extends past EOF'
                                    .format(source, name))
        view = memoryview(buf)[start:start + nbytes]
        if validate and (zlib.crc32(view) & 0xFFFFFFFF) != crc:
            raise CorruptChunkError('{}: field {!r} checksum mismatch'
                                    .format(source, name))
        try:
            arr = np.frombuffer(buf, dtype=dtype,
                                count=nbytes // dtype.itemsize, offset=start)
            cols[name] = arr.reshape(shape)
        except (ValueError, TypeError) as e:
            # Belt and braces: whatever numpy refuses is corruption here.
            raise CorruptChunkError('{}: field {!r} unmappable: {}'
                                    .format(source, name, e))
    return cols


class _OpenEntry(object):
    """One validated, mmapped store entry (kept open in a per-process LRU).

    The mmap is never explicitly closed: views of it may be anywhere in
    the pipeline (staged batches, arena holds), and ``mmap.close`` with
    exported buffers raises. Dropping the entry from the LRU lets the
    mapping die with its last view."""

    __slots__ = ('mm', 'views', 'nbytes')

    def __init__(self, mm, views, nbytes):
        self.mm = mm
        self.views = views
        self.nbytes = nbytes

    @classmethod
    def open(cls, path, validate=True):
        with open(path, 'rb') as f:
            if os.fstat(f.fileno()).st_size == 0:
                raise CorruptChunkError('{}: empty entry'.format(path))
            # ACCESS_COPY (MAP_PRIVATE copy-on-write), not ACCESS_READ: the
            # read path is identical — zero-copy views over shared page
            # cache — but the views stay WRITEABLE, which keeps downstream
            # zero-copy paths (DLPack export refuses read-only buffers and
            # the loader would silently fall back to a per-batch memcpy).
            # A protocol-violating in-process write diverges onto a private
            # page instead of corrupting the store every other process
            # shares — strictly safer than MemoryCache, where the same bug
            # corrupts every later epoch.
            mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_COPY)
        views = read_tensor_chunk(mm, validate=validate, source=path)
        return cls(mm, views, len(mm))

    def willneed(self):
        """Hint the kernel to fault this entry's pages in ahead of the
        collate copy (no-op where madvise is unavailable)."""
        if hasattr(self.mm, 'madvise'):
            try:
                self.mm.madvise(mmap.MADV_WILLNEED)
            except (OSError, ValueError):  # pragma: no cover - advisory only
                pass


class DecodedChunkStore(CacheBase):
    """Epoch-persistent, cross-process decoded-chunk cache on local NVMe.

    Plugs into the worker-side ``cache.get(key, fill_fn)`` protocol of the
    tensor path (values are ``{field: ndarray}`` column blocks). Misses
    run ``fill_fn`` (read + decode) and hand the result to a background
    write-behind thread; hits return zero-copy numpy views over the
    mmapped (copy-on-write) entry. Unlike :class:`~petastorm_tpu.cache.MemoryCache` the
    store is shared **across a process pool**: each worker process opens
    the same files, so the dataset is decoded once per host, not once per
    process, and warm reads all hit the same page-cache pages.

    :param path: store directory (created if missing). ``None`` reads the
        ``PETASTORM_TPU_CHUNK_STORE`` environment variable.
    :param size_limit: approximate total entry bytes; oldest-mtime entries
        are evicted after a write pushes past it. ``None`` = unlimited.
    :param writer_queue_depth: pending write-behind chunks; an overflowing
        queue DROPS the write (``stats()['write_skipped']``) rather than
        ever blocking the decode path — the chunk re-enqueues on its next
        epoch's miss.
    :param throttle_delay_s: writer pause granularity while throttled.
    :param validate: ``'open'`` (default) checks every field's CRC32 once
        per process when an entry is first mmapped; ``'off'`` trusts the
        bytes (bench experiments only).
    :param cleanup: remove the whole store directory on :meth:`cleanup`.
    """

    #: Diagnostics gate (``Reader.diagnostics()['chunk_store']``).
    is_chunk_store = True
    #: Provenance serving-tier label (``petastorm_tpu.lineage``): a chunk
    #: served from this store is an NVMe mmap hit, not a fresh decode.
    lineage_tier = 'chunk-store'

    def __init__(self, path=None, size_limit=None, writer_queue_depth=16,
                 throttle_delay_s=0.05, validate='open', cleanup=False,
                 max_open_entries=1024, **_):
        if path is None:
            path = os.environ.get(ENV_VAR) or None
        if not path:
            raise ValueError(
                "DecodedChunkStore needs a directory: pass cache_location or "
                "set the {} environment variable".format(ENV_VAR))
        self._config = {'path': path, 'size_limit': size_limit,
                        'writer_queue_depth': writer_queue_depth,
                        'throttle_delay_s': throttle_delay_s,
                        'validate': validate, 'cleanup': cleanup,
                        'max_open_entries': max_open_entries}
        self._init_from_config()

    def _init_from_config(self):
        cfg = self._config
        self._path = cfg['path']
        self._size_limit = cfg['size_limit']
        self._queue_depth = max(1, int(cfg['writer_queue_depth']))
        self._throttle_delay_s = float(cfg['throttle_delay_s'])
        self._validate = cfg['validate'] != 'off'
        self._do_cleanup = bool(cfg['cleanup'])
        self._max_open = max(1, int(cfg['max_open_entries']))
        os.makedirs(self._path, exist_ok=True)
        self._sweep_stale_scratch()
        self._lock = threading.RLock()
        self._entries = OrderedDict()      # digest -> _OpenEntry (LRU)
        # Entries validated once per process: a store larger than the open-
        # entry LRU (the tier's flagship case) must not re-CRC a full
        # entry on every post-eviction reopen — entries are immutable
        # (atomic-rename published), so one payload pass per process is
        # enough. A quarantine drops the digest again.
        self._validated = set()
        self._writeq = None                # lazily started with the thread
        self._writeq_bytes = 0             # decoded bytes pinned by the queue
        self._writer = None
        self._stopping = False
        self._throttled = False
        self._spill_paused = False         # memory governor's advisory hook
        self._dir_bytes = None   # running size estimate; None = needs a scan
        # Registry mirror (petastorm_tpu.metrics): the same counters as
        # scrapable instruments — one registry.collect() then covers the
        # NVMe tier next to staging/autotune/watchdog without a reader
        # handle. Worker PROCESSES count in their own registries (the
        # entry files are still shared); thread pools cover the pipeline.
        from petastorm_tpu import metrics as metrics_mod
        self._m = {name: metrics_mod.counter(
            'pst_chunk_store_{}_total'.format(name),
            'Decoded-chunk store {} count'.format(name.replace('_', ' ')))
            for name in ('hits', 'misses', 'fills', 'writes',
                         'write_skipped', 'corrupt', 'bytes_written',
                         'bytes_mapped', 'readaheads', 'unstorable')}
        # counters (read via stats(); guarded by _lock)
        self.hits = 0
        self.misses = 0
        self.fills = 0          # fill_fn calls that produced a chunk
                                # (misses minus empty row-groups)
        self.writes = 0
        self.write_skipped = 0
        self.write_races = 0    # another process won the flock first
        self.corrupt = 0
        self.bytes_written = 0
        self.bytes_mapped = 0
        self.readaheads = 0
        self.unstorable = 0

    def _sweep_stale_scratch(self):
        """Unlink ``*.tmp``/``*.lock`` files older than ``_STALE_SCRATCH_S``:
        a worker killed between ``mkstemp`` and the atomic rename leaves a
        chunk-sized temp file no rename will ever claim (and size-cap
        eviction only reclaims published entries)."""
        now = time.time()
        try:
            names = os.listdir(self._path)
        except OSError:  # pragma: no cover - directory racing a cleanup
            return
        for name in names:
            if not name.endswith(('.tmp', '.lock')):
                continue
            full = os.path.join(self._path, name)
            try:
                if now - os.stat(full).st_mtime > _STALE_SCRATCH_S:
                    os.unlink(full)
            except OSError:  # pragma: no cover - already gone
                continue

    # -- pickling (process pools ship the cache inside worker args) -------

    def __getstate__(self):
        return {'config': dict(self._config)}

    def __setstate__(self, state):
        self._config = state['config']
        self._init_from_config()

    # -- key/paths ---------------------------------------------------------

    @staticmethod
    def _digest(key):
        return hashlib.md5(str(key).encode('utf-8')).hexdigest()

    def _entry_path(self, key):
        return os.path.join(self._path, self._digest(key) + _ENTRY_SUFFIX)

    # -- read path ---------------------------------------------------------

    def _quarantine(self, path, error):
        """A corrupt/truncated entry must never be served OR retried
        forever: move it aside (post-mortem debuggable) and let the caller
        refill by re-decode."""
        logger.warning('chunk store entry quarantined: %s', error)
        try:
            os.replace(path, path + '.corrupt')
        except OSError:
            try:
                os.unlink(path)
            except OSError:  # pragma: no cover - already gone
                pass
        from petastorm_tpu.trace import get_global_tracer
        get_global_tracer().instant('chunk_store_quarantine', cat='fault')

    def _open_entry(self, key):
        """The validated entry for ``key``, opening+checking it on first
        touch, or ``None`` (absent or quarantined-just-now)."""
        digest = self._digest(key)
        with self._lock:
            entry = self._entries.get(digest)
            if entry is not None:
                self._entries.move_to_end(digest)
                return entry
        path = os.path.join(self._path, digest + _ENTRY_SUFFIX)
        if not os.path.exists(path):
            return None
        # Open + CRC-validate OUTSIDE the store lock: first-touch validation
        # is a full NVMe read of the entry, and holding the lock across it
        # would serialize every concurrent worker hit (and the ventilator's
        # readahead) behind one disk scan. Two threads racing the same
        # entry just validate twice; the insert below keeps one winner.
        with self._lock:
            validate = self._validate and digest not in self._validated
        try:
            from petastorm_tpu.faults import get_injector
            if get_injector().should_fire('store-read-corrupt', key=str(key)):
                raise CorruptChunkError(
                    '{}: injected fault store-read-corrupt (key={!r})'
                    .format(path, key))
            entry = _OpenEntry.open(path, validate=validate)
        except CorruptChunkError as e:
            with self._lock:
                self.corrupt += 1
                self._m['corrupt'].inc()
                self._validated.discard(digest)
            self._quarantine(path, e)
            return None
        except OSError as e:
            logger.warning('chunk store entry %s unreadable: %s', path, e)
            return None
        with self._lock:
            winner = self._entries.get(digest)
            if winner is not None:      # lost an open race: serve the winner
                self._entries.move_to_end(digest)
                return winner
            self._entries[digest] = entry
            self._validated.add(digest)
            self.bytes_mapped += entry.nbytes
            self._m['bytes_mapped'].inc(entry.nbytes)
            while len(self._entries) > self._max_open:
                # Dropped, not closed: live views keep the mapping alive.
                self._entries.popitem(last=False)
            return entry

    def readahead(self, key):
        """Fault-in hint for a row-group the ventilator just scheduled:
        ``madvise(WILLNEED)`` over the entry's extents so the pages are
        resident by the time a worker's hit copies them toward an arena.
        Deliberately does NOT parse or CRC-validate the entry — this runs
        on the single ventilator feed thread, and forcing first-touch
        validation there would serialize behind one thread what the N
        workers otherwise validate in parallel; a not-yet-open entry is
        just mmapped, hinted, and dropped (the pages stay in the cache).
        Returns True when an entry was hinted."""
        digest = self._digest(key)
        with self._lock:
            entry = self._entries.get(digest)
        if entry is not None:
            entry.willneed()
        else:
            path = os.path.join(self._path, digest + _ENTRY_SUFFIX)
            try:
                with open(path, 'rb') as f:
                    if os.fstat(f.fileno()).st_size == 0:
                        return False
                    mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
            except (OSError, ValueError):
                return False
            if hasattr(mm, 'madvise'):
                try:
                    mm.madvise(mmap.MADV_WILLNEED)
                except (OSError, ValueError):  # pragma: no cover - advisory
                    pass
            mm.close()   # nothing exported; the page-cache warmth remains
        with self._lock:
            self.readaheads += 1
            self._m['readaheads'].inc()
        return True

    # -- CacheBase protocol ------------------------------------------------

    def get(self, key, fill_cache_func):
        entry = self._open_entry(key)
        if entry is not None:
            with self._lock:
                self.hits += 1
                hits = self.hits
                self._m['hits'].inc()
            from petastorm_tpu.trace import get_global_tracer
            get_global_tracer().counter('chunk_store_hits', hits, 'chunk-store')
            # A fresh shallow dict per hit: callers slice/pop their copy
            # (resume skip, transform field filtering) without aliasing
            # another worker's view dict. The arrays themselves are the
            # shared read-only mmap views — the last_chunk_private=False
            # protocol guarantees downstream only ever copies FROM them.
            return dict(entry.views)
        with self._lock:
            self.misses += 1
            self._m['misses'].inc()
        value = fill_cache_func()
        if value is None:
            return None
        with self._lock:
            self.fills += 1   # actual decoded chunks (None = empty row-group)
            self._m['fills'].inc()
        if conforms_tensor_chunk(value):
            self._enqueue_write(key, value)
        else:
            with self._lock:
                self.unstorable += 1
                self._m['unstorable'].inc()
        return value

    def has(self, key):
        """True when ``key`` is already persisted (no mmap is opened —
        an existence probe, not a read)."""
        return os.path.exists(self._entry_path(key))

    def put(self, key, cols):
        """Synchronous fill: persist ``{field: ndarray}`` under ``key``
        NOW (fsync + atomic rename), bypassing the write-behind queue.
        The warm-join protocol uses this — a joining replica pre-filling
        from a peer needs durability it can assert, not best-effort
        spill that may have been shed under pressure. Returns True when
        the entry is on disk (already present counts), False when the
        value does not conform to the dense-chunk layout."""
        if not conforms_tensor_chunk(cols):
            with self._lock:
                self.unstorable += 1
                self._m['unstorable'].inc()
            return False
        self._write_entry(key, cols)
        return True

    # -- write-behind ------------------------------------------------------

    def _enqueue_write(self, key, cols):
        with self._lock:
            if self._stopping:
                return
            if self._spill_paused:
                # Advisory rung: refuse new spill work instead of pinning
                # decoded bytes in the queue — counted, never silent.
                self.write_skipped += 1
                self._m['write_skipped'].inc()
                return
            if self._writer is None:
                self._writeq = queue.Queue(maxsize=self._queue_depth)
                self._writer = threading.Thread(
                    target=self._writer_loop, daemon=True,
                    name='pst-chunk-store-writer')
                self._writer.start()
            nbytes = sum(int(getattr(arr, 'nbytes', 0)) for arr in cols.values())
            try:
                self._writeq.put_nowait((key, cols, nbytes))
                self._writeq_bytes += nbytes
            except queue.Full:
                # NEVER block decode on NVMe: drop, self-heals next epoch.
                self.write_skipped += 1
                self._m['write_skipped'].inc()

    def set_spill_paused(self, paused):
        """Memory-governor advisory hook: while True, new spill work is
        REFUSED at enqueue (counted as ``write_skipped``, self-healing on
        the chunk's next-epoch miss) and the already-queued backlog keeps
        draining to NVMe. Refusing-at-enqueue rather than holding the
        writer matters: a held writer would PIN a full queue of decoded
        chunks for the whole advisory episode — the relief rung would
        itself sustain the pressure (and could latch the ladder at
        advisory forever on a tight budget). Released the moment the
        ladder leaves the advisory band."""
        self._spill_paused = bool(paused)

    @property
    def spill_paused(self):
        return self._spill_paused

    def set_writer_throttled(self, throttled):
        """Autotune hookup: while True the write-behind writer is PACED —
        one entry per ``throttle_delay_s`` — so epoch-0 spill cedes CPU and
        NVMe bandwidth to a pipeline that is already the bottleneck without
        ever starving the fill. A hard pause would deadlock the tier's
        whole point on decode-bound workloads: the fill epochs ARE the
        reader-starved epochs, and a writer that stops during them never
        populates the store at all (everything drops as write_skipped)."""
        self._throttled = bool(throttled)

    @property
    def writer_throttled(self):
        return self._throttled

    def _writer_loop(self):
        while True:
            item = self._writeq.get()
            try:
                if item is _STOP:
                    return
                # Paced, not paused (see set_writer_throttled): yield for at
                # most throttle_delay_s per entry, waking early on
                # unthrottle/stop so flush() and close() stay prompt.
                waited = 0.0
                while (self._throttled and not self._stopping
                       and waited < self._throttle_delay_s):
                    time.sleep(0.005)
                    waited += 0.005
                key, cols, nbytes = item
                try:
                    self._write_entry(key, cols)
                except Exception:  # noqa: BLE001 - spill must never kill the pipe
                    logger.exception('chunk store write-behind failed for %r', key)
                with self._lock:
                    self._writeq_bytes = max(0, self._writeq_bytes - nbytes)
            finally:
                self._writeq.task_done()

    def _write_entry(self, key, cols):
        import fcntl
        path = self._entry_path(key)
        if os.path.exists(path):
            return
        # flock'd lock file: of N pool processes decoding the same
        # row-group (epoch-boundary duplicate dispatch), exactly one pays
        # the serialize+write; the others skip on the existence re-check.
        lock_path = path + '.lock'
        with open(lock_path, 'a') as lock_file:
            fcntl.flock(lock_file, fcntl.LOCK_EX)
            try:
                if os.path.exists(path):
                    with self._lock:
                        self.write_races += 1
                    return
                fd, tmp = tempfile.mkstemp(dir=self._path, suffix='.tmp')
                try:
                    with os.fdopen(fd, 'wb') as f:
                        nbytes = write_tensor_chunk(f, cols)
                        f.flush()
                        os.fsync(f.fileno())
                    # Atomic publish: a concurrent reader sees either no
                    # entry or the complete one — never a torn chunk.
                    os.replace(tmp, path)
                except BaseException:
                    try:
                        os.unlink(tmp)
                    except OSError:
                        pass
                    raise
                # Published: the lock file has served its purpose. A racer
                # already blocked on it locks the orphaned inode, re-checks
                # existence, and skips; the pathological interleaving
                # (quarantine between) at worst double-writes through the
                # same atomic-rename path — still never a torn read.
                try:
                    os.unlink(lock_path)
                except OSError:  # pragma: no cover - already gone
                    pass
            finally:
                fcntl.flock(lock_file, fcntl.LOCK_UN)
        with self._lock:
            self.writes += 1
            self.bytes_written += nbytes
            writes = self.writes
            self._m['writes'].inc()
            self._m['bytes_written'].inc(nbytes)
        from petastorm_tpu.trace import get_global_tracer
        get_global_tracer().counter('chunk_store_writes', writes, 'chunk-store')
        self._maybe_evict(nbytes)

    def _maybe_evict(self, new_bytes=0):
        """Size-cap enforcement, amortized: a running byte estimate grows
        with each write and the full directory scan (O(entries) stats)
        only runs when the estimate crosses the limit — not per write.
        Quarantined ``*.corrupt`` files count toward (and age out of) the
        budget like live entries; the estimate resyncs from every scan."""
        if self._size_limit is None:
            return
        with self._lock:
            if self._dir_bytes is not None:
                self._dir_bytes += new_bytes
                if self._dir_bytes <= self._size_limit:
                    return
        entries, total = [], 0
        for name in os.listdir(self._path):
            if not name.endswith((_ENTRY_SUFFIX, '.corrupt')):
                continue
            full = os.path.join(self._path, name)
            try:
                st = os.stat(full)
            except OSError:
                continue
            entries.append((st.st_mtime, st.st_size, full))
            total += st.st_size
        if total > self._size_limit:
            entries.sort()  # oldest first
            for _, size, full in entries:
                try:
                    os.unlink(full)
                except OSError:
                    continue
                total -= size
                if total <= self._size_limit:
                    break
        with self._lock:
            self._dir_bytes = total

    # -- memory-governor accounting (membudget.py) -------------------------

    def governed_nbytes(self):
        """Bytes this store currently pins in host memory: decoded chunks
        parked in the write-behind queue plus the resident open-entry
        mmaps (ACCESS_COPY mappings occupy page cache / private pages for
        every byte a hit has touched — the upper bound is the mapped
        size, which is what a budget must assume)."""
        with self._lock:
            mapped = sum(entry.nbytes for entry in self._entries.values())
            return self._writeq_bytes + mapped

    def close_lru_mmaps(self, keep_frac=0.5):
        """Drop the least-recently-used open entries until at most
        ``keep_frac`` of them remain (the governor's *degrade* hook). The
        mappings are dropped, not closed — live views keep their pages
        alive until the consumer releases them (the same rule the
        ``max_open_entries`` LRU follows) — so this is safe at any time;
        a dropped entry just re-mmaps (without re-CRC: the per-process
        validated set survives) on its next hit. Returns the mapped bytes
        released from the accounting."""
        freed = 0
        with self._lock:
            keep = int(len(self._entries) * float(keep_frac))
            while len(self._entries) > keep:
                _, entry = self._entries.popitem(last=False)
                freed += entry.nbytes
        return freed

    def flush(self, timeout_s=30.0):
        """Block until the write-behind queue drains (tests / epoch-end
        barriers). Returns False on timeout — e.g. a throttled writer."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            q = self._writeq
            if q is None or q.unfinished_tasks == 0:
                return True
            time.sleep(0.005)
        return False

    # -- lifecycle / stats -------------------------------------------------

    def stats(self):
        """Hit/miss/write-behind counters for ``stats['chunk_store']`` /
        ``Reader.diagnostics()['chunk_store']``. With a thread pool these
        cover the whole pipeline; with process pools each worker process
        counts its own (the files are still shared)."""
        with self._lock:
            q = self._writeq
            return {'path': self._path,
                    'hits': self.hits,
                    'misses': self.misses,
                    'fills': self.fills,
                    'writes': self.writes,
                    'write_skipped': self.write_skipped,
                    'write_races': self.write_races,
                    'corrupt_quarantined': self.corrupt,
                    'bytes_written': self.bytes_written,
                    'bytes_mapped': self.bytes_mapped,
                    'readaheads': self.readaheads,
                    'unstorable': self.unstorable,
                    'pending_writes': (q.unfinished_tasks if q is not None else 0),
                    'pending_write_bytes': self._writeq_bytes,
                    'writer_throttled': self._throttled,
                    'spill_paused': self._spill_paused,
                    'open_entries': len(self._entries)}

    def close(self):
        """Stop the write-behind thread (pending writes drain first)."""
        with self._lock:
            self._stopping = True
            writer, q = self._writer, self._writeq
            self._writer = None
        joined = True
        if writer is not None and writer.is_alive():
            q.put(_STOP)
            writer.join(timeout=10)
            joined = not writer.is_alive()
        if joined:
            # Re-arm only once the old writer is provably gone: resetting
            # under a timed-out join would revive a (possibly throttled)
            # zombie writer spinning against a store being deleted.
            with self._lock:
                self._stopping = False
        else:  # pragma: no cover - requires a wedged NVMe write
            logger.warning('chunk store writer still alive after close(); '
                           'the store stays write-disabled')

    def cleanup(self):
        self.close()
        if self._do_cleanup:
            shutil.rmtree(self._path, ignore_errors=True)
