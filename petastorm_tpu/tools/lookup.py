"""``python -m petastorm_tpu.tools.lookup`` — smoke-test the lookup tier.

One command exercises the whole path without writing client code: build
the row-level key index, resolve a point read through the chunk-store
hot tier, and (optionally) stand the rpc server up::

    # index the 'id' field, read id=7, report per-field CRC32 digests
    python -m petastorm_tpu.tools.lookup --dataset-url file:///data/ds \\
        --key id=7 --build-index

    # same dataset as a service (trainers' chunk store as the hot tier)
    python -m petastorm_tpu.tools.lookup --dataset-url file:///data/ds \\
        --key id=7 --store /mnt/nvme/chunks --serve

Prints ONE JSON line per action (index build, lookup result, serve
status), so orchestration scripts can parse it. The lookup result
carries per-field CRC32 digests (``lineage._digest_array`` — the same
digest the provenance ledger records), which is how an operator proves a
served row is byte-identical to the training feed's.

Fleet operations ride the same command. Client side, ``--fleet`` dials
a running fleet instead of opening the dataset::

    # routing table, per-partition replica health, the scatter-gather
    # read, and scatter stats — one JSON line each
    python -m petastorm_tpu.tools.lookup --fleet tcp://h1:7000 \\
        tcp://h2:7000 --key id=7

Server side, ``--serve`` grows fleet membership: ``--partitions N``
bootstraps a one-member fleet owning every partition, ``--join PEER``
joins a running fleet (warm-filling the chunk store from the peer
unless ``--no-warm``). The drain-on-SIGTERM discipline is unchanged —
draining a fleet member also reassigns its key range live.
"""

import argparse
import json
import signal
import sys
import threading


def _field_summary(name, value):
    """JSON-safe description of one served field: dtype/shape/CRC32,
    plus the value itself when it is a printable scalar."""
    import numpy as np

    from petastorm_tpu.lineage import _digest_array
    arr = np.asarray(value)
    out = {'dtype': str(arr.dtype), 'shape': list(arr.shape),
           'crc32': '{:#010x}'.format(_digest_array(arr))}
    if arr.ndim == 0 and arr.dtype.kind in 'biufU':
        out['value'] = arr.item()
    return out


def _fleet_client(args, field, value):
    """``--fleet`` mode: routing table, per-partition replica health,
    the scatter-gather read, and scatter stats — one JSON line each."""
    from petastorm_tpu.serving import LookupClient
    client = LookupClient(args.fleet,
                          control_endpoints=args.control,
                          timeout_ms=args.timeout_ms)
    try:
        try:
            client.refresh_partition_map()
        except Exception as e:  # noqa: BLE001 - a CLI prints, not dies
            print(json.dumps({'action': 'pmap-refresh',
                              'error': repr(e)}), flush=True)
        table = client.routing_table()
        print(json.dumps({'action': 'routing-table', 'table': table}),
              flush=True)
        health = {pid: [{'name': e['name'],
                         'endpoint': e['endpoint'],
                         'breaker': e['breaker'],
                         'hb_state': e['hb_state'],
                         'lease_fresh': e['lease_fresh']}
                        for e in entries]
                  for pid, entries in table['partitions'].items()}
        print(json.dumps({'action': 'partition-health',
                          'version': table['version'],
                          'partitions': health}), flush=True)
        try:
            rows = client.lookup([value])[0]
        except Exception as e:  # noqa: BLE001
            print(json.dumps({'action': 'lookup', 'key': args.key,
                              'error': repr(e)}), flush=True)
            return 1
        print(json.dumps({'action': 'lookup', 'key': args.key,
                          'matches': len(rows),
                          'rows': [{name: _field_summary(name, val)
                                    for name, val in row.items()}
                                   for row in rows]}), flush=True)
        print(json.dumps({'action': 'scatter-stats',
                          'stats': client.scatter_stats()}), flush=True)
        return 0 if rows else 3
    finally:
        client.close()


def main(argv=None):
    parser = argparse.ArgumentParser(
        description='Point reads over a petastorm_tpu dataset: build the '
                    'row-level index, look keys up, optionally serve rpc')
    parser.add_argument('--dataset-url', default=None,
                        help='the dataset to open (required unless '
                             '--fleet dials running servers instead)')
    parser.add_argument('--key', required=True, metavar='FIELD=VALUE',
                        help='the point read, e.g. id=7; FIELD names the '
                             'indexed key field')
    parser.add_argument('--fleet', nargs='+', default=None,
                        metavar='ENDPOINT',
                        help='client mode: dial these lookup rpc '
                             'endpoints, print the routing table, '
                             'per-partition replica health, the '
                             'scatter-gather read, and scatter stats '
                             'as JSON lines')
    parser.add_argument('--control', nargs='*', default=None,
                        metavar='ENDPOINT',
                        help='heartbeat endpoints for --fleet (lease-'
                             'aware ranking; the partition map also '
                             'arrives here)')
    parser.add_argument('--timeout-ms', type=int, default=5000,
                        help='--fleet whole-request (per-partition) '
                             'deadline')
    parser.add_argument('--build-index', action='store_true',
                        help='run the SingleFieldRowIndexer pass over the '
                             'key field first (persists alongside any '
                             'existing indexes)')
    parser.add_argument('--index', default=None,
                        help='row-level index name (default: the single '
                             'stored one, or FIELD_row_ix when building)')
    parser.add_argument('--store', default=None, metavar='DIR',
                        help='DecodedChunkStore directory — share the '
                             'training store so point reads hit its mmap '
                             'tier (default: decode-only)')
    parser.add_argument('--fields', nargs='*', default=None,
                        help='fields to serve (default: all)')
    parser.add_argument('--serve', action='store_true',
                        help='after the lookup, serve lookup/query rpc '
                             'until SIGTERM (first signal drains '
                             'gracefully, second forces exit)')
    parser.add_argument('--bind', default='tcp://127.0.0.1:*',
                        help='rpc endpoint for --serve (heartbeats bind '
                             'the next port)')
    parser.add_argument('--max-consumers', type=int, default=None)
    parser.add_argument('--lease-s', type=float, default=None)
    parser.add_argument('--rpc-workers', type=int, default=2)
    parser.add_argument('--name', default=None,
                        help='fleet identity of a --serve server '
                             '(placement assigns partitions to it)')
    parser.add_argument('--partitions', type=int, default=None,
                        help='--serve: bootstrap a one-member fleet '
                             'with this many hash partitions')
    parser.add_argument('--replication', type=int, default=2,
                        help='replica target R for --partitions')
    parser.add_argument('--join', default=None, metavar='PEER_ENDPOINT',
                        help='--serve: join the fleet this peer serves')
    parser.add_argument('--no-warm', action='store_true',
                        help='with --join: skip the peer cache '
                             'warm-fill (cold-decode on first reads)')
    args = parser.parse_args(argv)

    field, sep, value = args.key.partition('=')
    if not sep or not field:
        print(json.dumps({'error': '--key must be FIELD=VALUE, got {!r}'
                          .format(args.key)}), flush=True)
        return 2

    if args.fleet:
        return _fleet_client(args, field, value)
    if not args.dataset_url:
        print(json.dumps({'error': '--dataset-url is required without '
                                   '--fleet'}), flush=True)
        return 2

    from petastorm_tpu.serving import LookupEngine, LookupServer

    index_name = args.index
    if args.build_index:
        from petastorm_tpu.etl.rowgroup_indexers import SingleFieldRowIndexer
        from petastorm_tpu.etl.rowgroup_indexing import build_rowgroup_index
        index_name = index_name or '{}_row_ix'.format(field)
        payload = build_rowgroup_index(
            args.dataset_url, [SingleFieldRowIndexer(index_name, field)])
        print(json.dumps({'action': 'build-index', 'index': index_name,
                          'field': field,
                          'keys': len(payload[index_name]['values'])}),
              flush=True)

    try:
        engine = LookupEngine(args.dataset_url, index_name=index_name,
                              cache=args.store, schema_fields=args.fields)
    except Exception as e:  # noqa: BLE001 - a CLI prints, not tracebacks
        print(json.dumps({'error': str(e)}), flush=True)
        return 1
    if engine.index.field != field:
        print(json.dumps({'error': 'index {!r} keys field {!r}, not {!r}'
                          .format(engine.index.name, engine.index.field,
                                  field)}), flush=True)
        engine.close()
        return 1

    rows = engine.lookup([value])[0]
    print(json.dumps({'action': 'lookup', 'key': args.key,
                      'matches': len(rows),
                      'rows': [{name: _field_summary(name, val)
                                for name, val in row.items()}
                               for row in rows],
                      'engine': engine.stats()}), flush=True)

    if not args.serve:
        engine.close()
        return 0 if rows else 3

    drain_requested = threading.Event()
    stop = threading.Event()

    def _on_signal(*_):
        if drain_requested.is_set():
            stop.set()
        else:
            drain_requested.set()

    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, _on_signal)

    server = LookupServer(engine, args.bind,
                          lease_s=args.lease_s,
                          max_consumers=args.max_consumers,
                          rpc_workers=args.rpc_workers,
                          server_name=args.name).start()
    if args.partitions:
        pmap = server.init_fleet(n_partitions=args.partitions,
                                 replication=args.replication)
        print(json.dumps({'action': 'init-fleet',
                          'name': server.server_name,
                          'version': pmap.version,
                          'n_partitions': pmap.n_partitions,
                          'replication': pmap.replication}), flush=True)
    elif args.join:
        try:
            summary = server.join_fleet(args.join,
                                        warm=not args.no_warm)
        except Exception as e:  # noqa: BLE001 - a CLI prints, not dies
            print(json.dumps({'action': 'join-fleet',
                              'error': repr(e)}), flush=True)
            server.stop()
            engine.close()
            return 1
        print(json.dumps(dict({'action': 'join-fleet',
                               'name': server.server_name}, **summary)),
              flush=True)
    print(json.dumps({'action': 'serve',
                      'rpc_endpoint': server.rpc_endpoint,
                      'control_endpoint': server.control_endpoint,
                      'state': server.state}), flush=True)
    while not stop.is_set():
        if drain_requested.is_set():
            server.drain()
            break
        stop.wait(0.2)
    final = {'action': 'served', 'state': server.state,
             'requests_served': server.requests_served}
    server.stop()
    engine.close()
    print(json.dumps(final), flush=True)
    return 0


if __name__ == '__main__':
    sys.exit(main())
