"""pstlint CLI: run the project-invariant static analyzers.

Usage::

    python -m petastorm_tpu.tools.pstlint [paths...]
        [--check lock-order,threads,determinism,registry]
        [--list-checks] [--emit-lock-graph FILE] [--format text|json]

With no paths, analyzes the installed ``petastorm_tpu`` package tree.
Exit status: 0 clean, 1 findings, 2 usage/parse error. The tier-1 CI gate
(``tests/test_pstlint.py::test_package_tree_is_clean``) runs this over
``petastorm_tpu/`` and fails on any finding.

Findings are silenced per line with a mandatory reason::

    q.put(item)   # pstlint: disable=lock-order-blocking(bounded; see stop())

A suppression without a reason, an unused one, or a malformed one is
itself a finding — the shipped tree has zero unexplained exceptions.

``--emit-lock-graph`` writes the static acquired-before edge set as JSON
(``[[a, b], ...]``), the seed for the runtime lock-order recorder
(:class:`petastorm_tpu.analysis.sanitize.LockOrderRecorder`).
"""

import argparse
import json
import os
import sys


def _default_root():
    import petastorm_tpu
    return os.path.dirname(os.path.abspath(petastorm_tpu.__file__))


def main(argv=None):
    from petastorm_tpu import analysis

    parser = argparse.ArgumentParser(
        prog='python -m petastorm_tpu.tools.pstlint',
        description='Project-invariant static analyzer: lock-order graph, '
                    'thread lifecycle, determinism taint, registry sync.')
    parser.add_argument('paths', nargs='*',
                        help='files or directories to analyze '
                             '(default: the petastorm_tpu package)')
    parser.add_argument('--check', default=None,
                        help='comma-separated subset of: {}'.format(
                            ','.join(analysis.CHECKS)))
    parser.add_argument('--list-checks', action='store_true',
                        help='list check groups and exit')
    parser.add_argument('--emit-lock-graph', metavar='FILE', default=None,
                        help='write the static lock-order edge set as JSON '
                             '(implies the lock-order check: the file '
                             'seeds the runtime recorder, so it must '
                             'never be a silently empty contract)')
    parser.add_argument('--format', choices=('text', 'json'), default='text')
    args = parser.parse_args(argv)

    if args.list_checks:
        for check in analysis.CHECKS:
            print(check)
        return 0

    roots = args.paths or [_default_root()]
    for root in roots:
        if not os.path.exists(root):
            print('pstlint: no such path: {}'.format(root), file=sys.stderr)
            return 2
    checks = None
    if args.check:
        checks = [c.strip() for c in args.check.split(',') if c.strip()]
        if args.emit_lock_graph and 'lock-order' not in checks:
            # The emitted file seeds LockOrderRecorder.load_static_edges;
            # a subset run must not silently write an empty contract.
            checks.append('lock-order')
    try:
        findings, lock_edges = analysis.run_checks(roots, checks=checks)
    except (SyntaxError, ValueError) as e:
        print('pstlint: {}'.format(e), file=sys.stderr)
        return 2

    if args.emit_lock_graph:
        with open(args.emit_lock_graph, 'w', encoding='utf-8') as f:
            json.dump(sorted(lock_edges), f, indent=1)

    cwd = os.getcwd()
    if args.format == 'json':
        print(json.dumps([{'check': f.check,
                           'path': os.path.relpath(f.path, cwd)
                           if f.path.startswith(cwd) else f.path,
                           'line': f.line,
                           'message': f.message} for f in findings],
                         indent=1))
    else:
        for finding in findings:
            print(finding.render(relative_to=cwd))
        if findings:
            print('pstlint: {} finding(s). Fix them, or silence an '
                  'intentional exception with '
                  '# pstlint: disable=<check>(reason).'.format(len(findings)))
        else:
            print('pstlint: clean ({} check group(s) over {}).'.format(
                len(checks) if checks else len(analysis.CHECKS),
                ', '.join(os.path.relpath(r, cwd) if r.startswith(cwd) else r
                          for r in roots)))
    return 1 if findings else 0


if __name__ == '__main__':
    sys.exit(main())
