"""``python -m petastorm_tpu.tools.replay`` — re-materialize one ledgered batch.

Operational counterpart of :func:`petastorm_tpu.lineage.replay_record`:
point it at a provenance ledger directory (``PETASTORM_TPU_LINEAGE_DIR``
of the training run) and a batch id, and it re-opens the dataset,
re-reads exactly the recorded row-group spans, re-applies the recorded
slices/permutations/dtype sanitization, and writes (or just verifies)
the batch the training loop saw::

    python -m petastorm_tpu.tools.replay --ledger /nvme/lineage \\
        --batch-id 41237 --verify --out /tmp/batch41237.npz

``--verify`` additionally asserts the replay bit-identical against the
record's per-field CRC32 content digest (exit 3 on mismatch — the
dataset or decode stack drifted since the run). ``--print-record`` dumps
the raw record JSON for audits. Exit codes: 0 ok, 1 usage/lookup error,
2 not replayable (inexact record, transform, unsupported mode),
3 digest mismatch.

``--diff-ledgers A B`` compares two runs' ledgers batch-by-batch (per-
field CRC32 digests) and reports the first batch id where they diverge —
the triage entry point when a ``deterministic=True`` resume was supposed
to be bit-identical but training curves split (see
docs/troubleshoot.rst, "resumed stream diverged"). Exit 0 when the
overlapping id range matches, 3 on divergence, 1 when a ledger is empty
or unreadable.
"""

import argparse
import json
import sys


def _ledger_digests(path):
    """batch_id -> (digest dict, rows) across every ledger under
    ``path`` (a directory or a single file)."""
    import os

    from petastorm_tpu import lineage

    if os.path.isfile(path):
        _, records = lineage.read_ledger_file(path)
    else:
        records = [r for _, _, recs in lineage.read_ledger_dir(path)
                   for r in recs]
    out = {}
    for record in records:
        batch_id = record.get('batch_id')
        if batch_id is not None:
            out[batch_id] = (record.get('digest'), record.get('rows'))
    return out


def diff_ledgers(path_a, path_b):
    """Compare two ledgers' digest sequences. Returns a JSON-safe report
    with ``diverged`` (first differing batch id or None) and coverage
    facts; raises ``LookupError`` when either side has no records."""
    a, b = _ledger_digests(path_a), _ledger_digests(path_b)
    if not a:
        raise LookupError('no ledger records under {!r}'.format(path_a))
    if not b:
        raise LookupError('no ledger records under {!r}'.format(path_b))
    common = sorted(set(a) & set(b))
    diverged = None
    detail = None
    for batch_id in common:
        if a[batch_id] != b[batch_id]:
            diverged = batch_id
            digest_a, rows_a = a[batch_id]
            digest_b, rows_b = b[batch_id]
            fields = sorted(set(digest_a or {}) | set(digest_b or {}))
            detail = {'fields_differing': [f for f in fields
                                           if (digest_a or {}).get(f)
                                           != (digest_b or {}).get(f)],
                      'rows_a': rows_a, 'rows_b': rows_b}
            break
    return {'a': str(path_a), 'b': str(path_b),
            'records_a': len(a), 'records_b': len(b),
            'common_batches': len(common),
            'common_range': [common[0], common[-1]] if common else None,
            'only_in_a': len(set(a) - set(b)),
            'only_in_b': len(set(b) - set(a)),
            'diverged': diverged,
            'divergence': detail}


def main(argv=None):
    parser = argparse.ArgumentParser(
        description='Deterministically re-materialize one batch from a '
                    'provenance ledger')
    parser.add_argument('--ledger',
                        help='ledger directory (PETASTORM_TPU_LINEAGE_DIR '
                             'of the run) or a single ledger-*.jsonl file')
    parser.add_argument('--batch-id', type=int,
                        help='the batch to re-materialize (record batch_id)')
    parser.add_argument('--diff-ledgers', nargs=2, metavar=('A', 'B'),
                        help='compare two runs\' ledgers and report the '
                             'first batch id whose per-field digests '
                             'diverge (exit 3 on divergence)')
    parser.add_argument('--pid', type=int, default=None,
                        help='producing process pid, to disambiguate when '
                             'several pipelines ledgered into one directory')
    parser.add_argument('--verify', action='store_true',
                        help='assert the replay bit-identical against the '
                             'record\'s CRC32 content digest (exit 3 on '
                             'mismatch)')
    parser.add_argument('--out', default=None,
                        help='write the replayed batch as a .npz file')
    parser.add_argument('--print-record', action='store_true',
                        help='dump the raw record JSON instead of a summary')
    args = parser.parse_args(argv)

    if args.diff_ledgers:
        try:
            report = diff_ledgers(*args.diff_ledgers)
        except LookupError as e:
            print('replay: {}'.format(e), file=sys.stderr)
            return 1
        print(json.dumps(report))
        return 3 if report['diverged'] is not None else 0

    if args.ledger is None or args.batch_id is None:
        parser.error('--ledger and --batch-id are required '
                     '(or use --diff-ledgers A B)')

    import os

    from petastorm_tpu import lineage

    try:
        if os.path.isfile(args.ledger):
            ctx, records = lineage.read_ledger_file(args.ledger)
            matches = [r for r in records
                       if r.get('batch_id') == args.batch_id
                       and (args.pid is None or r.get('pid') == args.pid)]
            if not matches:
                ids = sorted(r.get('batch_id') for r in records)
                raise LookupError(
                    'batch_id {} not in {} (ids {}..{}, {} records)'.format(
                        args.batch_id, args.ledger,
                        ids[0] if ids else '-', ids[-1] if ids else '-',
                        len(ids)))
            record = matches[0]
        else:
            ctx, record = lineage.find_record(args.ledger, args.batch_id,
                                              pid=args.pid)
    except LookupError as e:
        print('replay: {}'.format(e), file=sys.stderr)
        return 1

    if args.print_record:
        print(json.dumps({'ctx': ctx, 'record': record}, indent=1))
        if not (args.verify or args.out):
            return 0

    try:
        if args.verify:
            batch = lineage.verify_record(record, ctx)
        else:
            batch = lineage.replay_record(record, ctx)
    except lineage.ReplayMismatchError as e:
        print('replay: DIGEST MISMATCH: {}'.format(e), file=sys.stderr)
        return 3
    except lineage.ReplayError as e:
        print('replay: not replayable: {}'.format(e), file=sys.stderr)
        return 2

    if args.out:
        import numpy as np
        np.savez(args.out, **batch)

    summary = {
        'batch_id': record.get('batch_id'),
        'rows': record.get('rows'),
        'padded': record.get('padded', 0),
        'fields': {name: {'shape': list(arr.shape), 'dtype': str(arr.dtype)}
                   for name, arr in batch.items()},
        'segments': len(record.get('segments') or []),
        'tiers': sorted({s.get('tier') for s in record.get('segments') or []}),
        'verified': bool(args.verify),
        'out': args.out,
    }
    print(json.dumps(summary))
    return 0


if __name__ == '__main__':
    sys.exit(main())
