"""``petastorm-tpu-serve`` — run a data-service decode tier from the shell.

Operational counterpart of :func:`petastorm_tpu.data_service.serve_dataset`:
starts one server process that reads, decodes, and streams a dataset to
remote trainers (``RemoteReader``), so a CPU decode tier can be deployed
with a process supervisor or container entry point instead of custom
Python. Prints one JSON line with the bound endpoints (trainers dial
``data_endpoint``), then serves until the stream completes or SIGINT/
SIGTERM. Role parity: the reference keeps decode inside the training
process (``reader.py:50``); the disaggregated tier is this repo's
TPU-first extension — trainer hosts spend their cores on staging, not
jpeg decode.
"""

import argparse
import json
import signal
import sys
import threading


def main(argv=None):
    parser = argparse.ArgumentParser(
        description='Serve a petastorm_tpu dataset to remote trainers')
    parser.add_argument('dataset_url')
    parser.add_argument('--bind', default='tcp://*:5555',
                        help='zmq data endpoint (default tcp://*:5555; '
                             'control/rpc default to the next two ports)')
    parser.add_argument('--fields', nargs='*', default=None,
                        help='schema field names/regexes (default: all)')
    parser.add_argument('--workers', type=int, default=4)
    parser.add_argument('--epochs', type=int, default=1,
                        help='epochs to serve; 0 = infinite')
    parser.add_argument('--cache-type', default='null',
                        choices=['null', 'memory', 'disk'])
    parser.add_argument('--shuffle-row-groups', action='store_true')
    parser.add_argument('--seed', type=int, default=None)
    parser.add_argument('--deterministic', action='store_true',
                        help='deterministic stream mode: chunk order is a '
                             'pure function of (dataset, seed, epoch, '
                             'position), chunks carry stream-cursor tags, '
                             'and a sole consumer can reconnect to a '
                             'replacement server bit-identically '
                             '(--await-cursor on the replacement)')
    parser.add_argument('--sndhwm', type=int, default=4,
                        help='per-consumer chunk buffer (backpressure)')
    parser.add_argument('--batch-reader', action='store_true',
                        help='serve a plain-Parquet store via '
                             'make_batch_reader instead of the decoded '
                             'tensor reader')
    parser.add_argument('--auth-key-file', default=None,
                        help='file whose bytes key the stream MACs '
                             '(consumers pass the same auth_key)')
    parser.add_argument('--snapshot-path', default=None,
                        help='arm periodic self-snapshots (crash recovery)')
    parser.add_argument('--snapshot-every', type=int, default=16)
    parser.add_argument('--resume', default=None, metavar='SNAPSHOT',
                        help='restart from a snapshot written by a '
                             'previous --snapshot-path run')
    parser.add_argument('--drain-grace', type=float, default=5.0,
                        help='seconds to keep sockets open after the '
                             'stream is served: lets zmq flush queued '
                             'chunks and the END broadcast reach slow '
                             'consumers before teardown (default 5)')
    parser.add_argument('--metrics-port', type=int, default=None,
                        metavar='PORT',
                        help='start the Prometheus scrape endpoint '
                             '(petastorm_tpu.metrics.MetricsExporter) on '
                             'this port; 0 binds an ephemeral port. The '
                             'bound URL is printed as metrics_endpoint in '
                             'the JSON status line. Until now the exporter '
                             'was reachable only programmatically — this '
                             'makes a shell-deployed decode tier scrapable.')
    parser.add_argument('--no-lineage', action='store_true',
                        help='do not ship per-chunk provenance segments on '
                             'the wire; required while any trainer predates '
                             'the lineage sidecar (old consumers crash '
                             'unpacking the reserved payload key)')
    parser.add_argument('--max-consumers', type=int, default=None,
                        metavar='N',
                        help='admission-control capacity: consumers past N '
                             'get a typed ServerOverloaded refusal at '
                             'attach instead of degrading everyone')
    parser.add_argument('--lease-s', type=float, default=None,
                        help='control-plane lease duration (heartbeats go '
                             'out at a third of it; consumers declare the '
                             'server dead one lease after the last one). '
                             'Default: PETASTORM_TPU_LEASE_S or 10')
    parser.add_argument('--await-cursor', action='store_true',
                        help='defer the reader build until the first '
                             'consumer attaches: a REPLACEMENT server for '
                             'a dead deterministic peer then resumes from '
                             'the consumer\'s shipped cursor and continues '
                             'the stream bit-identically (reader flags '
                             'here must match the dead server\'s)')
    args = parser.parse_args(argv)

    from petastorm_tpu.data_service import serve_dataset

    auth_key = None
    if args.auth_key_file:
        # Verbatim file bytes: stripping would silently alter binary keys
        # whose edge bytes are ASCII whitespace, and the consumers MAC
        # with the raw bytes they loaded.
        with open(args.auth_key_file, 'rb') as f:
            auth_key = f.read()

    if (args.snapshot_path or args.resume) and args.workers != 1:
        # Crash recovery dedupes by (server_id, seq): resume must re-produce
        # chunks in the original order, which needs a single-worker reader
        # (serve_dataset docstring contract).
        print('petastorm-tpu-serve: snapshot/resume requires deterministic '
              'chunk order; forcing --workers 1 (was {})'.format(args.workers),
              file=sys.stderr, flush=True)
        args.workers = 1

    reader_kwargs = {
        'workers_count': args.workers,
        'num_epochs': None if args.epochs == 0 else args.epochs,
        'cache_type': args.cache_type,
        'shuffle_row_groups': args.shuffle_row_groups,
    }
    if args.deterministic:
        reader_kwargs['deterministic'] = True
    if args.seed is not None:
        reader_kwargs['seed'] = args.seed
    if args.fields:
        reader_kwargs['schema_fields'] = args.fields
    if args.batch_reader:
        from petastorm_tpu import make_batch_reader
        reader_kwargs['reader_factory'] = make_batch_reader

    # Handlers first: a supervisor's SIGTERM during a slow dataset open
    # must request clean teardown, not take the default kill and orphan
    # pool workers. The FIRST signal requests a graceful drain (finish
    # the in-flight chunk, broadcast an exact END, report `drained`); a
    # SECOND one forces immediate teardown.
    drain_requested = threading.Event()
    stop = threading.Event()

    def _on_signal(*_):
        if drain_requested.is_set():
            stop.set()
        else:
            drain_requested.set()

    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, _on_signal)

    exporter = None
    if args.metrics_port is not None:
        # Before the (possibly slow) dataset open: a supervisor's scrape
        # target should answer from process start, and a bind failure on a
        # chosen port must fail fast, not after minutes of store listing.
        from petastorm_tpu.metrics import start_http_exporter
        exporter = start_http_exporter(port=args.metrics_port)

    try:
        server = serve_dataset(args.dataset_url, args.bind,
                               sndhwm=args.sndhwm, auth_key=auth_key,
                               snapshot_path=args.snapshot_path,
                               snapshot_every=args.snapshot_every,
                               snapshot_resume=args.resume,
                               lineage=not args.no_lineage,
                               lease_s=args.lease_s,
                               max_consumers=args.max_consumers,
                               await_cursor=args.await_cursor,
                               **reader_kwargs)
    except BaseException:
        if exporter is not None:
            exporter.stop()
        raise
    status = {'data_endpoint': server.data_endpoint,
              'control_endpoint': server.control_endpoint,
              'rpc_endpoint': server.rpc_endpoint,
              'state': server.state}
    if exporter is not None:
        status['metrics_endpoint'] = exporter.address
    print(json.dumps(status), flush=True)

    # wait() fires when the READER is exhausted — up to sndhwm chunks can
    # still sit in the zmq send queue and the END broadcast keeps repeating
    # for slow joiners, so hold the sockets open for a drain grace before
    # stop() (which closes with linger=0, discarding anything queued).
    drained = False
    while not stop.is_set():
        if drain_requested.is_set():
            # Graceful drain (first SIGTERM/SIGINT): stop admitting,
            # finish the in-flight chunk, END with the exact served count
            # — zero chunks lost, and the final stream cursor lands in
            # the server's stats for a replacement to pick up. Non-
            # blocking: the wait() below observes completion, and a
            # second signal still forces teardown promptly.
            server.drain(timeout_s=0)
        if server.wait(0.5):
            drained = server.state == 'drained'
            stop.wait(args.drain_grace)
            break
    final = {'state': 'drained' if (drained or server.state == 'drained')
             else ('stopped' if stop.is_set() else 'served'),
             'served_chunks': server.served_chunks}
    server.stop()
    print(json.dumps(final), flush=True)
    if exporter is not None:
        exporter.stop()
    return 0


if __name__ == '__main__':
    sys.exit(main())
