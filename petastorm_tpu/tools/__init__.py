"""Dataset maintenance tools (parity: reference ``petastorm/tools/``)."""
