"""Dataset copy tool: column subset / not-null filter / re-chunk copy.

Parity: reference ``petastorm/tools/copy_dataset.py:34-90`` (which drives a
Spark job; this is a pyarrow/JVM-free reimplementation using our own reader
and writer).
"""

import argparse
import sys

from petastorm_tpu.reader import make_reader
from petastorm_tpu.etl.writer import DatasetWriter
from petastorm_tpu.etl.dataset_metadata import get_schema_from_dataset_url


def copy_dataset(source_url, target_url, field_regex=None, not_null_fields=None,
                 rows_per_row_group=None, row_group_size_mb=None,
                 partition_fields=(), storage_options=None):
    """Copy (a subset of) a materialized dataset to a new location."""
    from petastorm_tpu.predicates import in_lambda

    source_schema = get_schema_from_dataset_url(source_url, storage_options)
    if field_regex:
        schema = source_schema.create_schema_view(field_regex)
    else:
        schema = source_schema

    predicate = None
    if not_null_fields:
        not_null_fields = list(not_null_fields)
        # in_lambda passes one positional value per field (reference
        # predicates.py:97-101).
        predicate = in_lambda(not_null_fields,
                              lambda *values: all(v is not None for v in values))

    rows_copied = 0
    with make_reader(source_url, schema_fields=list(schema.fields),
                     predicate=predicate, shuffle_row_groups=False,
                     storage_options=storage_options) as reader:
        with DatasetWriter(target_url, schema,
                           rows_per_row_group=rows_per_row_group,
                           row_group_size_mb=row_group_size_mb,
                           partition_fields=partition_fields,
                           storage_options=storage_options) as writer:
            for row in reader:
                writer.write(row._asdict())
                rows_copied += 1
    return rows_copied


def main(argv=None):
    parser = argparse.ArgumentParser(description='Copy a petastorm_tpu dataset')
    parser.add_argument('source_url')
    parser.add_argument('target_url')
    parser.add_argument('--field-regex', nargs='+', default=None)
    parser.add_argument('--not-null-fields', nargs='+', default=None)
    parser.add_argument('--rows-per-row-group', type=int, default=None)
    parser.add_argument('--row-group-size-mb', type=int, default=None)
    parser.add_argument('--partition-fields', nargs='+', default=())
    args = parser.parse_args(argv if argv is not None else sys.argv[1:])
    count = copy_dataset(args.source_url, args.target_url,
                         field_regex=args.field_regex,
                         not_null_fields=args.not_null_fields,
                         rows_per_row_group=args.rows_per_row_group,
                         row_group_size_mb=args.row_group_size_mb,
                         partition_fields=tuple(args.partition_fields))
    print('Copied {} rows'.format(count))
    return 0


if __name__ == '__main__':
    sys.exit(main())
