"""Spark-session argparse plumbing for CLIs (optional pyspark).

Parity: reference ``petastorm/tools/spark_session_cli.py:19-50``
(``--master`` / ``--spark-session-config key=val`` flags +
``configure_spark``).
"""


def add_configure_spark_arguments(parser):
    """Add ``--master`` and ``--spark-session-config`` to an ArgumentParser."""
    parser.add_argument('--master', type=str, default='local[*]',
                        help='Spark master (default local[*])')
    parser.add_argument('--spark-session-config', type=str, nargs='*', default=[],
                        help='Extra spark conf entries as key=value pairs')
    return parser


def configure_spark(builder, args):
    """Apply parsed CLI args onto a ``SparkSession.Builder``."""
    builder = builder.master(args.master)
    for entry in args.spark_session_config:
        key, sep, value = entry.partition('=')
        if not sep:
            raise ValueError('--spark-session-config entries must be key=value, '
                             'got {!r}'.format(entry))
        builder = builder.config(key, value)
    return builder


def create_spark_session(args, app_name='petastorm_tpu'):
    """Build a SparkSession from CLI args (requires pyspark)."""
    try:
        from pyspark.sql import SparkSession
    except ImportError:
        raise ImportError('create_spark_session requires pyspark')
    return configure_spark(SparkSession.builder.appName(app_name), args).getOrCreate()
