"""``python -m petastorm_tpu.tools.fleet`` — preprocessing-fleet worker
entry point and fleet status probe.

Two modes:

``--worker DATASET_URL``
    Run one fleet worker: a :func:`petastorm_tpu.data_service.
    serve_dataset` server joined to ``--job`` (its control-plane
    heartbeats then carry the job + capacity announce the fleet
    registry folds into membership), with optional per-tenant quotas
    (``--tenant-quotas``). Prints ONE JSON announce line (server id,
    endpoints, job) — the line :class:`petastorm_tpu.fleet.autoscaler.
    SubprocessLauncher` reads to learn the member key it must wait for
    — then serves until the stream ends or a signal lands. Signal
    discipline matches ``petastorm-tpu-serve``: the FIRST SIGTERM/
    SIGINT requests a graceful drain (finish the in-flight chunk,
    broadcast an exact END, exit 0 = drained), a SECOND one forces
    teardown. The ``fleet-worker-kill`` fault site fires right after
    the announce — the chaos drill for a spawn that dies mid-scale-up.

``--status``
    Probe a fleet and print ONE JSON line: per-worker membership (the
    ``fleet`` rpc verb of every ``--rpc`` endpoint) plus the
    fleet-aggregated per-tenant SLO snapshot (the ``pst_fleet_tenant_*``
    series out of :func:`petastorm_tpu.metrics.scrape_fleet_metrics`).
    One line, JSON, exit 0 if every endpoint answered — fit for a
    watch loop or a CI assertion.
"""

import argparse
import json
import signal
import sys
import threading

#: Tenant SLO series surfaced in the --status snapshot.
_TENANT_METRIC_PREFIX = 'pst_fleet_tenant_'


def _status(args):
    import zmq

    from petastorm_tpu import metrics as metrics_mod
    from petastorm_tpu.serving.server import _one_shot

    context = zmq.Context.instance()

    def _rpc(endpoint, request):
        return _one_shot(context, endpoint, request,
                         timeout_ms=int(args.timeout_s * 1000))

    members = {}
    unreachable = []
    for ep in args.rpc:
        try:
            reply = _rpc(ep, {'cmd': 'fleet'})
        except Exception:  # noqa: BLE001 - a dead member is a data point
            unreachable.append(ep)
            continue
        sid = reply.get('server_id')
        if isinstance(sid, (bytes, bytearray)):
            reply['server_id'] = bytes(sid).hex()
        members[ep] = {k: reply.get(k) for k in
                       ('server_id', 'state', 'job', 'capacity',
                        'consumers', 'sent', 'tenants')}
    fleet = metrics_mod.scrape_fleet_metrics(
        args.rpc, lambda ep: _rpc(ep, {'cmd': 'metrics'}))
    tenant_slo = {name: metric for name, metric
                  in (fleet.get('aggregate') or {}).items()
                  if name.startswith(_TENANT_METRIC_PREFIX)}
    print(json.dumps({'members': members,
                      'tenant_slo': tenant_slo,
                      'unreachable': sorted(set(unreachable)
                                            | set(fleet['unreachable']))},
                     default=str), flush=True)
    return 1 if (unreachable or fleet['unreachable']) else 0


def _worker(args):
    from petastorm_tpu import faults
    from petastorm_tpu.data_service import serve_dataset
    from petastorm_tpu.fleet.tenancy import TenantLedger, TenantQuota

    tenants = None
    if args.tenant_quotas:
        quotas = {tenant: TenantQuota.coerce(kwargs) for tenant, kwargs
                  in json.loads(args.tenant_quotas).items()}
        tenants = TenantLedger(quotas=quotas)

    # Handlers before the (possibly slow) dataset open, same contract as
    # petastorm-tpu-serve: first signal drains, second forces.
    drain_requested = threading.Event()
    stop = threading.Event()

    def _on_signal(*_):
        if drain_requested.is_set():
            stop.set()
        else:
            drain_requested.set()

    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, _on_signal)

    reader_kwargs = {'workers_count': args.workers,
                     'num_epochs': None if args.epochs == 0 else args.epochs}
    if args.deterministic:
        reader_kwargs['deterministic'] = True
    if args.seed is not None:
        reader_kwargs['seed'] = args.seed

    server = serve_dataset(args.dataset_url, args.bind,
                           sndhwm=args.sndhwm,
                           lease_s=args.lease_s,
                           max_consumers=args.max_consumers,
                           await_cursor=args.await_cursor,
                           job_id=args.job,
                           tenants=tenants,
                           **reader_kwargs)
    # The announce line the launcher blocks on. server_id hex IS the
    # registry member key (binary heartbeats carry no separate name), so
    # the launcher can wait_for_member() on exactly this worker.
    print(json.dumps({'server_id': server._server_id.hex(),
                      'job': args.job,
                      'data_endpoint': server.data_endpoint,
                      'control_endpoint': server.control_endpoint,
                      'rpc_endpoint': server.rpc_endpoint,
                      'state': server.state}), flush=True)
    # Chaos seam: a worker that dies AFTER announcing but BEFORE its
    # first heartbeat reaches the registry — the mid-scale-up SIGKILL
    # the autoscaler's spawn-grace reap exists for.
    faults.maybe_inject('fleet-worker-kill')

    drained = False
    while not stop.is_set():
        if drain_requested.is_set():
            server.drain(timeout_s=0)
        if server.wait(0.5):
            drained = server.state == 'drained'
            stop.wait(args.drain_grace)
            break
    drained = drained or server.state == 'drained'
    final = {'state': 'drained' if drained
             else ('stopped' if stop.is_set() else 'served'),
             'served_chunks': server.served_chunks}
    server.stop()
    if tenants is not None:
        tenants.close()
    print(json.dumps(final), flush=True)
    # Exit 0 only on a clean drain or full serve: the launcher's
    # drain() judges zero-loss by this code.
    return 0 if (drained or final['state'] == 'served') else 1


def main(argv=None):
    parser = argparse.ArgumentParser(
        description='petastorm_tpu preprocessing-fleet worker / status '
                    'probe')
    mode = parser.add_mutually_exclusive_group(required=True)
    mode.add_argument('--worker', action='store_true',
                      help='run one fleet worker (announce, serve, '
                           'drain on SIGTERM)')
    mode.add_argument('--status', action='store_true',
                      help='print one JSON line of fleet membership + '
                           'per-tenant SLO snapshot')
    parser.add_argument('dataset_url', nargs='?',
                        help='dataset to serve (--worker)')
    parser.add_argument('--job', default=None,
                        help='fleet job id (default: '
                             'PETASTORM_TPU_FLEET_JOB)')
    parser.add_argument('--bind', default='tcp://127.0.0.1:*',
                        help='zmq data endpoint (--worker); control/rpc '
                             'take the next two ports')
    parser.add_argument('--workers', type=int, default=2)
    parser.add_argument('--epochs', type=int, default=1,
                        help='epochs to serve; 0 = infinite')
    parser.add_argument('--deterministic', action='store_true')
    parser.add_argument('--seed', type=int, default=None)
    parser.add_argument('--sndhwm', type=int, default=4)
    parser.add_argument('--max-consumers', type=int, default=None)
    parser.add_argument('--lease-s', type=float, default=None)
    parser.add_argument('--await-cursor', action='store_true',
                        help='defer the reader build until a consumer '
                             'ships a resume cursor (replacement worker '
                             'in a deterministic fleet)')
    parser.add_argument('--tenant-quotas', default=None, metavar='JSON',
                        help='per-tenant quota dict, e.g. '
                             '\'{"a": {"max_consumers": 2, '
                             '"credits": 8, "mem_budget": "512m"}}\'')
    parser.add_argument('--drain-grace', type=float, default=5.0)
    parser.add_argument('--rpc', nargs='*', default=[],
                        help='worker rpc endpoints to probe (--status)')
    parser.add_argument('--timeout-s', type=float, default=5.0,
                        help='per-endpoint probe deadline (--status)')
    args = parser.parse_args(argv)

    if args.status:
        if not args.rpc:
            parser.error('--status needs at least one --rpc endpoint')
        return _status(args)
    if not args.dataset_url:
        parser.error('--worker needs a dataset_url')
    return _worker(args)


if __name__ == '__main__':
    sys.exit(main())
