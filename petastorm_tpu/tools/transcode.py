"""Offline transcode ETL: pre-fill the decoded-chunk store.

``python -m petastorm_tpu.tools.transcode --dataset-url URL --store DIR``
walks every row-group of a dataset through the tensor decode path ONCE and
leaves the NVMe decoded-chunk store (:mod:`petastorm_tpu.chunk_store`)
fully populated — so steady-state production training never touches a
JPEG: epoch 0 of every later job mmaps decoded tensors (``decode_s`` ~ 0,
the zero-decode property the epoch-2 chunk-store test proves, moved to
epoch 0).

Everything rides the existing store machinery — ``tensor_chunk_key`` (so
training readers compute the identical keys), the flock'd single-writer
protocol (N transcode jobs or a transcode racing a training job produce
exactly one entry per chunk), and the write-behind thread (decode never
blocks on NVMe). Because write-behind DROPS on queue overflow (by design),
one pass is not a guarantee: the tool re-walks the dataset until a pass
serves every row-group from the store (drops re-enqueue on their next
miss — the documented self-healing), or ``--max-passes`` is exhausted.

The tool prints one JSON report line::

    {"row_groups": 12, "passes": 2, "writes": 12, "preexisting": 0,
     "bytes_written": 123456, "complete": true, ...}

Exit status: 0 when the final verification pass was all hits, 1 otherwise.
"""

import argparse
import json
import sys

#: Deeper-than-default write-behind queue: an ETL job's whole point is the
#: spill, so give it room before the drop-and-retry path kicks in.
_ETL_WRITER_QUEUE_DEPTH = 64


def transcode_dataset(dataset_url, store_path, schema_fields=None,
                      workers_count=4, max_passes=4, flush_timeout_s=300.0,
                      size_limit=None):
    """Pre-fill ``store_path`` with every decoded chunk of ``dataset_url``.

    Returns the report dict (see module docstring). ``schema_fields``
    narrows the transcoded columns — the store key carries the schema
    hash, so a training job selecting different fields misses and refills
    its own entries (document the field set with your dataset).
    """
    from petastorm_tpu import make_tensor_reader

    report = {'dataset_url': dataset_url, 'store': store_path, 'passes': 0,
              'row_groups': None, 'writes': 0, 'write_races': 0,
              'preexisting': 0, 'bytes_written': 0, 'unstorable': 0,
              'complete': False}
    for _ in range(max_passes):
        report['passes'] += 1
        reader = make_tensor_reader(
            dataset_url, schema_fields=schema_fields,
            reader_pool_type='thread', workers_count=workers_count,
            shuffle_row_groups=False, num_epochs=1,
            cache_type='chunk-store', cache_location=store_path,
            cache_size_limit=size_limit,
            cache_extra_settings={'writer_queue_depth':
                                  _ETL_WRITER_QUEUE_DEPTH})
        store = reader.chunk_store
        try:
            for _ in reader:
                pass
            # The pass only counts once its write-behind backlog is ON
            # DISK — a timed-out flush means entries may be missing and
            # another pass must verify.
            flushed = store.flush(timeout_s=flush_timeout_s)
            stats = store.stats()
        finally:
            reader.stop()
            reader.join()
        report['row_groups'] = stats['hits'] + stats['misses']
        report['writes'] += stats['writes']
        report['write_races'] += stats['write_races']
        report['bytes_written'] += stats['bytes_written']
        report['unstorable'] = stats['unstorable']
        if report['passes'] == 1:
            # First-pass hits are entries a previous transcode (or a
            # training job's epoch-0 spill) already published.
            report['preexisting'] = stats['hits']
        if stats['unstorable']:
            # Object/void columns can never be stored: more passes would
            # loop forever re-decoding them. Narrow schema_fields.
            break
        if flushed and stats['misses'] == 0:
            # Every row-group served from the store: the dataset is fully
            # transcoded (this pass doubled as the verification read).
            report['complete'] = True
            break
    return report


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog='python -m petastorm_tpu.tools.transcode',
        description='Pre-fill the NVMe decoded-chunk store so production '
                    'training never decodes a JPEG')
    parser.add_argument('--dataset-url', required=True,
                        help='petastorm_tpu dataset URL (file://...)')
    parser.add_argument('--store', required=True,
                        help='chunk-store directory (the same path training '
                             'jobs pass as cache_location / '
                             'PETASTORM_TPU_CHUNK_STORE)')
    parser.add_argument('--fields', nargs='*', default=None,
                        help='schema fields to transcode (default: all; the '
                             'store key carries the field set)')
    parser.add_argument('--workers', type=int, default=4)
    parser.add_argument('--max-passes', type=int, default=4,
                        help='re-walk budget until a pass is all hits '
                             '(write-behind drops self-heal on later passes)')
    parser.add_argument('--size-limit', type=int, default=None,
                        help='store size cap in bytes (oldest entries evict '
                             'past it — a cap smaller than the dataset can '
                             'never transcode completely)')
    args = parser.parse_args(argv)

    report = transcode_dataset(
        args.dataset_url, args.store, schema_fields=args.fields,
        workers_count=args.workers, max_passes=args.max_passes,
        size_limit=args.size_limit)
    print(json.dumps(report))
    return 0 if report['complete'] else 1


if __name__ == '__main__':
    sys.exit(main())
