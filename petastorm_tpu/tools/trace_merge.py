"""Merge per-process trace sidecar files into one Chrome trace JSON.

A run traced with ``PETASTORM_TPU_TRACE_DIR`` leaves one ``trace-<pid>-
<uid>.jsonl`` sidecar per process (the loader process plus every pool
worker). This CLI folds a finished run's sidecars into a single timeline —
worker ``decode`` tracks under their real pids next to the loader's
``assemble``/``stage``/``wait`` tracks — ready for chrome://tracing or
Perfetto::

    python -m petastorm_tpu.tools.trace_merge --dir /tmp/pst-trace \\
        --out /tmp/pipeline.json --summary

Torn trailing lines (a worker killed mid-write) are skipped, so merging a
crashed run works. ``--summary`` prints the per-span latency digest
(count/total/p50/p99) to stdout as JSON.
"""

import argparse
import json
import os
import sys


def main(argv=None):
    from petastorm_tpu.trace import TRACE_DIR_ENV, Tracer

    parser = argparse.ArgumentParser(
        prog='python -m petastorm_tpu.tools.trace_merge',
        description='Merge per-process trace sidecar (JSONL) files from a '
                    'finished run into one Chrome trace JSON.')
    parser.add_argument('--dir', dest='spill_dir',
                        default=os.environ.get(TRACE_DIR_ENV),
                        help='sidecar directory (default: ${})'
                        .format(TRACE_DIR_ENV))
    parser.add_argument('--out', dest='out_path', default=None,
                        help='output trace path (default: '
                             '<dir>/merged_trace.json)')
    parser.add_argument('--summary', action='store_true',
                        help='also print the per-span count/total/p50/p99 '
                             'digest as JSON')
    args = parser.parse_args(argv)

    if not args.spill_dir:
        parser.error('no sidecar directory: pass --dir or set {}'
                     .format(TRACE_DIR_ENV))
    if not os.path.isdir(args.spill_dir):
        parser.error('not a directory: {!r}'.format(args.spill_dir))
    out_path = args.out_path or os.path.join(args.spill_dir,
                                             'merged_trace.json')

    # spill_dir=False: the merge tool must never append a sidecar of its
    # own to the directory it is merging.
    tracer = Tracer(spill_dir=False, role='trace-merge')
    merged = tracer.merge_process_files(args.spill_dir)
    if merged == 0:
        print('no sidecar files under {!r}'.format(args.spill_dir),
              file=sys.stderr)
        return 1
    tracer.export_chrome_trace(out_path)
    report = {'merged_files': merged,
              'events': len(tracer.events),
              'out': out_path}
    if args.summary:
        report['summary'] = tracer.summary()
    print(json.dumps(report, indent=1))
    return 0


if __name__ == '__main__':
    sys.exit(main())
