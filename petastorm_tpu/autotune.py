"""Adaptive pipeline autotuner: feedback-driven knob control.

The pipeline's speed knobs — ``workers_count``, ``prefetch``,
``arena_depth``, ``inflight``, ventilation depth — are fixed at
construction, yet the optimum moves at runtime: the first (decode-bound)
epoch and the cache-warm (collate-bound) steady state want different
settings, and shared-host load swings capacity severalfold between runs
(PROFILE_r05). tf.data's autotuning (Murray et al., VLDB 2021) and DALI's
pipeline-depth tuning both show a feedback controller over stage latencies
recovers near-hand-tuned throughput without per-workload sweeps. Every
signal such a controller needs already exists here (PR-3 heartbeats, PR-2
staging counters, consumer wait accounting); this module closes the loop:

:class:`AutoTuner`
    A control thread that samples a telemetry function every
    ``interval_s``, computes per-tick deltas of the cumulative wait
    counters, classifies the **dominant bottleneck** (reader-starved /
    dispatch-bound / arena-bound / consumer-bound / balanced), and nudges
    one knob per decision in an AIMD/hill-climbing loop:

    * reader-starved -> grow the worker pool (``ThreadPool.resize``) and
      loosen ventilation;
    * dispatch-bound -> widen the per-device ``device_put`` windows
      (the per-device sharded staging path), then the batch-level
      in-flight window, then prefetch depth;
    * arena-bound -> deepen the host-arena pool;
    * consumer-bound -> shrink everything one step and tighten the
      ventilator's results-queue watermark — release memory instead of
      racing ahead of a consumer that isn't draining.

    Safeguards: per-knob min/max clamps, hysteresis (a classification
    must repeat for ``hysteresis`` consecutive ticks before any action),
    a post-action cooldown, a throughput guard that *reverts* the last
    action when the delivered rate drops past ``throughput_tolerance``,
    and a hard pause whenever the watchdog (``health.py``) has an active
    stall episode — the tuner must never fight stall recovery. Every
    decision lands in a bounded log (surfaced as
    ``Reader.diagnostics()['autotune']`` / loader ``stats['autotune']``)
    plus per-knob trace counter events.

Enable with ``autotune=True`` (or an :class:`AutotuneConfig`) on
``make_reader`` / ``make_batch_reader`` / ``make_tensor_reader`` /
``JaxLoader``, or process-wide via the ``PETASTORM_TPU_AUTOTUNE``
environment variable (``1``/``true`` = on with defaults; a number = on
with that tick interval in seconds; ``0``/``off``/unset = off). A
``JaxLoader`` wrapping an autotuned reader adopts its knobs so one
controller tunes the whole pipeline (mirroring the watchdog's
``attach_health`` ownership rule).
"""

import logging
import os
import threading
import time
from collections import deque

logger = logging.getLogger(__name__)

ENV_VAR = 'PETASTORM_TPU_AUTOTUNE'

# Bottleneck classification labels (the vocabulary tests and docs assert
# against; deliberately overlapping with health.py's stall vocabulary where
# the meaning matches).
READER_STARVED = 'reader-starved'
DISPATCH_BOUND = 'dispatch-bound'
ARENA_BOUND = 'arena-bound'
CONSUMER_BOUND = 'consumer-bound'
INPUT_BOUND = 'input-bound'     # consumer waits but no stage blames a wait:
                                # the pipeline's own work is the limit
BALANCED = 'balanced'


def active_bottleneck_classes(snapshot):
    """Read the ``pst_autotune_bottleneck`` enum gauge out of a metrics
    snapshot (one process's ``collect()``, or a fleet aggregate from
    :func:`petastorm_tpu.metrics.aggregate_snapshots`): ``{pipeline:
    class}`` for every pipeline whose active class reads >= 1. The
    shared vocabulary bridge between the in-process tuner and the fleet
    autoscaler — both sides consume the classification through this one
    parse instead of re-reading gauge samples by hand."""
    metric = (snapshot or {}).get('pst_autotune_bottleneck') or {}
    active = {}
    for sample in metric.get('samples', ()):
        if sample.get('value', 0) >= 1:
            labels = sample.get('labels') or {}
            active[labels.get('pipeline', '')] = labels.get('class')
    return active


def autotune_enabled(explicit=None):
    """Resolve the ``autotune=`` knob against the environment default.

    ``explicit`` wins when not None (an :class:`AutotuneConfig` counts as
    True); otherwise ``PETASTORM_TPU_AUTOTUNE`` decides
    (unset/empty/0/off = disabled)."""
    if explicit is not None:
        return bool(explicit)
    raw = os.environ.get(ENV_VAR, '').strip().lower()
    return raw not in ('', '0', 'off', 'false', 'no')


def env_interval():
    """A numeric ``PETASTORM_TPU_AUTOTUNE`` value is the tick interval in
    seconds; any other truthy value keeps the built-in default. ``'1'``
    is the documented plain on-switch, NOT a 1-second interval."""
    raw = os.environ.get(ENV_VAR, '').strip()
    if raw == '1':
        return None
    try:
        value = float(raw)
    except ValueError:
        return None
    return value if value > 0 else None


class AutotuneConfig(object):
    """Bounds and pacing for the :class:`AutoTuner` control loop.

    Pass an instance as ``autotune=`` to any reader/loader factory. Every
    knob has a ``[min, max]`` clamp the tuner never crosses; ``hysteresis``
    and ``cooldown`` are in ticks; ``throughput_tolerance`` is the
    fractional rate drop past which the last action is reverted.
    """

    def __init__(self, interval_s=0.5, hysteresis=2, cooldown=2,
                 throughput_tolerance=0.15, log_size=256,
                 min_workers=1, max_workers=None,
                 min_prefetch=1, max_prefetch=8,
                 min_inflight=1, max_inflight=8,
                 min_device_inflight=1, max_device_inflight=8,
                 min_device_stream_mb=1, max_device_stream_mb=64,
                 min_arena_depth=2, max_arena_depth=16,
                 min_watermark=4,
                 min_decode_threads=1, max_decode_threads=None,
                 starve_frac=0.05, signal_frac=0.05):
        if interval_s <= 0:
            raise ValueError('interval_s must be positive, got {}'.format(interval_s))
        self.interval_s = float(interval_s)
        self.hysteresis = max(1, int(hysteresis))
        self.cooldown = max(0, int(cooldown))
        self.throughput_tolerance = float(throughput_tolerance)
        self.log_size = int(log_size)
        self.min_workers = max(1, int(min_workers))
        if max_workers is None:
            # Threads beyond a few per core only add GIL ping-pong; the
            # decode path releases the GIL, so oversubscribe moderately.
            max_workers = min(32, 4 * (os.cpu_count() or 4))
        self.max_workers = max(self.min_workers, int(max_workers))
        self.min_prefetch = max(1, int(min_prefetch))
        self.max_prefetch = max(self.min_prefetch, int(max_prefetch))
        self.min_inflight = max(1, int(min_inflight))
        self.max_inflight = max(self.min_inflight, int(max_inflight))
        self.min_device_inflight = max(1, int(min_device_inflight))
        self.max_device_inflight = max(self.min_device_inflight,
                                       int(max_device_inflight))
        self.min_device_stream_mb = max(0, int(min_device_stream_mb))
        self.max_device_stream_mb = max(self.min_device_stream_mb,
                                        int(max_device_stream_mb))
        self.min_arena_depth = max(1, int(min_arena_depth))
        self.max_arena_depth = max(self.min_arena_depth, int(max_arena_depth))
        self.min_watermark = max(2, int(min_watermark))
        self.min_decode_threads = max(1, int(min_decode_threads))
        if max_decode_threads is None:
            # Decode threads are GIL-free C++ — mild oversubscription
            # hides IO bubbles, heavy oversubscription just context-
            # switches (2605.08731's single-thread-decode analysis).
            max_decode_threads = 2 * (os.cpu_count() or 4)
        self.max_decode_threads = max(self.min_decode_threads,
                                      int(max_decode_threads))
        # Below this fraction of wall time blocked, the consumer counts as
        # "kept fed"; above it, the biggest stage-wait fraction must also
        # clear signal_frac to earn the blame.
        self.starve_frac = float(starve_frac)
        self.signal_frac = float(signal_frac)


def resolve_config(explicit=None):
    """The effective config for an ``autotune=`` value: pass through an
    :class:`AutotuneConfig`, else defaults with any env-var interval."""
    if isinstance(explicit, AutotuneConfig):
        return explicit
    interval = env_interval()
    return AutotuneConfig(interval_s=interval) if interval else AutotuneConfig()


class Knob(object):
    """One tunable pipeline parameter: live getter/setter plus clamps.

    ``get``/``set`` must be thread-safe — they run on the tuner thread
    against state owned by pipeline threads (``ThreadPool.resize``, queue
    maxsize under its mutex, plain atomic attribute writes)."""

    def __init__(self, name, get, set, lo, hi):
        self.name = name
        self.get = get
        self.set = set
        self.lo = int(lo)
        self.hi = int(hi)

    def clamp(self, value):
        return max(self.lo, min(self.hi, int(value)))


# --------------------------------------------------------------------------
# bottleneck classification
# --------------------------------------------------------------------------

def classify_loader(deltas, gauges, dt, config):
    """Dominant bottleneck of a JaxLoader pipeline from one tick's wait
    deltas (seconds blocked per stage) and queue gauges.

    Returns ``(label, detail)``. The rule set mirrors the stats doc: the
    consumer's own blocked fraction says whether the pipeline keeps up;
    when it doesn't, whichever stage spent the biggest fraction of the
    tick *waiting* (reader pull / arena acquire / transfer fence) is the
    bottleneck its knob can relieve."""
    wait_frac = deltas.get('wait_s', 0.0) / dt
    reader_frac = deltas.get('reader_wait_s', 0.0) / dt
    arena_frac = deltas.get('arena_wait_s', 0.0) / dt
    ready_frac = deltas.get('ready_wait_s', 0.0) / dt
    capacity = gauges.get('queue_capacity') or 1
    fill = (gauges.get('queue_depth') or 0) / capacity
    if wait_frac < config.starve_frac:
        if fill >= 0.5:
            return (CONSUMER_BOUND,
                    'consumer blocked {:.0%} of the tick with the staging '
                    'queue {:.0%} full — pipeline is ahead of the trainer'
                    .format(wait_frac, fill))
        return (BALANCED, 'consumer blocked only {:.0%} of the tick'
                .format(wait_frac))
    candidates = [(READER_STARVED, reader_frac),
                  (ARENA_BOUND, arena_frac),
                  (DISPATCH_BOUND, ready_frac)]
    label, frac = max(candidates, key=lambda kv: kv[1])
    if frac < config.signal_frac:
        return (INPUT_BOUND,
                'consumer blocked {:.0%} of the tick but no stage reports '
                'waiting — pipeline work itself is the limit'.format(wait_frac))
    return (label, 'consumer blocked {:.0%}; dominant stage wait: {} '
            '{:.0%} of the tick'.format(wait_frac, label, frac))


def classify_reader(deltas, gauges, dt, config):
    """Bottleneck of a standalone Reader (no staging engine): judged from
    the worker pool's results-queue occupancy — a full queue means the
    consumer is the limit, an empty one with work still ventilated means
    the decode tier is."""
    capacity = gauges.get('results_queue_capacity') or 0
    if capacity <= 0:
        # Unbounded results queue: occupancy carries no saturation signal
        # (any backlog would read as "full" against a fake capacity) — do
        # nothing rather than shrink a pool on garbage evidence.
        return (BALANCED, 'results queue unbounded: no fill signal')
    fill = (gauges.get('results_queue_depth') or 0) / capacity
    pending = gauges.get('ventilated_unprocessed') or 0
    if fill >= 0.6:
        return (CONSUMER_BOUND,
                'results queue {:.0%} full — consumer is the limit'.format(fill))
    if fill <= 0.1 and pending > 0:
        return (READER_STARVED,
                'results queue {:.0%} full with {} ventilated item(s) still '
                'unprocessed — decode tier is the limit'.format(fill, pending))
    return (BALANCED, 'results queue {:.0%} full'.format(fill))


# Per-classification grow preferences: the first listed knob that exists
# and is not already at its clamp takes one additive step. ``input-bound``
# (the pipeline's own work is the limit — on image workloads that work IS
# decode) grows native decode parallelism FIRST: widening the GIL-free
# C++ decode pool attacks the bottleneck directly, where another Python
# worker mostly adds scheduling overhead; workers remain the fallback
# once the thread budget clamps. ``reader-starved`` keeps workers first
# (a standalone reader's signal — the queue is empty because too few
# row-groups are in flight) with decode threads as its second lever.
_GROW_ACTIONS = {
    READER_STARVED: (('workers', 1), ('decode_threads', 2),
                     ('results_watermark', 8)),
    INPUT_BOUND: (('decode_threads', 2), ('workers', 1)),
    # dispatch-bound steps the PER-DEVICE in-flight window first (the
    # per-device sharded staging path, ISSUE 14): transfer backpressure
    # forms per device stream, so widening every stream's window attacks
    # it directly. Next come the dispatch-cost levers: pinned arenas
    # (DMA-friendly host slabs make each transfer cheaper) and the
    # inline/batched threshold (growing it routes more fields through
    # the single C++ batched transfer per wave); the batch-level window
    # and prefetch depth remain the fallbacks once those clamp (and the
    # only levers on single-device pipelines, which have none of the
    # per-device knobs).
    DISPATCH_BOUND: (('device_inflight', 1), ('arena_pinned', 1),
                     ('device_stream_min_mb', 8), ('inflight', 1),
                     ('prefetch', 1)),
    ARENA_BOUND: (('arena_depth', 2),),
}

# Consumer-bound shrink: one step down on every present knob (release
# memory/CPU), with the ventilation watermark tightened hardest — over-
# ventilating row-groups into a saturated results queue only pins memory
# and stretches tail latency. decode_threads participates (incl. the
# governor's mem-shrink sweep): a pipeline ahead of its consumer has no
# business saturating the host's cores either.
_SHRINK_STEPS = (('workers', 1), ('prefetch', 1), ('inflight', 1),
                 ('device_inflight', 1), ('arena_depth', 2),
                 ('arena_pinned', 1),
                 ('decode_threads', 2), ('results_watermark', 8))

# Cumulative telemetry counters (everything else is a gauge).
_CUMULATIVE_KEYS = ('batches', 'wait_s', 'reader_wait_s', 'arena_wait_s',
                    'ready_wait_s')

#: Classifications during which the NVMe chunk store's write-behind writer
#: is throttled (PACED to one entry per ``throttle_delay_s``, never fully
#: paused — fill epochs are naturally reader-starved, and a hard pause
#: would keep the store cold forever): dispatch-bound (transfers already
#: saturate the host's IO/DMA paths), reader-starved and input-bound
#: (decode/pipeline work is the limit — epoch-0 spill must not steal CPU
#: or NVMe bandwidth from it). Balanced/consumer-bound ticks restore full
#: writer speed: the pipeline is ahead, spill is free.
WRITER_THROTTLE_CLASSES = (DISPATCH_BOUND, READER_STARVED, INPUT_BOUND)


def writer_throttle_listener(store):
    """A classification listener (see :meth:`AutoTuner.add_listener`)
    driving ``store.set_writer_throttled``: armed (paced spill) while the
    tick's bottleneck class is in :data:`WRITER_THROTTLE_CLASSES`,
    released otherwise. Wired automatically by ``Reader``/``JaxLoader``
    when the pipeline carries a
    :class:`~petastorm_tpu.chunk_store.DecodedChunkStore`.
    """
    def listener(label, detail=None):
        store.set_writer_throttled(label in WRITER_THROTTLE_CLASSES)
    return listener


_tuner_id_lock = threading.Lock()
_tuner_id_next = 0


def _next_tuner_id():
    """Process-unique tuner index for the metrics ``pipeline`` label."""
    global _tuner_id_next
    with _tuner_id_lock:
        tuner_id, _tuner_id_next = _tuner_id_next, _tuner_id_next + 1
        return tuner_id


class AutoTuner(object):
    """Feedback control thread over a set of :class:`Knob`\\ s.

    :param telemetry_fn: ``() -> dict`` sampled once per tick. Keys in
        ``_CUMULATIVE_KEYS`` are treated as monotonically increasing
        counters (the tuner differences them); everything else is a gauge.
        Must be cheap and must not block.
    :param knobs: dict name -> :class:`Knob`.
    :param config: :class:`AutotuneConfig` (defaults applied when None).
    :param classify_fn: ``(deltas, gauges, dt, config) -> (label, detail)``.
    :param watchdog_active_fn: ``() -> bool``; True pauses tuning for the
        tick (an active stall episode — recovery owns the pipeline).
    :param memory_state_fn: ``() -> int`` pressure-ladder level of the
        host memory governor (``membudget.get_governor().pressure_level``;
        0 while unarmed). At advisory or worse the tuner stops growing and
        instead takes one ``mem-shrink`` step per cooldown — prefetch,
        in-flight window, arena depth, workers, watermark all step down —
        releasing host memory ahead of the governor's harder rungs.
    """

    def __init__(self, telemetry_fn, knobs, config=None, tracer=None,
                 classify_fn=classify_loader, watchdog_active_fn=None,
                 memory_state_fn=None, name='pst-autotune'):
        self._telemetry_fn = telemetry_fn
        self.knobs = dict(knobs)
        self.config = config if config is not None else AutotuneConfig()
        if tracer is None:
            from petastorm_tpu.trace import NullTracer
            tracer = NullTracer()
        self._tracer = tracer
        self._classify_fn = classify_fn
        self._watchdog_active_fn = watchdog_active_fn
        self._memory_state_fn = memory_state_fn
        self.mem_shrinks = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=name)
        self._lock = threading.Lock()
        self._log = deque(maxlen=self.config.log_size)
        self._trajectory = deque(maxlen=self.config.log_size)
        self._t0 = None
        self._prev = None
        self._prev_t = None
        self._streak = (None, 0)
        self._cooldown = 0
        self._pending = None      # last action awaiting its throughput verdict
        self._paused_streak = False
        self._listeners = []
        self.ticks = 0
        self.paused_ticks = 0
        self.reverts = 0
        self.last_class = None
        # Registry mirror (petastorm_tpu.metrics): the bottleneck class as
        # an enum gauge (per pipeline, exactly one class label at 1 — the
        # service-level signal ROADMAP-1 autoscaling consumes), knob values
        # as gauges, and a per-action decision counter. Gauges carry a
        # per-tuner ``pipeline`` label: two controllers in one process
        # (train + eval loaders) must not overwrite each other's class or
        # flap each other's knob values.
        from petastorm_tpu import metrics as metrics_mod
        self._pipeline_label = 'tuner-{}'.format(_next_tuner_id())
        self._m_decisions = metrics_mod.counter(
            'pst_autotune_decisions_total',
            'Autotuner knob decisions, by action', labelnames=('action',))
        self._m_bottleneck = metrics_mod.gauge(
            'pst_autotune_bottleneck',
            'Current bottleneck classification (enum gauge: per pipeline, '
            'the active class reads 1, every other 0)',
            labelnames=('pipeline', 'class'))
        self._m_knobs = metrics_mod.gauge(
            'pst_autotune_knob', 'Current autotuner knob values',
            labelnames=('pipeline', 'knob'))
        self._metric_class = None
        self._metric_classes_seen = set()

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        self._thread.start()
        return self

    def stop(self, join_timeout_s=5):
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=join_timeout_s)
        # Retire this pipeline's gauge children: a stopped tuner must not
        # keep scraping as a live bottleneck (class stuck at 1), and a
        # trainer building loaders per epoch must not grow 'tuner-N'
        # label children in the process registry without bound.
        for label in self._metric_classes_seen:
            self._m_bottleneck.remove(self._pipeline_label, label)
        self._metric_classes_seen.clear()
        self._metric_class = None
        for name in self.knobs:
            self._m_knobs.remove(self._pipeline_label, name)

    @property
    def alive(self):
        return self._thread.is_alive()

    def add_listener(self, fn):
        """Register ``fn(label, detail)`` to run after every classified
        tick (not while the watchdog pause holds). Listeners observe the
        bottleneck class without being knobs — e.g. the chunk store's
        write-behind throttle (:func:`writer_throttle_listener`). Must be
        cheap; exceptions are logged and swallowed."""
        self._listeners.append(fn)
        return fn

    def _loop(self):
        while not self._stop.wait(self.config.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - the tuner must not die of a bug
                logger.exception('autotune tick failed')

    # -- control loop ------------------------------------------------------

    def tick(self, now=None):
        """One control pass (called by the thread; tests drive it directly
        with a synthetic clock). Returns the decision dict when a knob
        changed, else None."""
        now = now if now is not None else time.monotonic()
        if self._t0 is None:
            self._t0 = now
        snap = self._telemetry_fn() or {}
        prev, prev_t = self._prev, self._prev_t
        self._prev, self._prev_t = snap, now
        self.ticks += 1
        if self._watchdog_active_fn is not None and self._watchdog_active_fn():
            # A diagnosed stall episode is in progress: recovery owns the
            # pipeline. Tuning against it would blur the diagnosis (and a
            # knob change can mask the stall the watchdog is escalating).
            self.paused_ticks += 1
            self._streak = (None, 0)
            self._pending = None
            if not self._paused_streak:
                self._paused_streak = True
                self._record({'action': 'paused',
                              'detail': 'watchdog stall episode active'}, now)
            return None
        self._paused_streak = False
        if self._memory_state_fn is not None and self._mem_pressure():
            # Advisory-or-worse memory pressure: the governor's ladder
            # owns the pipeline's direction. Growing any knob would add
            # bytes against the budget, and the throughput guard would
            # "revert" memory relief the moment rate dipped — so both are
            # suspended, and one additive shrink step runs per cooldown
            # instead (the same AIMD step _shrink uses, applied for bytes
            # rather than for a consumer-bound classification).
            self._pending = None
            self._streak = (None, 0)
            if self._cooldown > 0:
                self._cooldown -= 1
                return None
            changes = self._shrink()
            if not changes:
                return None   # every knob already at its floor
            self.mem_shrinks += 1
            decision = {'action': 'mem-shrink', 'class': 'memory-pressure',
                        'changes': changes,
                        'detail': 'host memory governor at advisory or '
                                  'worse: biasing every knob down one step'}
            self._record(decision, now)
            self._snapshot_trajectory(now)
            self._cooldown = self.config.cooldown
            return decision
        if prev is None:
            self._snapshot_trajectory(now)
            return None
        dt = now - prev_t
        if dt <= 0:
            return None
        deltas = {k: snap.get(k, 0) - prev.get(k, 0) for k in _CUMULATIVE_KEYS}
        if any(v < 0 for v in deltas.values()):
            # A cumulative counter went BACKWARD: someone reset the stats
            # mid-run (bench reset_stats() after warmup). The tick's
            # deltas — and any pending action verdict judged on them —
            # are garbage; discard both and re-baseline from this sample.
            self._pending = None
            self._streak = (None, 0)
            return None
        rate = deltas.get('batches', 0) / dt
        label, detail = self._classify_fn(deltas, snap, dt, self.config)
        self.last_class = label
        if label != self._metric_class:
            if self._metric_class is not None:
                self._m_bottleneck.labels(
                    self._pipeline_label, self._metric_class).set(0)
            self._m_bottleneck.labels(self._pipeline_label, label).set(1)
            self._metric_class = label
            self._metric_classes_seen.add(label)
        for listener in self._listeners:
            try:
                listener(label, detail)
            except Exception:  # noqa: BLE001 - a listener must not kill the tuner
                logger.exception('autotune classification listener failed')

        # Throughput guard first: the verdict on the previous action is due
        # once its cooldown expired (one settling window after the change).
        if self._pending is not None and self._cooldown <= 1:
            pending, self._pending = self._pending, None
            base = pending['base_rate']
            tol = self.config.throughput_tolerance
            if base > 0 and rate < base * (1.0 - tol):
                for name, old, _new in pending['changes']:
                    self.knobs[name].set(old)
                self.reverts += 1
                decision = {'action': 'revert', 'class': label,
                            'changes': [(n, new, old)
                                        for n, old, new in pending['changes']],
                            'rate': round(rate, 2),
                            'detail': 'rate {:.1f}/s fell past {:.0%} of '
                                      'pre-action {:.1f}/s'.format(
                                          rate, 1.0 - tol, base)}
                self._record(decision, now)
                self._snapshot_trajectory(now)
                self._cooldown = self.config.cooldown
                self._streak = (None, 0)
                return decision

        if self._cooldown > 0:
            self._cooldown -= 1
            return None

        streak_label, streak_count = self._streak
        if label != streak_label:
            self._streak = (label, 1)
        else:
            self._streak = (label, streak_count + 1)
        if self._streak[1] < self.config.hysteresis:
            return None
        if label in (BALANCED,):
            return None

        changes = (self._shrink() if label == CONSUMER_BOUND
                   else self._grow(label))
        if not changes:
            self._streak = (label, 0)
            return None
        decision = {'action': 'shrink' if label == CONSUMER_BOUND else 'grow',
                    'class': label, 'changes': changes,
                    'rate': round(rate, 2), 'detail': detail}
        self._record(decision, now)
        self._snapshot_trajectory(now)
        self._pending = {'changes': changes, 'base_rate': rate}
        self._cooldown = self.config.cooldown
        self._streak = (label, 0)
        return decision

    def _mem_pressure(self):
        """True at advisory (level 1) or worse; a dying probe reads 0."""
        try:
            return int(self._memory_state_fn()) >= 1
        except Exception:  # noqa: BLE001 - a dying probe must not kill the tuner
            return False

    def _grow(self, label):
        for name, step in _GROW_ACTIONS.get(label, ()):
            knob = self.knobs.get(name)
            if knob is None:
                continue
            old = knob.get()
            if old >= knob.hi:
                # At (or hand-set above) the clamp: clamping old+step would
                # MOVE THE KNOB DOWN — shrinking the very resource the
                # classifier wants more of. Out-of-range stays untouched.
                continue
            new = knob.clamp(old + step)
            if new != old:
                knob.set(new)
                return [(name, old, new)]
        return []

    def _shrink(self):
        changes = []
        for name, step in _SHRINK_STEPS:
            knob = self.knobs.get(name)
            if knob is None:
                continue
            old = knob.get()
            if old <= knob.lo:   # mirror of _grow: never clamp upward
                continue
            # One additive step, floored at lo — deliberately NOT hi-
            # clamped: a hand-set above-range value must step down
            # gradually, not collapse to the clamp in one decision.
            new = max(knob.lo, old - step)
            if new != old:
                knob.set(new)
                changes.append((name, old, new))
        return changes

    # -- bookkeeping -------------------------------------------------------

    def _record(self, decision, now):
        decision = dict(decision)
        decision['t'] = round(now - self._t0, 3)
        decision['tick'] = self.ticks
        self._m_decisions.labels(decision['action']).inc()
        with self._lock:
            self._log.append(decision)
        self._tracer.instant(
            'autotune:{}:{}'.format(decision['action'],
                                    decision.get('class', '-')),
            cat='autotune',
            args={k: v for k, v in decision.items() if k != 'detail'})
        logger.debug('autotune decision: %s', decision)

    def _snapshot_trajectory(self, now):
        point = {'t': round(now - self._t0, 3)}
        for name, knob in self.knobs.items():
            try:
                point[name] = knob.get()
                self._tracer.counter('autotune_{}'.format(name), point[name],
                                     'autotune')
                self._m_knobs.labels(self._pipeline_label, name).set(
                    point[name])
            except Exception:  # noqa: BLE001 - a dying getter must not kill it
                point[name] = None
        with self._lock:
            self._trajectory.append(point)

    def stats(self):
        """Decision log + knob trajectory + current values (what rides in
        ``stats['autotune']`` / ``diagnostics()['autotune']``)."""
        knobs = {}
        for name, knob in self.knobs.items():
            try:
                knobs[name] = knob.get()
            except Exception:  # noqa: BLE001
                knobs[name] = None
        with self._lock:
            return {'ticks': self.ticks,
                    'paused_ticks': self.paused_ticks,
                    'reverts': self.reverts,
                    'mem_shrinks': self.mem_shrinks,
                    'last_class': self.last_class,
                    'knobs': knobs,
                    'decisions': list(self._log),
                    'trajectory': list(self._trajectory)}
