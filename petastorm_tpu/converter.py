"""In-memory DataFrame -> cached Parquet -> training-loader converter.

Parity: reference ``petastorm/spark/spark_dataset_converter.py`` —
``make_spark_converter(df)`` materializes a DataFrame into a parquet cache
dir (``:474-526``), dedupes repeated conversions of the same frame
(``:363-396``), narrows float precision (``:399-452``), registers atexit
cleanup (``:103-114,469``) and hands back an object that builds framework
loaders (``make_tf_dataset`` ``:142-172`` / ``make_torch_dataloader``
``:174-215``).

TPU-native redesign: the primary input is a **pandas DataFrame or pyarrow
Table** (TPU-VM hosts don't carry a JVM), the primary output is
``make_jax_loader`` producing mesh-sharded ``jax.Array`` batches; Spark
DataFrames are accepted when pyspark is importable. Deduplication is by
content fingerprint (sha1 of the Arrow IPC stream) instead of Spark
logical-plan equality — same effect (one materialization per distinct
frame), but exact rather than plan-heuristic.
"""

import atexit
import hashlib
import logging
import os
import shutil
import tempfile
import threading
import uuid
from contextlib import contextmanager

logger = logging.getLogger(__name__)

#: Parity with the reference's one config knob,
#: ``petastorm.spark.converter.parentCacheDirUrl`` (``spark_dataset_converter.py:42-54``).
CACHE_DIR_ENV = 'PETASTORM_TPU_CONVERTER_CACHE_DIR'

_conversion_cache = {}
_cache_lock = threading.Lock()
_default_parent_dir = None


def register_converter_cache_dir(url_or_path):
    """Set the default parent cache dir for :func:`make_converter`."""
    global _default_parent_dir
    _default_parent_dir = url_or_path


def _parent_cache_dir(explicit):
    parent = explicit or _default_parent_dir or os.environ.get(CACHE_DIR_ENV)
    if parent is None:
        parent = os.path.join(tempfile.gettempdir(), 'petastorm_tpu_converter_cache')
        logger.info('No converter cache dir configured (%s); using %s',
                    CACHE_DIR_ENV, parent)
    return parent


def _narrow_precision(table, precision):
    """float64->float32 when ``precision == 32`` (integers are left alone).

    Parity: the reference narrows DoubleType->FloatType unless the user asks
    for 64-bit (``spark_dataset_converter.py:399-452``); TPUs strongly prefer
    32-bit, so that is the default here too.
    """
    import pyarrow as pa

    if precision not in (32, 64):
        raise ValueError('precision must be 32 or 64, got {!r}'.format(precision))
    if precision == 64:
        return table
    fields = []
    changed = False
    for field in table.schema:
        if pa.types.is_float64(field.type):
            fields.append(field.with_type(pa.float32()))
            changed = True
        else:
            fields.append(field)
    if not changed:
        return table
    return table.cast(pa.schema(fields, metadata=table.schema.metadata))


def _to_arrow_table(df):
    """pandas / pyarrow / pyspark -> pyarrow.Table."""
    import pyarrow as pa

    if isinstance(df, pa.Table):
        return df
    try:
        import pandas as pd
        if isinstance(df, pd.DataFrame):
            return pa.Table.from_pandas(df, preserve_index=False)
    except ImportError:  # pragma: no cover
        pass
    # pyspark DataFrame (optional dependency)
    if hasattr(df, 'toPandas') and (hasattr(df, 'sql_ctx') or
                                    type(df).__module__.startswith('pyspark.')):
        return pa.Table.from_pandas(df.toPandas(), preserve_index=False)
    raise TypeError('make_converter expects a pandas DataFrame, pyarrow Table '
                    'or pyspark DataFrame; got {!r}'.format(type(df)))


def _fingerprint(table):
    """sha1 over the Arrow IPC stream: schema + data content.

    Chunk-layout independent with bounded memory: fixed 64Ki-row windows are
    sliced and combined one at a time, so content-identical tables that arrive
    with different record-batch boundaries hash identically while peak extra
    memory stays one window (not a contiguous copy of the table).
    """
    import pyarrow as pa

    class _HashSink(object):
        """File-like sink feeding sha1 incrementally — peak extra memory is
        one IPC chunk, not a full serialized copy of the table."""

        def __init__(self):
            self.digest = hashlib.sha1()

        def write(self, data):
            self.digest.update(memoryview(data))
            return len(data)

        def close(self):
            pass

        @property
        def closed(self):
            return False

    sink = _HashSink()
    window = 1 << 16
    with pa.ipc.new_stream(pa.PythonFile(sink, mode='w'), table.schema) as writer:
        for offset in range(0, table.num_rows, window):
            for batch in table.slice(offset, window).combine_chunks().to_batches():
                writer.write_batch(batch)
    return sink.digest.hexdigest()


class Converter(object):
    """A materialized DataFrame cache: builds readers/loaders over it.

    Parity: reference ``SparkDatasetConverter`` (``spark_dataset_converter.py:117-330``).
    """

    def __init__(self, cache_url, num_rows, fingerprint):
        self.dataset_url = cache_url
        self._num_rows = num_rows
        self._fingerprint = fingerprint
        self._deleted = False

    def __len__(self):
        return self._num_rows

    # -- loader factories --------------------------------------------------

    @contextmanager
    def make_jax_loader(self, batch_size=32, mesh=None, sharding=None,
                        num_epochs=None, workers_count=4, seed=None,
                        shuffle_row_groups=True, reader_pool_type='thread',
                        prefetch=2, shape_policies=None, last_batch='drop',
                        shuffling_queue_capacity=0, **reader_kwargs):
        """Context manager yielding a :class:`~petastorm_tpu.jax_loader.JaxLoader`
        over the cached data (mesh-sharded when ``mesh`` is given)."""
        from petastorm_tpu.jax_loader import JaxLoader
        from petastorm_tpu.reader import make_batch_reader

        with make_batch_reader(self.dataset_url,
                               reader_pool_type=reader_pool_type,
                               workers_count=workers_count,
                               num_epochs=num_epochs, seed=seed,
                               shuffle_row_groups=shuffle_row_groups,
                               **reader_kwargs) as reader:
            with JaxLoader(reader, batch_size, mesh=mesh, sharding=sharding,
                           prefetch=prefetch, shape_policies=shape_policies,
                           shuffling_queue_capacity=shuffling_queue_capacity,
                           seed=seed, last_batch=last_batch) as loader:
                yield loader

    @contextmanager
    def make_torch_dataloader(self, batch_size=32, num_epochs=None,
                              workers_count=4, seed=None,
                              shuffle_row_groups=True,
                              reader_pool_type='thread',
                              shuffling_queue_capacity=0, collate_fn=None,
                              **reader_kwargs):
        """Parity: reference ``make_torch_dataloader`` (``:277-306``)."""
        from petastorm_tpu.pytorch import DataLoader
        from petastorm_tpu.reader import make_batch_reader

        with make_batch_reader(self.dataset_url,
                               reader_pool_type=reader_pool_type,
                               workers_count=workers_count,
                               num_epochs=num_epochs, seed=seed,
                               shuffle_row_groups=shuffle_row_groups,
                               **reader_kwargs) as reader:
            with DataLoader(reader, batch_size=batch_size,
                            collate_fn=collate_fn,
                            shuffling_queue_capacity=shuffling_queue_capacity,
                            seed=seed) as loader:
                yield loader

    @contextmanager
    def make_tf_dataset(self, batch_size=32, num_epochs=None, workers_count=4,
                        seed=None, shuffle_row_groups=True,
                        reader_pool_type='thread', **reader_kwargs):
        """Parity: reference ``make_tf_dataset`` (``:224-274``); requires
        TensorFlow (optional in this environment)."""
        from petastorm_tpu.reader import make_batch_reader
        from petastorm_tpu.tf_utils import make_petastorm_dataset

        with make_batch_reader(self.dataset_url,
                               reader_pool_type=reader_pool_type,
                               workers_count=workers_count,
                               num_epochs=num_epochs, seed=seed,
                               shuffle_row_groups=shuffle_row_groups,
                               **reader_kwargs) as reader:
            dataset = make_petastorm_dataset(reader)
            if batch_size is not None:
                dataset = dataset.batch(batch_size)
            yield dataset

    # -- lifecycle ---------------------------------------------------------

    def delete(self):
        """Remove the cached files (reference ``SparkDatasetConverter.delete``)."""
        if self._deleted:
            return
        self._deleted = True
        with _cache_lock:
            _conversion_cache.pop(self._fingerprint, None)
        _delete_dataset_url(self.dataset_url)


def _delete_dataset_url(url):
    from petastorm_tpu.fs import FilesystemResolver

    try:
        resolver = FilesystemResolver(url)
        fs, path = resolver.filesystem(), resolver.get_dataset_path()
        if fs.exists(path):
            fs.rm(path, recursive=True)
    except Exception:
        # local-path fast path / best-effort cleanup
        local = url[len('file://'):] if url.startswith('file://') else url
        shutil.rmtree(local, ignore_errors=True)


def _cleanup_all():
    with _cache_lock:
        converters = list(_conversion_cache.values())
        _conversion_cache.clear()
    for conv in converters:
        try:
            conv._deleted = True
            _delete_dataset_url(conv.dataset_url)
        except Exception:  # pragma: no cover
            logger.warning('Failed to clean converter cache %s', conv.dataset_url)


atexit.register(_cleanup_all)  # parity: reference ``:103-114,469``


def make_converter(df, parent_cache_dir_url=None, precision=32,
                   rows_per_row_group=None, row_group_size_mb=None,
                   storage_options=None):
    """Materialize ``df`` to a cached Parquet store and return a
    :class:`Converter`.

    Repeated calls with identical content return the same converter without
    re-writing (parity: reference dedupe ``spark_dataset_converter.py:363-396``).
    """
    import json

    import pyarrow.parquet as pq

    from petastorm_tpu.fs import FilesystemResolver
    from petastorm_tpu.storage import NUM_ROW_GROUPS_KEY, ParquetStore

    table = _narrow_precision(_to_arrow_table(df), precision)
    parent = _parent_cache_dir(parent_cache_dir_url)
    # Dedupe key covers content AND materialization parameters — a repeat call
    # asking for different row-group sizing or cache location must re-write
    # (the reference keys its dedupe on row-group size too,
    # spark_dataset_converter.py:363-396).
    content_hash = _fingerprint(table)
    fingerprint = '{}:{}:{}:{}'.format(
        content_hash, parent, rows_per_row_group, row_group_size_mb)

    with _cache_lock:
        cached = _conversion_cache.get(fingerprint)
        if cached is not None:
            logger.info('Converter cache hit for fingerprint %s', content_hash[:12])
            return cached
    sub = 'conv_{}_{}'.format(content_hash[:16], uuid.uuid4().hex[:8])
    if '://' in parent:
        cache_url = parent.rstrip('/') + '/' + sub
    else:
        os.makedirs(parent, exist_ok=True)
        cache_url = 'file://' + os.path.join(os.path.abspath(parent), sub)

    resolver = FilesystemResolver(cache_url, storage_options)
    fs, path = resolver.filesystem(), resolver.get_dataset_path()
    fs.makedirs(path, exist_ok=True)

    if rows_per_row_group is None:
        if row_group_size_mb is not None:
            approx_row = max(1, table.nbytes // max(1, table.num_rows))
            rows_per_row_group = max(1, row_group_size_mb * 1024 * 1024 // approx_row)
        else:
            rows_per_row_group = min(max(1, table.num_rows), 64 * 1024)

    with fs.open(path + '/part-00000.parquet', 'wb') as f:
        pq.write_table(table, f, row_group_size=rows_per_row_group)

    # Plain-parquet cache (reference converter caches carry no petastorm
    # metadata either) + our row-group count index for fast listing.
    store = ParquetStore(cache_url, storage_options)
    store.write_common_metadata(
        table.schema, {NUM_ROW_GROUPS_KEY: json.dumps(store.num_row_groups_per_file())})

    converter = Converter(cache_url, table.num_rows, fingerprint)
    with _cache_lock:
        existing = _conversion_cache.get(fingerprint)
        if existing is not None:  # lost the race; drop our copy
            _delete_dataset_url(cache_url)
            return existing
        _conversion_cache[fingerprint] = converter
    logger.info('Materialized converter cache %s (%d rows)', cache_url, table.num_rows)
    return converter
