"""Mid-epoch checkpoint/resume for readers.

The reference has **no** reader-state checkpointing (SURVEY §5.4: closest
analogs are ``Reader.reset()`` and disk caches). On TPU pods that gap is
expensive: preemption is routine and restarting an epoch re-reads terabytes.
This module adds exactly-once-per-epoch resume at row granularity:

* every chunk a worker publishes is tagged with its ventilation key
  ``"piece:drop_partition"`` (see ``py_dict_worker``/``arrow_worker``);
* the consumer-side :class:`ConsumptionTracker` counts, per key, completed
  instances (a full pass over that row-group's rows) and the partial row
  position of the open instance;
* ``Reader.state_dict()`` serializes those counters (JSON-safe);
* a new Reader built with ``resume_state=`` skips, consumer-side, the
  already-consumed instances/rows: completed keys are dropped on their next
  arrival, a partially-consumed key drops its first ``partial`` rows.

Semantics:

* **Finite ``num_epochs``** — construct the resumed Reader with the *same*
  ``num_epochs``; skips are absolute, so the total delivered across sessions
  is exactly ``num_epochs`` passes.
* **Infinite ``num_epochs=None``** (the TPU training loop case) — skips are
  relative to the least-consumed key, preserving per-sample balance without
  discarding unbounded amounts of decode work.
* Rows held in downstream prefetch/shuffle buffers at checkpoint time count
  as consumed: resume never replays a delivered row (no duplicated training
  steps); un-trained in-flight rows return next epoch.

Determinism requirements: same dataset, same reader configuration. Worker
interleaving may reorder rows — the guarantee is multiset-exactness, not
order. For an *order-exact* (bit-identical stream) resume build the reader
with ``deterministic=True``: consumption tracking then collapses to the
compact stream cursor of :class:`petastorm_tpu.determinism.
DeterministicCursor` and resume fast-forwards the seed-stable permutation
instead of skipping chunks consumer-side (see ``docs/failure_model.rst``,
"Determinism & elastic resume").
"""

import logging

logger = logging.getLogger(__name__)

STATE_VERSION = 1


def chunk_key(piece_index, shuffle_row_drop_partition):
    drop_idx = shuffle_row_drop_partition[0] if shuffle_row_drop_partition else 0
    return '{}:{}'.format(piece_index, drop_idx)


class DeferredRowAccounting(object):
    """Mixin for batched results-queue readers: optional row-granular
    checkpoint attribution.

    Default (chunk-level): a chunk's rows are counted consumed the moment it
    leaves the reader. After :meth:`enable_deferred_rows` (requested by a
    loader that consumes rows strictly in delivery order, e.g. ``JaxLoader``
    without a shuffling buffer), ``_record_chunk`` queues (key, rows) and the
    loader attributes actual consumption via :meth:`rows_consumed` — rows
    buffered downstream at checkpoint time then re-deliver on resume instead
    of being lost.
    """

    _tracker = None
    _pending_rows = None

    def set_tracker(self, tracker):
        self._tracker = tracker

    def enable_deferred_rows(self):
        from collections import deque
        if self._pending_rows is None:
            self._pending_rows = deque()

    def _record_chunk(self, key, n_rows):
        """Called by read_next once a chunk's post-skip rows are delivered."""
        if self._tracker is None:
            return
        if self._pending_rows is not None:
            self._pending_rows.append((key, n_rows))
        else:
            self._tracker.rows_yielded(key, n_rows)

    def rows_consumed(self, n):
        """Attribute ``n`` consumed rows to chunks in delivery order."""
        if self._tracker is None or self._pending_rows is None:
            return
        while n > 0 and self._pending_rows:
            key, left = self._pending_rows[0]
            take = min(n, left)
            self._tracker.rows_yielded(key, take)
            n -= take
            if take == left:
                self._pending_rows.popleft()
            else:
                self._pending_rows[0] = (key, left - take)


class ConsumptionTracker(object):
    """Counts per-key consumption; computes resume-time skips.

    Thread-safe: the consuming side may be a background thread (JaxLoader's
    staging loop drives ``Reader.__next__``) while ``state_dict()`` is called
    from the training thread mid-iteration, so every mutation and the
    snapshot hold a lock — otherwise a checkpoint could capture ``done``
    incremented but ``partial`` not yet reset and silently drop rows on
    resume.
    """

    def __init__(self, resume_state=None, num_epochs=1):
        import threading
        self._lock = threading.Lock()
        self._done = {}      # key -> instances fully consumed (incl. prior sessions)
        self._partial = {}   # key -> rows consumed of the open instance
        self._totals = {}    # key -> rows per instance (observed)
        self._skip_instances = {}
        self._skip_rows = {}
        if resume_state:
            self._load(resume_state, num_epochs)

    def _load(self, state, num_epochs):
        if state.get('version') != STATE_VERSION:
            raise ValueError('Unsupported reader state version {!r}'.format(
                state.get('version')))
        keys = state.get('keys', {})
        if not keys:
            return
        if num_epochs is None:
            # Balance-preserving: only skip what a key is ahead of the
            # least-consumed key (absolute skips would discard unbounded
            # decode work in a long-running infinite loop).
            base = min(entry['done'] for entry in keys.values())
        else:
            base = 0
        for key, entry in keys.items():
            done = int(entry['done'])
            partial = int(entry.get('partial', 0))
            self._done[key] = done
            self._partial[key] = 0   # session-local position restarts
            if entry.get('total') is not None:
                self._totals[key] = int(entry['total'])
            skip = done - base
            if num_epochs is not None:
                skip = min(skip, num_epochs)
            if skip > 0:
                self._skip_instances[key] = skip
            if partial > 0:
                self._skip_rows[key] = partial

    # -- consumption events (called by results-queue readers) --------------

    def on_chunk(self, key, total_rows, det=None):
        """A new instance of ``key`` arrived with ``total_rows`` rows.
        Returns how many leading rows the consumer must drop.

        ``det`` (the chunk's deterministic-mode tag) is accepted for call-
        site uniformity with :class:`~petastorm_tpu.determinism.
        DeterministicCursor` and ignored here — multiset accounting does
        not care about order.

        Skipped instances/rows re-deliver consumption that prior sessions
        already counted in ``done``/``partial`` — they must NOT be counted
        again, or a resume-of-a-resume would over-skip.
        """
        del det
        with self._lock:
            self._totals[key] = total_rows
            if self._skip_instances.get(key, 0) > 0:
                self._skip_instances[key] -= 1
                return total_rows
            skip = self._skip_rows.pop(key, 0)
            if skip >= total_rows:
                # The prior session consumed at least this whole instance
                # (totals may have shrunk, e.g. config drift); drop it all.
                return total_rows
            if skip:
                self._partial[key] = skip
            return skip

    def rows_yielded(self, key, n):
        with self._lock:
            partial = self._partial.get(key, 0) + n
            total = self._totals.get(key)
            if total is not None and partial >= total:
                self._done[key] = self._done.get(key, 0) + 1
                partial = 0
            self._partial[key] = partial

    # -- persistence -------------------------------------------------------

    def state_dict(self):
        with self._lock:
            keys = {}
            for key in set(self._done) | set(self._partial) | set(self._totals):
                partial = self._partial.get(key, 0)
                # A still-pending partial skip is prior-session consumption
                # not yet re-observed; carry it forward for the next resume.
                keys[key] = {'done': self._done.get(key, 0),
                             'partial': partial or self._skip_rows.get(key, 0),
                             'total': self._totals.get(key)}
            return {'version': STATE_VERSION, 'keys': keys}
