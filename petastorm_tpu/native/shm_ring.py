"""ctypes bindings for the shared-memory ring transport (src/shm_ring.cc).

One SPSC ring per direction per worker. Blocking calls release the GIL
(ctypes CDLL), so a consumer waiting on a ring doesn't stall worker threads.
"""

import ctypes
import logging
import os

from petastorm_tpu.native.build import NativeBuildError, build_and_load

logger = logging.getLogger(__name__)

#: Where POSIX shared-memory objects surface as plain files (Linux
#: tmpfs). Shared by the process-pool rings here and the fleet wire's
#: ``pst-wire-*`` segment rings (``fleet/wire.py``) so segment listing,
#: liveness sweeps, and diagnostics all look at one directory.
SHM_DIR = '/dev/shm'


def shm_dir():
    """The shm mount, or None when the host has none — callers (the wire
    transport's shm tier, stale-segment sweeps) degrade gracefully."""
    return SHM_DIR if os.path.isdir(SHM_DIR) else None


def list_segments(prefix, base_dir=None):
    """Names of shm segments starting with ``prefix`` (e.g. the wire
    transport's ``pst-wire-``), sorted, for sweeps and tests."""
    d = base_dir or shm_dir()
    if d is None:
        return []
    try:
        return sorted(n for n in os.listdir(d) if n.startswith(prefix))
    except OSError:
        return []

RING_OK = 0
RING_ERR_SYS = -1
RING_ERR_ARGS = -2
RING_ERR_TIMEOUT = -3
RING_ERR_CLOSED = -4
RING_ERR_TOO_BIG = -5
RING_ERR_AGAIN = -6
RING_ERR_CAPACITY = -7

_lib = None
_load_failed = False


def _load():
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    try:
        lib = build_and_load('pst_shm_ring', ['shm_ring.cc'], link_flags=['-lrt'])
    except NativeBuildError as exc:
        logger.warning('shm ring transport unavailable: %s', exc)
        _load_failed = True
        return None
    lib.pst_ring_create.restype = ctypes.c_void_p
    lib.pst_ring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.pst_ring_open.restype = ctypes.c_void_p
    lib.pst_ring_open.argtypes = [ctypes.c_char_p]
    lib.pst_ring_close.restype = None
    lib.pst_ring_close.argtypes = [ctypes.c_void_p]
    lib.pst_ring_unlink.restype = ctypes.c_int
    lib.pst_ring_unlink.argtypes = [ctypes.c_char_p]
    lib.pst_ring_write.restype = ctypes.c_int
    lib.pst_ring_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_uint64, ctypes.c_int]
    lib.pst_ring_write_tagged.restype = ctypes.c_int
    lib.pst_ring_write_tagged.argtypes = [ctypes.c_void_p, ctypes.c_uint8,
                                          ctypes.c_char_p, ctypes.c_uint64,
                                          ctypes.c_int]
    lib.pst_ring_mark_closed.restype = None
    lib.pst_ring_mark_closed.argtypes = [ctypes.c_void_p]
    lib.pst_ring_peek.restype = ctypes.c_int
    lib.pst_ring_peek.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
    lib.pst_ring_pop.restype = ctypes.c_int
    lib.pst_ring_pop.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64]
    lib.pst_ring_wait.restype = ctypes.c_int
    lib.pst_ring_wait.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
                                  ctypes.c_int]
    lib.pst_ring_readable_bytes.restype = ctypes.c_uint64
    lib.pst_ring_readable_bytes.argtypes = [ctypes.c_void_p]
    lib.pst_ring_capacity.restype = ctypes.c_uint64
    lib.pst_ring_capacity.argtypes = [ctypes.c_void_p]
    lib.pst_ring_set_flags.restype = None
    lib.pst_ring_set_flags.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.pst_ring_get_flags.restype = ctypes.c_uint32
    lib.pst_ring_get_flags.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


def available():
    return _load() is not None


class RingClosed(Exception):
    """Producer closed (drained) or FINISHED flag aborted a blocked write."""


class RingTimeout(Exception):
    pass


class ShmRing(object):
    """One endpoint of a shared-memory SPSC ring."""

    def __init__(self, handle, name, owner):
        self._h = handle
        self.name = name
        self._owner = owner
        self._closed = False

    @classmethod
    def create(cls, name, capacity):
        lib = _load()
        if lib is None:
            raise RuntimeError('shm ring native library unavailable')
        h = lib.pst_ring_create(name.encode(), capacity)
        if not h:
            raise OSError('failed to create shm ring {!r}'.format(name))
        return cls(h, name, owner=True)

    @classmethod
    def open(cls, name):
        lib = _load()
        if lib is None:
            raise RuntimeError('shm ring native library unavailable')
        h = lib.pst_ring_open(name.encode())
        if not h:
            raise OSError('failed to open shm ring {!r}'.format(name))
        return cls(h, name, owner=False)

    def write(self, data, timeout_ms=-1):
        if not isinstance(data, bytes):
            data = bytes(data)
        rc = _load().pst_ring_write(self._h, data, len(data), timeout_ms)
        self._check_write_rc(rc, len(data))

    def write_tagged(self, tag, payload, timeout_ms=-1):
        """Write ``tag`` (one byte) + ``payload`` as a single message without
        concatenating on the Python side."""
        if not isinstance(payload, bytes):
            payload = bytes(payload)
        rc = _load().pst_ring_write_tagged(self._h, tag[0], payload,
                                           len(payload), timeout_ms)
        self._check_write_rc(rc, len(payload) + 1)

    @staticmethod
    def _check_write_rc(rc, nbytes):
        if rc == RING_OK:
            return
        if rc == RING_ERR_CLOSED:
            raise RingClosed()
        if rc == RING_ERR_TIMEOUT:
            raise RingTimeout()
        if rc == RING_ERR_TOO_BIG:
            raise ValueError(
                'message of {} bytes exceeds ring capacity/2; raise '
                'result_ring_bytes (ShmProcessPool) or shrink row-groups'.format(nbytes))
        raise OSError('ring write failed (rc={})'.format(rc))

    def read(self, timeout_ms=0):
        """Next message as bytes; None when empty (timeout_ms=0 = non-blocking).

        Raises RingClosed once the producer marked closed and the ring drained.
        """
        lib = _load()
        length = ctypes.c_uint64()
        rc = lib.pst_ring_wait(self._h, ctypes.byref(length), timeout_ms)
        if rc == RING_ERR_AGAIN or rc == RING_ERR_TIMEOUT:
            return None
        if rc == RING_ERR_CLOSED:
            raise RingClosed()
        if rc != RING_OK:
            raise OSError('ring peek failed (rc={})'.format(rc))
        buf = bytearray(length.value)
        view = (ctypes.c_char * length.value).from_buffer(buf)
        rc = lib.pst_ring_pop(self._h, view, length.value)
        del view
        if rc != RING_OK:
            raise OSError('ring pop failed (rc={})'.format(rc))
        # memoryview: lets callers slice off framing bytes without copying
        return memoryview(buf)

    def mark_closed(self):
        _load().pst_ring_mark_closed(self._h)

    def set_flags(self, flags):
        _load().pst_ring_set_flags(self._h, flags)

    def get_flags(self):
        return _load().pst_ring_get_flags(self._h)

    @property
    def readable_bytes(self):
        return _load().pst_ring_readable_bytes(self._h)

    @property
    def capacity(self):
        return _load().pst_ring_capacity(self._h)

    def close(self):
        if self._closed:
            return
        self._closed = True
        _load().pst_ring_close(self._h)
        if self._owner:
            _load().pst_ring_unlink(self.name.encode())

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
