"""ctypes bindings for the native JPEG/PNG codec (src/image_codec.cc).

The batch decode releases the GIL for the whole call (ctypes CDLL semantics)
and fans out across a C++ thread pool — this is the hot path that replaces
the reference's per-row ``cv2.imdecode`` loop
(reference ``py_dict_reader_worker.py:181`` -> ``utils.py:54-87``).
"""

import ctypes
import logging
import os

import numpy as np

from petastorm_tpu.native.build import NativeBuildError, build_and_load

logger = logging.getLogger(__name__)

_ERRORS = {
    -1: 'not a JPEG or PNG stream',
    -2: 'decode failed (corrupt stream?)',
    -3: 'output buffer too small',
    -4: 'bad arguments',
    -5: 'encode failed',
}

_lib = None
_load_failed = False


def _load():
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    try:
        lib = build_and_load('pst_image', ['image_codec.cc'],
                             link_flags=['-ljpeg', '-lpng'])
    except NativeBuildError as exc:
        logger.warning('native image codec unavailable, using cv2/PIL: %s', exc)
        _load_failed = True
        return None
    lib.pst_image_info.restype = ctypes.c_int
    lib.pst_image_info.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
    lib.pst_image_decode.restype = ctypes.c_int
    lib.pst_image_decode.argtypes = [
        ctypes.c_char_p, ctypes.c_size_t, ctypes.c_void_p, ctypes.c_size_t,
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
    lib.pst_image_decode_batch.restype = ctypes.c_int
    lib.pst_image_info_batch.restype = ctypes.c_int
    lib.pst_jpeg_encode.restype = ctypes.c_int
    lib.pst_jpeg_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_size_t)]
    lib.pst_png_encode.restype = ctypes.c_int
    lib.pst_png_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.POINTER(ctypes.c_void_p),
        ctypes.POINTER(ctypes.c_size_t)]
    lib.pst_buffer_free.restype = None
    lib.pst_buffer_free.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


def available():
    return _load() is not None


def _check(rc, context):
    if rc != 0:
        raise ValueError('{}: {}'.format(context, _ERRORS.get(rc, 'error {}'.format(rc))))


def image_info(data):
    """(height, width, channels, bit_depth) from a JPEG/PNG byte stream."""
    lib = _load()
    w = ctypes.c_int()
    h = ctypes.c_int()
    ch = ctypes.c_int()
    bd = ctypes.c_int()
    rc = lib.pst_image_info(data, len(data), ctypes.byref(w), ctypes.byref(h),
                            ctypes.byref(ch), ctypes.byref(bd))
    _check(rc, 'image_info')
    return h.value, w.value, ch.value, bd.value


def _alloc_output(data):
    h, w, ch, bd = image_info(data)
    dtype = np.uint16 if bd == 16 else np.uint8
    out = np.empty((h, w, ch), dtype=dtype)
    return out


def _squeeze(arr):
    return arr[:, :, 0] if arr.shape[2] == 1 else arr


def decode_image(data):
    """Decode one JPEG/PNG byte stream to an RGB/gray ndarray (uint8/uint16)."""
    lib = _load()
    out = _alloc_output(data)
    w = ctypes.c_int()
    h = ctypes.c_int()
    ch = ctypes.c_int()
    bd = ctypes.c_int()
    rc = lib.pst_image_decode(data, len(data),
                              out.ctypes.data_as(ctypes.c_void_p), out.nbytes,
                              ctypes.byref(w), ctypes.byref(h),
                              ctypes.byref(ch), ctypes.byref(bd))
    _check(rc, 'decode_image')
    return _squeeze(out)


def image_info_batch(blobs, num_threads=None):
    """Header-probe N byte streams with ONE native call (C++ threads, GIL
    released): returns ``(heights, widths, channels, bit_depths)`` lists.
    Raises on the first unprobeable stream."""
    lib = _load()
    n = len(blobs)
    if n == 0:
        return [], [], [], []
    if num_threads is None:
        num_threads = min(n, os.cpu_count() or 4)
    datas = (ctypes.c_char_p * n)(*blobs)
    lens = (ctypes.c_size_t * n)(*[len(b) for b in blobs])
    ws = (ctypes.c_int * n)()
    hs = (ctypes.c_int * n)()
    chs = (ctypes.c_int * n)()
    bds = (ctypes.c_int * n)()
    results = (ctypes.c_int * n)()
    rc = lib.pst_image_info_batch(n, datas, lens, ws, hs, chs, bds, results,
                                  num_threads)
    if rc != 0:
        bad = [i for i in range(n) if results[i] != 0]
        raise ValueError('image_info_batch failed for images {}: {}'.format(
            bad[:5], _ERRORS.get(results[bad[0]] if bad else rc, 'error')))
    return list(hs), list(ws), list(chs), list(bds)


def decode_batch(blobs, num_threads=None):
    """Decode a list of JPEG/PNG byte streams in parallel C++ threads.

    GIL is released for the whole batch; allocation happens up front from
    ONE batched header probe so worker threads never touch Python state.
    """
    lib = _load()
    n = len(blobs)
    if n == 0:
        return []
    if num_threads is None:
        num_threads = min(n, os.cpu_count() or 4)
    heights, widths, channels, depths = image_info_batch(
        blobs, num_threads=num_threads)
    outs = [np.empty((h, w, ch), dtype=np.uint16 if bd == 16 else np.uint8)
            for h, w, ch, bd in zip(heights, widths, channels, depths)]

    datas = (ctypes.c_char_p * n)(*blobs)
    lens = (ctypes.c_size_t * n)(*[len(b) for b in blobs])
    out_ptrs = (ctypes.c_void_p * n)(*[o.ctypes.data for o in outs])
    caps = (ctypes.c_size_t * n)(*[o.nbytes for o in outs])
    ws = (ctypes.c_int * n)()
    hs = (ctypes.c_int * n)()
    chs = (ctypes.c_int * n)()
    bds = (ctypes.c_int * n)()
    results = (ctypes.c_int * n)()
    rc = lib.pst_image_decode_batch(n, datas, lens, out_ptrs, caps, ws, hs,
                                    chs, bds, results, num_threads)
    if rc != 0:
        bad = [i for i in range(n) if results[i] != 0]
        if bad:
            raise ValueError('batch decode failed for images {}: {}'.format(
                bad[:5], _ERRORS.get(results[bad[0]], 'error')))
        raise ValueError('batch decode failed: {}'.format(_ERRORS.get(rc, 'error {}'.format(rc))))
    return [_squeeze(o) for o in outs]


def decode_batch_into(ptrs, lens, out, num_threads=None):
    """Decode N JPEG/PNG streams directly into one contiguous output block.

    ``ptrs``/``lens`` are integer arrays of blob addresses/sizes (typically
    pointer math over an Arrow BinaryArray's value buffer — no per-cell
    ``bytes`` objects are materialized), and ``out`` is a C-contiguous
    ``[N, H, W, C]`` array; image ``i`` decodes into ``out[i]``. The GIL is
    released for the whole batch. Returns per-image ``(results, channels,
    heights, widths)`` lists: a nonzero result marks a slot the caller must
    redo itself (e.g. an RGBA stream in an RGB-capacity slot fails with
    'buffer too small' *before* its channel count is knowable — the caller
    falls back to a per-cell decode for exactly those slots).
    """
    lib = _load()
    n = len(ptrs)
    if n == 0:
        return [], [], [], []
    if not out.flags['C_CONTIGUOUS'] or out.shape[0] != n:
        raise ValueError('out must be C-contiguous with leading dim {}'.format(n))
    if num_threads is None:
        num_threads = min(n, os.cpu_count() or 4)
    stride = out.nbytes // n
    base = out.ctypes.data
    datas = (ctypes.c_void_p * n)(*[int(p) for p in ptrs])
    lens_arr = (ctypes.c_size_t * n)(*[int(l) for l in lens])
    out_ptrs = (ctypes.c_void_p * n)(*[base + i * stride for i in range(n)])
    caps = (ctypes.c_size_t * n)(*([stride] * n))
    ws = (ctypes.c_int * n)()
    hs = (ctypes.c_int * n)()
    chs = (ctypes.c_int * n)()
    bds = (ctypes.c_int * n)()
    results = (ctypes.c_int * n)()
    lib.pst_image_decode_batch(n, datas, lens_arr, out_ptrs, caps, ws, hs,
                               chs, bds, results, num_threads)
    return list(results), list(chs), list(hs), list(ws)


def decode_error_message(code):
    """Human-readable message for a nonzero ``decode_batch_into`` result."""
    return _ERRORS.get(code, 'error {}'.format(code))


def encode_jpeg(array, quality=80):
    """Encode a uint8 gray/RGB ndarray to JPEG bytes."""
    array = np.ascontiguousarray(array)
    if array.dtype != np.uint8:
        raise ValueError('jpeg encode requires uint8, got {}'.format(array.dtype))
    if array.ndim == 2:
        h, w, ch = array.shape[0], array.shape[1], 1
    elif array.ndim == 3 and array.shape[2] in (1, 3):
        h, w, ch = array.shape
    else:
        raise ValueError('jpeg encode requires HxW or HxWx{1,3}, got shape {}'.format(array.shape))
    lib = _load()
    out = ctypes.c_void_p()
    out_len = ctypes.c_size_t()
    rc = lib.pst_jpeg_encode(array.ctypes.data_as(ctypes.c_void_p), w, h, ch,
                             int(quality), ctypes.byref(out), ctypes.byref(out_len))
    _check(rc, 'encode_jpeg')
    try:
        return ctypes.string_at(out, out_len.value)
    finally:
        lib.pst_buffer_free(out)


def encode_png(array, compression=-1):
    """Encode an 8/16-bit gray/gray-alpha/RGB/RGBA ndarray to PNG bytes."""
    array = np.ascontiguousarray(array)
    if array.dtype == np.uint8:
        bit_depth = 8
    elif array.dtype == np.uint16:
        bit_depth = 16
    else:
        raise ValueError('png encode requires uint8/uint16, got {}'.format(array.dtype))
    if array.ndim == 2:
        h, w, ch = array.shape[0], array.shape[1], 1
    elif array.ndim == 3 and array.shape[2] in (1, 2, 3, 4):
        h, w, ch = array.shape
    else:
        raise ValueError('png encode requires HxW or HxWx{1..4}, got shape {}'.format(array.shape))
    lib = _load()
    out = ctypes.c_void_p()
    out_len = ctypes.c_size_t()
    rc = lib.pst_png_encode(array.ctypes.data_as(ctypes.c_void_p), w, h, ch,
                            bit_depth, int(compression), ctypes.byref(out),
                            ctypes.byref(out_len))
    _check(rc, 'encode_png')
    try:
        return ctypes.string_at(out, out_len.value)
    finally:
        lib.pst_buffer_free(out)
