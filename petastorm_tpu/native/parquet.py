"""ctypes binding for the C++ Parquet row-group reader (``parquet_stage.cc``).

SURVEY §2.9's mandatory native component: row-group IO + decode runs wholly
in C++ with the GIL released (a plain ctypes call drops it), and the decoded
columnar buffers enter pyarrow through the Arrow C Data Interface with zero
copies. Fixed-width columns then flow to numpy/JAX staging zero-copy.

Availability is environment-dependent (needs g++ and the pyarrow wheel's
bundled headers/libraries); callers use :func:`is_available` and fall back to
``pyarrow.parquet`` — behavior is identical, this path just removes Python
from the per-row-group hot loop.
"""

import ctypes
import glob
import logging
import os

from petastorm_tpu.native.build import NativeBuildError, build_and_load

logger = logging.getLogger(__name__)

_ERR_CAP = 4096
_lib = None
_load_error = None


def _arrow_link_flags():
    """Locate the pyarrow wheel's bundled libarrow/libparquet to link against.

    The wheel ships only versioned sonames (``libarrow.so.2500``), so link
    with ``-l:`` exact-name syntax plus an rpath back to the wheel directory.
    """
    import pyarrow

    lib_dir = pyarrow.get_library_dirs()[0]
    flags = ['-L' + lib_dir, '-Wl,-rpath,' + lib_dir]
    for stem in ('libarrow.so', 'libparquet.so'):
        versioned = sorted(glob.glob(os.path.join(lib_dir, stem + '*')))
        if not versioned:
            raise NativeBuildError('{} not found under {}'.format(stem, lib_dir))
        flags.append('-l:' + os.path.basename(versioned[0]))
    return flags


def _load():
    global _lib, _load_error
    if _lib is not None or _load_error is not None:
        return _lib
    try:
        import pyarrow

        lib = build_and_load(
            'pst_parquet', ['parquet_stage.cc'],
            # c++20 (overrides the default c++17): arrow 25 headers use
            # std::span / std::popcount.
            compile_flags=['-std=c++20', '-I' + pyarrow.get_include()],
            link_flags=_arrow_link_flags())
        lib.pst_parquet_file_info.restype = ctypes.c_int32
        lib.pst_parquet_file_info.argtypes = [
            ctypes.c_char_p, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
            ctypes.c_char_p, ctypes.c_int32]
        lib.pst_read_row_group.restype = ctypes.c_int32
        lib.pst_read_row_group.argtypes = [
            ctypes.c_char_p, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_char_p, ctypes.c_int32]
        lib.pst_open.restype = ctypes.c_void_p
        lib.pst_open.argtypes = [ctypes.c_char_p, ctypes.c_int32, ctypes.c_int32,
                                 ctypes.c_char_p, ctypes.c_int32]
        lib.pst_close.restype = None
        lib.pst_close.argtypes = [ctypes.c_void_p]
        lib.pst_handle_num_row_groups.restype = ctypes.c_int32
        lib.pst_handle_num_row_groups.argtypes = [ctypes.c_void_p]
        lib.pst_handle_read_row_group.restype = ctypes.c_int32
        lib.pst_handle_read_row_group.argtypes = [
            ctypes.c_void_p, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_char_p, ctypes.c_int32]
        _lib = lib
    except (NativeBuildError, OSError) as e:
        _load_error = e
        logger.info('native parquet reader unavailable: %s', e)
    return _lib


def is_available():
    return _load() is not None


class NativeParquetError(RuntimeError):
    pass


def file_info(path, use_mmap=False):
    """``(num_row_groups, num_rows, [rows_per_row_group])`` from the footer."""
    lib = _load()
    if lib is None:
        raise NativeParquetError('native parquet reader unavailable: {}'.format(_load_error))
    err = ctypes.create_string_buffer(_ERR_CAP)
    n_rg = ctypes.c_int64()
    n_rows = ctypes.c_int64()
    cap = 1 << 20
    rg_rows = (ctypes.c_int64 * cap)()
    rc = lib.pst_parquet_file_info(path.encode(), 1 if use_mmap else 0,
                                   ctypes.byref(n_rg), ctypes.byref(n_rows),
                                   rg_rows, cap, err, _ERR_CAP)
    if rc != 0:
        raise NativeParquetError(err.value.decode(errors='replace'))
    return n_rg.value, n_rows.value, list(rg_rows[:n_rg.value])


def read_row_group(path, row_group, columns=None, use_mmap=False, use_threads=True):
    """Read one row group into a ``pyarrow.RecordBatch`` — decode in C++,
    imported zero-copy via the Arrow C Data Interface.

    :param columns: optional list of parquet **leaf** column indices (ints).
        For flat schemas (every petastorm_tpu store) these equal field
        positions. ``None`` reads all columns.
    """
    import pyarrow as pa
    from pyarrow.cffi import ffi

    lib = _load()
    if lib is None:
        raise NativeParquetError('native parquet reader unavailable: {}'.format(_load_error))

    if columns is None:
        col_ptr, n_cols = None, -1
    else:
        arr = (ctypes.c_int32 * len(columns))(*columns)
        col_ptr, n_cols = arr, len(columns)

    c_schema = ffi.new('struct ArrowSchema*')
    c_array = ffi.new('struct ArrowArray*')
    err = ctypes.create_string_buffer(_ERR_CAP)
    rc = lib.pst_read_row_group(
        path.encode(), row_group, col_ptr, n_cols,
        1 if use_mmap else 0, 1 if use_threads else 0,
        int(ffi.cast('uintptr_t', c_schema)), int(ffi.cast('uintptr_t', c_array)),
        err, _ERR_CAP)
    if rc != 0:
        raise NativeParquetError(err.value.decode(errors='replace'))
    return pa.RecordBatch._import_from_c(int(ffi.cast('uintptr_t', c_array)),
                                         int(ffi.cast('uintptr_t', c_schema)))


class NativeParquetFile(object):
    """Handle-cached native reader: the file is opened and the footer parsed
    once, then row groups decode through the same C++ path as
    :func:`read_row_group` (which re-opens per call — fine for one-shots,
    ~25% slower on 100-row groups when called in a loop)."""

    def __init__(self, path, use_mmap=False, use_threads=True):
        lib = _load()
        if lib is None:
            raise NativeParquetError(
                'native parquet reader unavailable: {}'.format(_load_error))
        self._lib = lib
        err = ctypes.create_string_buffer(_ERR_CAP)
        self._handle = lib.pst_open(path.encode(), 1 if use_mmap else 0,
                                    1 if use_threads else 0, err, _ERR_CAP)
        if not self._handle:
            raise NativeParquetError(err.value.decode(errors='replace'))

    @property
    def num_row_groups(self):
        return self._lib.pst_handle_num_row_groups(self._handle)

    def read_row_group(self, row_group, columns=None):
        """One row group as a ``pyarrow.RecordBatch`` (zero-copy import);
        ``columns`` are parquet leaf indices like :func:`read_row_group`."""
        import pyarrow as pa
        from pyarrow.cffi import ffi

        if self._handle is None:
            raise NativeParquetError('reader is closed')
        if columns is None:
            col_ptr, n_cols = None, -1
        else:
            arr = (ctypes.c_int32 * len(columns))(*columns)
            col_ptr, n_cols = arr, len(columns)
        c_schema = ffi.new('struct ArrowSchema*')
        c_array = ffi.new('struct ArrowArray*')
        err = ctypes.create_string_buffer(_ERR_CAP)
        rc = self._lib.pst_handle_read_row_group(
            self._handle, row_group, col_ptr, n_cols,
            int(ffi.cast('uintptr_t', c_schema)), int(ffi.cast('uintptr_t', c_array)),
            err, _ERR_CAP)
        if rc != 0:
            raise NativeParquetError(err.value.decode(errors='replace'))
        return pa.RecordBatch._import_from_c(int(ffi.cast('uintptr_t', c_array)),
                                             int(ffi.cast('uintptr_t', c_schema)))

    def close(self):
        if self._handle:
            self._lib.pst_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter shutdown
            pass


def leaf_indices_for_fields(parquet_schema, field_names):
    """Map top-level field names to parquet leaf-column indices, or ``None``
    when any field maps to multiple leaves (nested types) — callers fall back
    to pyarrow in that case."""
    leaf_paths = [parquet_schema.column(i).path for i in range(len(parquet_schema))]
    indices = []
    for name in field_names:
        matches = [i for i, p in enumerate(leaf_paths)
                   if p == name or p.startswith(name + '.')]
        if len(matches) != 1:
            return None
        indices.append(matches[0])
    return indices
