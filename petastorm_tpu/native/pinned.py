"""DMA-friendly host slabs (src/pinned.cc) + the memcpy ceiling probe.

The arena pool allocates its per-batch host buffers out of these slabs
when pinned mode is on: page-aligned, pre-faulted, and best-effort
``mlock``\\ ed so the accelerator runtime's DMA engine never stalls on a
page fault or an evicted page mid-transfer.

Three tiers, degrading gracefully:

``native``
    The compiled probe: ``mmap(MAP_POPULATE)`` + ``mlock``.
``mmap``
    Toolchain missing — anonymous :mod:`mmap` mappings (page-aligned by
    construction) with ``mlock`` attempted through libc.
``None`` (:func:`allocate` returns ``None``)
    Neither tier works (or ``PETASTORM_TPU_NO_NATIVE`` plus no mmap);
    callers fall back to plain ``np.empty`` — the arena pool stays
    fully functional, just unpinned.
"""

import ctypes
import logging
import mmap as mmap_mod
import os
import weakref

import numpy as np

from petastorm_tpu.native.build import NativeBuildError, build_and_load

logger = logging.getLogger(__name__)

_lib = None
_load_failed = False


def _load():
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    if os.environ.get('PETASTORM_TPU_NO_NATIVE'):
        _load_failed = True
        return None
    try:
        lib = build_and_load('pst_pinned', ['pinned.cc'])
    except NativeBuildError as exc:
        logger.warning('native pinned allocator unavailable, '
                       'falling back to mmap: %s', exc)
        _load_failed = True
        return None
    lib.pst_pinned_alloc.restype = ctypes.c_int
    lib.pst_pinned_alloc.argtypes = [ctypes.c_size_t, ctypes.c_int,
                                     ctypes.POINTER(ctypes.c_void_p)]
    lib.pst_pinned_free.restype = None
    lib.pst_pinned_free.argtypes = [ctypes.c_void_p, ctypes.c_size_t,
                                    ctypes.c_int]
    lib.pst_memcpy_GBps.restype = ctypes.c_double
    lib.pst_memcpy_GBps.argtypes = [ctypes.c_size_t, ctypes.c_int]
    _lib = lib
    return _lib


def available():
    """True when the compiled allocator is usable (mmap fallback not
    counted — callers that care about the tier read ``PinnedSlab.mode``)."""
    return _load() is not None


class PinnedSlab(object):
    """One page-aligned host allocation; freed on :meth:`free` or GC.

    ``array`` is a ``np.uint8`` view of the whole slab; ``locked`` says
    whether ``mlock`` actually succeeded (page-aligned-only slabs are
    still useful — alignment and pre-faulting are most of the win).
    """

    def __init__(self, array, nbytes, locked, mode, release):
        self.array = array
        self.nbytes = nbytes
        self.locked = locked
        self.mode = mode
        self._finalizer = weakref.finalize(self, release)

    def free(self):
        self._finalizer()


def _allocate_native(nbytes, lock):
    lib = _load()
    if lib is None:
        return None
    ptr = ctypes.c_void_p()
    rc = lib.pst_pinned_alloc(nbytes, 1 if lock else 0, ctypes.byref(ptr))
    if rc < 0 or not ptr.value:
        return None
    buf = (ctypes.c_ubyte * nbytes).from_address(ptr.value)
    arr = np.frombuffer(buf, dtype=np.uint8)
    addr, locked = ptr.value, bool(rc)

    def release(lib=lib, addr=addr, nbytes=nbytes, locked=locked):
        lib.pst_pinned_free(addr, nbytes, 1 if locked else 0)

    return PinnedSlab(arr, nbytes, locked, 'native', release)


def _mlock_via_libc(addr, nbytes):
    try:
        libc = ctypes.CDLL(None, use_errno=True)
        return libc.mlock(ctypes.c_void_p(addr), ctypes.c_size_t(nbytes)) == 0
    except Exception:  # noqa: BLE001 - no libc / no mlock: stay unlocked
        return False


def _allocate_mmap(nbytes, lock):
    try:
        m = mmap_mod.mmap(-1, nbytes)
    except (OSError, ValueError, OverflowError):
        return None
    arr = np.frombuffer(m, dtype=np.uint8)
    locked = bool(lock) and _mlock_via_libc(arr.ctypes.data, nbytes)

    def release(m=m):
        try:
            m.close()
        except BufferError:  # a view still exported: the GC will get it
            pass

    return PinnedSlab(arr, nbytes, locked, 'mmap', release)


def allocate(nbytes, lock=True):
    """A :class:`PinnedSlab` of ``nbytes`` (page-aligned, best-effort
    mlocked) or ``None`` when no tier can serve it."""
    nbytes = int(nbytes)
    if nbytes <= 0:
        return None
    slab = _allocate_native(nbytes, lock)
    if slab is None:
        slab = _allocate_mmap(nbytes, lock)
    return slab


def memcpy_ceiling_GBps(nbytes=64 << 20, reps=5):
    """Measured sustained host-memcpy bandwidth in GB/s — the ceiling any
    memcpy-based h2d path is chasing. Uses the GIL-free native probe when
    available, a ``np.copyto`` timing loop otherwise; ``None`` when the
    measurement failed outright."""
    nbytes, reps = int(nbytes), int(reps)
    if nbytes <= 0 or reps <= 0:
        return None
    lib = _load()
    if lib is not None:
        gbps = float(lib.pst_memcpy_GBps(nbytes, reps))
        return gbps if gbps > 0 else None
    import time
    try:
        a = np.ones(nbytes, np.uint8)
        b = np.zeros(nbytes, np.uint8)
    except MemoryError:
        return None
    np.copyto(b, a)  # warmup
    t0 = time.perf_counter()
    for _ in range(reps):
        np.copyto(b, a)
    dt = time.perf_counter() - t0
    if dt <= 0:
        return None
    return nbytes * reps / dt / 1e9
