// JPEG/PNG codec for petastorm_tpu, on system libjpeg + libpng.
//
// Replaces the reference's OpenCV dependency for CompressedImageCodec
// (reference petastorm/codecs.py:53-118).  Works directly in RGB channel
// order (no BGR detour), supports 8-bit JPEG (1/3 channels) and 8/16-bit
// PNG (1/2/3/4 channels), and offers a multithreaded batch decode whose
// whole run happens with the Python GIL released (ctypes releases it for
// the duration of the call).
//
// C ABI, all functions return 0 on success / negative error code.

#include <atomic>
#include <csetjmp>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

#include <jpeglib.h>
#include <png.h>

extern "C" {

enum PstError {
  PST_OK = 0,
  PST_ERR_FORMAT = -1,      // not a JPEG or PNG
  PST_ERR_DECODE = -2,      // codec-level failure
  PST_ERR_CAPACITY = -3,    // output buffer too small
  PST_ERR_ARGS = -4,        // bad arguments
  PST_ERR_ENCODE = -5,
};

// ---------------------------------------------------------------- helpers

static bool is_jpeg(const uint8_t* data, size_t len) {
  return len >= 3 && data[0] == 0xFF && data[1] == 0xD8 && data[2] == 0xFF;
}

static bool is_png(const uint8_t* data, size_t len) {
  static const uint8_t sig[8] = {0x89, 'P', 'N', 'G', 0x0D, 0x0A, 0x1A, 0x0A};
  return len >= 8 && memcmp(data, sig, 8) == 0;
}

static bool host_is_little_endian() {
  const uint16_t one = 1;
  return *reinterpret_cast<const uint8_t*>(&one) == 1;
}

// ------------------------------------------------------------------ JPEG

struct PstJpegErr {
  struct jpeg_error_mgr pub;
  jmp_buf env;
};

static void pst_jpeg_error_exit(j_common_ptr cinfo) {
  PstJpegErr* err = reinterpret_cast<PstJpegErr*>(cinfo->err);
  longjmp(err->env, 1);
}

static void pst_jpeg_silent(j_common_ptr, int) {}
static void pst_jpeg_silent_msg(j_common_ptr) {}

static int jpeg_info(const uint8_t* data, size_t len, int* w, int* h, int* ch) {
  jpeg_decompress_struct cinfo;
  PstJpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = pst_jpeg_error_exit;
  jerr.pub.emit_message = pst_jpeg_silent;
  jerr.pub.output_message = pst_jpeg_silent_msg;
  if (setjmp(jerr.env)) {
    jpeg_destroy_decompress(&cinfo);
    return PST_ERR_DECODE;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(data), len);
  jpeg_read_header(&cinfo, TRUE);
  *w = cinfo.image_width;
  *h = cinfo.image_height;
  *ch = cinfo.num_components >= 3 ? 3 : 1;
  jpeg_destroy_decompress(&cinfo);
  return PST_OK;
}

static int jpeg_decode(const uint8_t* data, size_t len, uint8_t* out,
                       size_t capacity, int* w, int* h, int* ch) {
  jpeg_decompress_struct cinfo;
  PstJpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = pst_jpeg_error_exit;
  jerr.pub.emit_message = pst_jpeg_silent;
  jerr.pub.output_message = pst_jpeg_silent_msg;
  if (setjmp(jerr.env)) {
    jpeg_destroy_decompress(&cinfo);
    return PST_ERR_DECODE;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<unsigned char*>(data), len);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = cinfo.num_components >= 3 ? JCS_RGB : JCS_GRAYSCALE;
  jpeg_start_decompress(&cinfo);
  const int width = cinfo.output_width;
  const int height = cinfo.output_height;
  const int comps = cinfo.output_components;
  const size_t stride = static_cast<size_t>(width) * comps;
  if (capacity < stride * height) {
    jpeg_destroy_decompress(&cinfo);
    return PST_ERR_CAPACITY;
  }
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = out + stride * cinfo.output_scanline;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  *w = width;
  *h = height;
  *ch = comps;
  return PST_OK;
}

// ------------------------------------------------------------------- PNG

struct PngReadState {
  const uint8_t* data;
  size_t len;
  size_t pos;
};

static void png_mem_read(png_structp png, png_bytep out, png_size_t n) {
  PngReadState* st = static_cast<PngReadState*>(png_get_io_ptr(png));
  if (st->pos + n > st->len) {
    png_error(png, "read past end");
  }
  memcpy(out, st->data + st->pos, n);
  st->pos += n;
}

static int png_channels_for_color_type(int color_type) {
  switch (color_type) {
    case PNG_COLOR_TYPE_GRAY: return 1;
    case PNG_COLOR_TYPE_GRAY_ALPHA: return 2;
    case PNG_COLOR_TYPE_PALETTE: return 3;  // expanded to RGB on decode
    case PNG_COLOR_TYPE_RGB: return 3;
    case PNG_COLOR_TYPE_RGB_ALPHA: return 4;
    default: return -1;
  }
}

static int png_info_from_header(const uint8_t* data, size_t len, int* w,
                                int* h, int* ch, int* bit_depth) {
  // IHDR is mandatory first chunk: width@16, height@20, depth@24, color@25.
  if (len < 26) return PST_ERR_DECODE;
  *w = (data[16] << 24) | (data[17] << 16) | (data[18] << 8) | data[19];
  *h = (data[20] << 24) | (data[21] << 16) | (data[22] << 8) | data[23];
  int depth = data[24];
  int color_type = data[25];
  int channels = png_channels_for_color_type(color_type);
  if (channels < 0) return PST_ERR_DECODE;
  // Walk chunk headers up to IDAT looking for tRNS: decode expands it to a
  // full alpha channel (png_set_tRNS_to_alpha), so the probe must account
  // for the extra channel when sizing output buffers.
  bool has_trns = false;
  size_t off = 8;
  while (off + 8 <= len) {
    uint32_t chunk_len = (static_cast<uint32_t>(data[off]) << 24) |
                         (data[off + 1] << 16) | (data[off + 2] << 8) |
                         data[off + 3];
    const uint8_t* type = data + off + 4;
    if (memcmp(type, "IDAT", 4) == 0 || memcmp(type, "IEND", 4) == 0) break;
    if (memcmp(type, "tRNS", 4) == 0) {
      has_trns = true;
      break;
    }
    off += 12ULL + chunk_len;  // len + type + data + crc
  }
  if (has_trns) {
    channels = color_type == PNG_COLOR_TYPE_GRAY ? 2 : 4;
  }
  *ch = channels;
  // sub-8-bit gray/palette is expanded to 8-bit on decode
  *bit_depth = depth == 16 ? 16 : 8;
  return PST_OK;
}

static int png_decode(const uint8_t* data, size_t len, uint8_t* out,
                      size_t capacity, int* w, int* h, int* ch,
                      int* bit_depth) {
  png_structp png = png_create_read_struct(PNG_LIBPNG_VER_STRING, nullptr,
                                           nullptr, nullptr);
  if (!png) return PST_ERR_DECODE;
  png_infop info = png_create_info_struct(png);
  if (!info) {
    png_destroy_read_struct(&png, nullptr, nullptr);
    return PST_ERR_DECODE;
  }
  std::vector<png_bytep> rows;
  if (setjmp(png_jmpbuf(png))) {
    png_destroy_read_struct(&png, &info, nullptr);
    return PST_ERR_DECODE;
  }
  PngReadState st{data, len, 0};
  png_set_read_fn(png, &st, png_mem_read);
  png_read_info(png, info);
  // From here on the only critical chunks left are IDAT, whose payload
  // zlib's adler32 already guards — skip the redundant crc32 over the
  // compressed stream (~15-20% of decode for large poorly-compressing
  // images). Set AFTER png_read_info so IHDR/PLTE/tRNS (no inner
  // checksum) keep full CRC verification; corrupt or truncated pixel
  // data still fails loudly via zlib ("incorrect data check") or the
  // read callback.
  png_set_crc_action(png, PNG_CRC_QUIET_USE, PNG_CRC_QUIET_USE);

  png_uint_32 width = png_get_image_width(png, info);
  png_uint_32 height = png_get_image_height(png, info);
  int depth = png_get_bit_depth(png, info);
  int color_type = png_get_color_type(png, info);

  if (color_type == PNG_COLOR_TYPE_PALETTE) png_set_palette_to_rgb(png);
  if (color_type == PNG_COLOR_TYPE_GRAY && depth < 8)
    png_set_expand_gray_1_2_4_to_8(png);
  if (png_get_valid(png, info, PNG_INFO_tRNS)) png_set_tRNS_to_alpha(png);
  if (depth == 16 && host_is_little_endian()) png_set_swap(png);
  png_read_update_info(png, info);

  const int channels = png_get_channels(png, info);
  depth = png_get_bit_depth(png, info);
  const size_t stride = png_get_rowbytes(png, info);
  if (capacity < stride * height) {
    png_destroy_read_struct(&png, &info, nullptr);
    return PST_ERR_CAPACITY;
  }
  rows.resize(height);
  for (png_uint_32 i = 0; i < height; i++) rows[i] = out + i * stride;
  png_read_image(png, rows.data());
  png_read_end(png, nullptr);
  png_destroy_read_struct(&png, &info, nullptr);
  *w = static_cast<int>(width);
  *h = static_cast<int>(height);
  *ch = channels;
  *bit_depth = depth;
  return PST_OK;
}

struct PngWriteState {
  std::vector<uint8_t> buf;
};

static void png_mem_write(png_structp png, png_bytep data, png_size_t n) {
  PngWriteState* st = static_cast<PngWriteState*>(png_get_io_ptr(png));
  st->buf.insert(st->buf.end(), data, data + n);
}

static void png_mem_flush(png_structp) {}

// ------------------------------------------------------------- public API

// Header-only probe; bit_depth is 8 for JPEG.
int pst_image_info(const uint8_t* data, size_t len, int* w, int* h, int* ch,
                   int* bit_depth) {
  if (!data || !w || !h || !ch || !bit_depth) return PST_ERR_ARGS;
  if (is_jpeg(data, len)) {
    *bit_depth = 8;
    return jpeg_info(data, len, w, h, ch);
  }
  if (is_png(data, len)) {
    return png_info_from_header(data, len, w, h, ch, bit_depth);
  }
  return PST_ERR_FORMAT;
}

// Decode into caller-allocated `out` (row-major interleaved, native endian
// for 16-bit). Caller sizes `out` from pst_image_info.
int pst_image_decode(const uint8_t* data, size_t len, uint8_t* out,
                     size_t capacity, int* w, int* h, int* ch,
                     int* bit_depth) {
  if (!data || !out) return PST_ERR_ARGS;
  if (is_jpeg(data, len)) {
    *bit_depth = 8;
    return jpeg_decode(data, len, out, capacity, w, h, ch);
  }
  if (is_png(data, len)) {
    return png_decode(data, len, out, capacity, w, h, ch, bit_depth);
  }
  return PST_ERR_FORMAT;
}

// Batch header probe with an internal thread pool: one native call sizes
// every output of a heterogeneous batch (the variable-shape decode_batch
// path) instead of n round trips through ctypes. All arrays have length n;
// results[i] gets the per-image error code. Returns the first nonzero
// result (callers inspect results[] for the rest).
int pst_image_info_batch(int n, const uint8_t** datas, const size_t* lens,
                         int* ws, int* hs, int* chs, int* bit_depths,
                         int* results, int num_threads) {
  if (n < 0 || !datas || !results) return PST_ERR_ARGS;
  if (num_threads <= 0) num_threads = 1;
  if (num_threads > n) num_threads = n > 0 ? n : 1;
  std::atomic<int> next{0};
  auto worker = [&]() {
    for (;;) {
      int i = next.fetch_add(1);
      if (i >= n) break;
      results[i] = pst_image_info(datas[i], lens[i], &ws[i], &hs[i], &chs[i],
                                  &bit_depths[i]);
    }
  };
  if (num_threads == 1) {
    worker();
  } else {
    std::vector<std::thread> threads;
    threads.reserve(num_threads);
    for (int t = 0; t < num_threads; t++) threads.emplace_back(worker);
    for (auto& th : threads) th.join();
  }
  for (int i = 0; i < n; i++) {
    if (results[i] != PST_OK) return results[i];
  }
  return PST_OK;
}

// Batch decode with an internal thread pool. All arrays have length n;
// results[i] gets the per-image error code.
int pst_image_decode_batch(int n, const uint8_t** datas, const size_t* lens,
                           uint8_t** outs, const size_t* capacities, int* ws,
                           int* hs, int* chs, int* bit_depths, int* results,
                           int num_threads) {
  if (n < 0 || !datas || !outs) return PST_ERR_ARGS;
  if (num_threads <= 0) num_threads = 1;
  if (num_threads > n) num_threads = n > 0 ? n : 1;
  std::atomic<int> next{0};
  auto worker = [&]() {
    for (;;) {
      int i = next.fetch_add(1);
      if (i >= n) break;
      results[i] = pst_image_decode(datas[i], lens[i], outs[i], capacities[i],
                                    &ws[i], &hs[i], &chs[i], &bit_depths[i]);
    }
  };
  if (num_threads == 1) {
    worker();
  } else {
    std::vector<std::thread> threads;
    threads.reserve(num_threads);
    for (int t = 0; t < num_threads; t++) threads.emplace_back(worker);
    for (auto& th : threads) th.join();
  }
  for (int i = 0; i < n; i++) {
    if (results[i] != PST_OK) return results[i];
  }
  return PST_OK;
}

// Encode RGB/gray uint8 to JPEG. Library-allocated output; free with
// pst_buffer_free.
int pst_jpeg_encode(const uint8_t* pixels, int w, int h, int ch, int quality,
                    uint8_t** out, size_t* out_len) {
  if (!pixels || !out || !out_len || (ch != 1 && ch != 3)) return PST_ERR_ARGS;
  jpeg_compress_struct cinfo;
  PstJpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = pst_jpeg_error_exit;
  jerr.pub.emit_message = pst_jpeg_silent;
  jerr.pub.output_message = pst_jpeg_silent_msg;
  unsigned char* buf = nullptr;
  unsigned long buf_len = 0;
  if (setjmp(jerr.env)) {
    jpeg_destroy_compress(&cinfo);
    if (buf) free(buf);
    return PST_ERR_ENCODE;
  }
  jpeg_create_compress(&cinfo);
  jpeg_mem_dest(&cinfo, &buf, &buf_len);
  cinfo.image_width = w;
  cinfo.image_height = h;
  cinfo.input_components = ch;
  cinfo.in_color_space = ch == 3 ? JCS_RGB : JCS_GRAYSCALE;
  jpeg_set_defaults(&cinfo);
  jpeg_set_quality(&cinfo, quality, TRUE);
  jpeg_start_compress(&cinfo, TRUE);
  const size_t stride = static_cast<size_t>(w) * ch;
  while (cinfo.next_scanline < cinfo.image_height) {
    JSAMPROW row =
        const_cast<uint8_t*>(pixels) + stride * cinfo.next_scanline;
    jpeg_write_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_compress(&cinfo);
  jpeg_destroy_compress(&cinfo);
  *out = buf;
  *out_len = buf_len;
  return PST_OK;
}

// Encode 8/16-bit gray/gray-alpha/RGB/RGBA to PNG. Pixels are native-endian;
// 16-bit is byte-swapped to PNG big-endian on write. compression in [0, 9];
// negative = zlib default.
int pst_png_encode(const uint8_t* pixels, int w, int h, int ch, int bit_depth,
                   int compression, uint8_t** out, size_t* out_len) {
  if (!pixels || !out || !out_len || ch < 1 || ch > 4 ||
      (bit_depth != 8 && bit_depth != 16))
    return PST_ERR_ARGS;
  static const int color_types[5] = {0, PNG_COLOR_TYPE_GRAY,
                                     PNG_COLOR_TYPE_GRAY_ALPHA,
                                     PNG_COLOR_TYPE_RGB,
                                     PNG_COLOR_TYPE_RGB_ALPHA};
  png_structp png = png_create_write_struct(PNG_LIBPNG_VER_STRING, nullptr,
                                            nullptr, nullptr);
  if (!png) return PST_ERR_ENCODE;
  png_infop info = png_create_info_struct(png);
  if (!info) {
    png_destroy_write_struct(&png, nullptr);
    return PST_ERR_ENCODE;
  }
  PngWriteState st;
  std::vector<png_bytep> rows;
  if (setjmp(png_jmpbuf(png))) {
    png_destroy_write_struct(&png, &info);
    return PST_ERR_ENCODE;
  }
  png_set_write_fn(png, &st, png_mem_write, png_mem_flush);
  png_set_IHDR(png, info, w, h, bit_depth, color_types[ch],
               PNG_INTERLACE_NONE, PNG_COMPRESSION_TYPE_DEFAULT,
               PNG_FILTER_TYPE_DEFAULT);
  if (compression >= 0) png_set_compression_level(png, compression);
  png_write_info(png, info);
  if (bit_depth == 16 && host_is_little_endian()) png_set_swap(png);
  const size_t stride =
      static_cast<size_t>(w) * ch * (bit_depth == 16 ? 2 : 1);
  rows.resize(h);
  for (int i = 0; i < h; i++)
    rows[i] = const_cast<uint8_t*>(pixels) + i * stride;
  png_write_image(png, rows.data());
  png_write_end(png, nullptr);
  png_destroy_write_struct(&png, &info);
  uint8_t* buf = static_cast<uint8_t*>(malloc(st.buf.size()));
  if (!buf) return PST_ERR_ENCODE;
  memcpy(buf, st.buf.data(), st.buf.size());
  *out = buf;
  *out_len = st.buf.size();
  return PST_OK;
}

void pst_buffer_free(uint8_t* p) { free(p); }

}  // extern "C"
