// C++ Parquet row-group reader with Arrow C Data export.
//
// SURVEY.md §2.9 names this the one mandatory native component: "a C++
// Parquet row-group reader + Arrow-compatible columnar buffers with zero-copy
// export for JAX device_put" (the reference's native horsepower is the same
// Arrow/Parquet C++ stack, reached via pyarrow — reference setup.py:41).
//
// The whole read happens inside one extern-"C" call: file open (optionally
// memory-mapped), footer/metadata decode, column projection, decompression
// and decode into Arrow columnar buffers — all GIL-free (ctypes releases the
// GIL for the duration). The result crosses back into Python through the
// Arrow C Data Interface (ArrowSchema/ArrowArray), which pyarrow imports
// without copying; fixed-width columns then reach numpy/JAX zero-copy.
//
// Built against the pyarrow wheel's bundled libarrow/libparquet (same
// libraries pyarrow itself runs), so buffers are allocated from the same
// Arrow memory pool and stay compatible across the boundary.

#include <cstdint>
#include <cstring>
#include <memory>
#include <string>
#include <vector>

#include <arrow/api.h>
#include <arrow/c/bridge.h>
#include <arrow/io/api.h>
#include <parquet/arrow/reader.h>
#include <parquet/file_reader.h>
#include <parquet/properties.h>

namespace {

int32_t set_err(const std::string& msg, char* err, int32_t err_cap) {
  if (err != nullptr && err_cap > 0) {
    std::strncpy(err, msg.c_str(), static_cast<size_t>(err_cap) - 1);
    err[err_cap - 1] = '\0';
  }
  return -1;
}

arrow::Result<std::shared_ptr<arrow::io::RandomAccessFile>> open_file(
    const char* path, int32_t use_mmap) {
  if (use_mmap) {
    ARROW_ASSIGN_OR_RAISE(auto mmapped, arrow::io::MemoryMappedFile::Open(
                                            path, arrow::io::FileMode::READ));
    return std::static_pointer_cast<arrow::io::RandomAccessFile>(mmapped);
  }
  ARROW_ASSIGN_OR_RAISE(auto file, arrow::io::ReadableFile::Open(path));
  return std::static_pointer_cast<arrow::io::RandomAccessFile>(file);
}

arrow::Status make_reader(const char* path, int32_t use_mmap,
                          int32_t use_threads,
                          std::unique_ptr<parquet::arrow::FileReader>* out) {
  ARROW_ASSIGN_OR_RAISE(auto file, open_file(path, use_mmap));
  parquet::arrow::FileReaderBuilder builder;
  ARROW_RETURN_NOT_OK(builder.Open(file));
  parquet::ArrowReaderProperties props;
  props.set_use_threads(use_threads != 0);
  // Coalesced async column-chunk prefetch: one large read per column chunk
  // instead of many small ones — matters on object-store-backed mounts.
  props.set_pre_buffer(true);
  builder.properties(props);
  return builder.Build(out);
}

struct ReaderHandle {
  std::unique_ptr<parquet::arrow::FileReader> reader;
};

}  // namespace

extern "C" {

// ---- cached-handle API: open once, read many row groups -------------------
// (Re-opening per read costs a footer parse per call — ~25% on small groups.)

// Returns an opaque handle (0 on failure). One handle per thread: the
// underlying FileReader is not safe for concurrent reads.
void* pst_open(const char* path, int32_t use_mmap, int32_t use_threads,
               char* err, int32_t err_cap) {
  auto handle = std::make_unique<ReaderHandle>();
  auto st = make_reader(path, use_mmap, use_threads, &handle->reader);
  if (!st.ok()) {
    set_err(st.ToString(), err, err_cap);
    return nullptr;
  }
  return handle.release();
}

void pst_close(void* opaque) {
  delete static_cast<ReaderHandle*>(opaque);
}

int32_t pst_handle_num_row_groups(void* opaque) {
  auto* handle = static_cast<ReaderHandle*>(opaque);
  return handle->reader->parquet_reader()->metadata()->num_row_groups();
}

int32_t pst_handle_read_row_group(void* opaque, int32_t row_group,
                                  const int32_t* columns, int32_t n_columns,
                                  struct ArrowSchema* out_schema,
                                  struct ArrowArray* out_array,
                                  char* err, int32_t err_cap) {
  auto* handle = static_cast<ReaderHandle*>(opaque);
  auto* reader = handle->reader.get();
  if (row_group < 0 ||
      row_group >= reader->parquet_reader()->metadata()->num_row_groups()) {
    return set_err("row_group index out of range", err, err_cap);
  }
  std::shared_ptr<arrow::Table> table;
  arrow::Status st;
  if (n_columns >= 0) {
    std::vector<int> cols(columns, columns + n_columns);
    st = reader->ReadRowGroup(row_group, cols, &table);
  } else {
    st = reader->ReadRowGroup(row_group, &table);
  }
  if (!st.ok()) return set_err(st.ToString(), err, err_cap);
  auto batch_result = table->CombineChunksToBatch(arrow::default_memory_pool());
  if (!batch_result.ok()) {
    return set_err(batch_result.status().ToString(), err, err_cap);
  }
  st = arrow::ExportRecordBatch(*batch_result.ValueUnsafe(), out_array,
                                out_schema);
  if (!st.ok()) return set_err(st.ToString(), err, err_cap);
  return 0;
}

// Footer probe: row-group count, total rows, per-row-group row counts
// (out_rg_rows may be null; otherwise it must hold >= the returned count).
int32_t pst_parquet_file_info(const char* path, int32_t use_mmap,
                              int64_t* out_num_row_groups, int64_t* out_num_rows,
                              int64_t* out_rg_rows, int32_t rg_rows_cap,
                              char* err, int32_t err_cap) {
  std::unique_ptr<parquet::arrow::FileReader> reader;
  auto st = make_reader(path, use_mmap, /*use_threads=*/0, &reader);
  if (!st.ok()) return set_err(st.ToString(), err, err_cap);
  auto metadata = reader->parquet_reader()->metadata();
  *out_num_row_groups = metadata->num_row_groups();
  *out_num_rows = metadata->num_rows();
  if (out_rg_rows != nullptr) {
    int32_t n = metadata->num_row_groups();
    if (n > rg_rows_cap) return set_err("rg_rows_cap too small", err, err_cap);
    for (int32_t i = 0; i < n; ++i) {
      out_rg_rows[i] = metadata->RowGroup(i)->num_rows();
    }
  }
  return 0;
}

// Read one row group (optionally a projection of parquet leaf-column
// indices; n_columns < 0 reads all) into a single Arrow record batch and
// export it via the C Data Interface. The caller owns out_schema/out_array
// and must release them (pyarrow's import does).
int32_t pst_read_row_group(const char* path, int32_t row_group,
                           const int32_t* columns, int32_t n_columns,
                           int32_t use_mmap, int32_t use_threads,
                           struct ArrowSchema* out_schema,
                           struct ArrowArray* out_array,
                           char* err, int32_t err_cap) {
  void* handle = pst_open(path, use_mmap, use_threads, err, err_cap);
  if (handle == nullptr) return -1;
  int32_t rc = pst_handle_read_row_group(handle, row_group, columns, n_columns,
                                         out_schema, out_array, err, err_cap);
  pst_close(handle);
  return rc;
}

}  // extern "C"
