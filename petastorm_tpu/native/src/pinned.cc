// DMA-friendly host memory for the arena pool, plus the host-memcpy
// ceiling probe the bench children compare h2d_GBps against.
//
// pst_pinned_alloc maps page-aligned anonymous memory (MAP_POPULATE
// pre-faults every page so first-touch faults never land inside the
// assemble thread) and best-effort mlocks it so the pages stay resident
// for the accelerator runtime's DMA engine. mlock failure (RLIMIT_MEMLOCK)
// is not an error: the mapping is still page-aligned and pre-faulted,
// which is most of the win on hosts without CAP_IPC_LOCK.

#include <cstring>
#include <cstdlib>
#include <ctime>

#include <sys/mman.h>

extern "C" {

// Returns 1 when the region is mlocked, 0 when page-aligned only,
// -1 when the mapping itself failed. *out receives the base pointer.
int pst_pinned_alloc(size_t nbytes, int do_lock, void** out) {
    if (out == nullptr || nbytes == 0) return -1;
    int flags = MAP_PRIVATE | MAP_ANONYMOUS;
#ifdef MAP_POPULATE
    flags |= MAP_POPULATE;
#endif
    void* p = mmap(nullptr, nbytes, PROT_READ | PROT_WRITE, flags, -1, 0);
    if (p == MAP_FAILED) return -1;
    int locked = 0;
    if (do_lock && mlock(p, nbytes) == 0) locked = 1;
    *out = p;
    return locked;
}

void pst_pinned_free(void* p, size_t nbytes, int locked) {
    if (p == nullptr) return;
    if (locked) munlock(p, nbytes);
    munmap(p, nbytes);
}

// Sustained single-thread memcpy bandwidth in GB/s over `reps` copies of
// an `nbytes` buffer (one untimed warmup). This is the host-side ceiling
// any h2d path built on host memcpy cannot beat.
double pst_memcpy_GBps(size_t nbytes, int reps) {
    if (nbytes == 0 || reps <= 0) return -1.0;
    char* a = static_cast<char*>(malloc(nbytes));
    char* b = static_cast<char*>(malloc(nbytes));
    if (a == nullptr || b == nullptr) {
        free(a);
        free(b);
        return -1.0;
    }
    memset(a, 1, nbytes);
    memset(b, 0, nbytes);
    memcpy(b, a, nbytes);  // warmup: fault + warm caches outside the window
    struct timespec t0, t1;
    clock_gettime(CLOCK_MONOTONIC, &t0);
    for (int i = 0; i < reps; ++i) {
        memcpy(b, a, nbytes);
        asm volatile("" : : "r"(b) : "memory");
    }
    clock_gettime(CLOCK_MONOTONIC, &t1);
    double dt = (t1.tv_sec - t0.tv_sec) + (t1.tv_nsec - t0.tv_nsec) * 1e-9;
    free(a);
    free(b);
    if (dt <= 0.0) return -1.0;
    return static_cast<double>(nbytes) * reps / dt / 1e9;
}

}  // extern "C"
