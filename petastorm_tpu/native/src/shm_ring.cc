// POSIX shared-memory SPSC ring buffer: the process-pool transport.
//
// Replaces the reference's ZeroMQ tcp://127.0.0.1 sockets
// (reference workers_pool/process_pool.py:52-74) with a zero-syscall
// steady-state path: one producer process, one consumer process, variable
// size length-prefixed messages in an mmap'd ring, C++11 atomics for the
// head/tail handshake, adaptive spin-then-sleep waiting.
//
// Layout of the shm segment:
//   [ PstRingHeader (one 4 KiB page) | data bytes (capacity) ]
// head/tail are monotonically increasing byte offsets (mod capacity for
// indexing). Messages are 8-byte-aligned: u32 length + payload. A length of
// 0xFFFFFFFF is a wrap marker: skip to the start of the ring.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <new>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x70737452494e4731ULL;  // "pstRING1"
constexpr uint32_t kWrapMarker = 0xFFFFFFFFu;
constexpr size_t kHeaderSize = 4096;
// Modest spin before napping: high spin counts starve peers on low-core
// hosts (the transport is memcpy-bound, not latency-bound).
constexpr int kSpinIters = 64;

struct PstRingHeader {
  uint64_t magic;
  uint64_t capacity;
  alignas(64) std::atomic<uint64_t> head;  // producer cursor
  alignas(64) std::atomic<uint64_t> tail;  // consumer cursor
  alignas(64) std::atomic<uint32_t> flags;     // control word, peer-settable
  std::atomic<uint32_t> producer_closed;
};

struct PstRing {
  PstRingHeader* hdr;
  uint8_t* data;
  size_t map_size;
  bool owner;
  char name[256];
};

inline uint64_t align8(uint64_t v) { return (v + 7) & ~7ULL; }

void nap() {
  struct timespec ts {0, 200000};  // 0.2 ms
  nanosleep(&ts, nullptr);
}

// Remaining milliseconds budget helper; timeout_ms < 0 means forever.
struct Deadline {
  explicit Deadline(int timeout_ms) : forever(timeout_ms < 0) {
    if (!forever) {
      clock_gettime(CLOCK_MONOTONIC, &end);
      end.tv_sec += timeout_ms / 1000;
      end.tv_nsec += (timeout_ms % 1000) * 1000000L;
      if (end.tv_nsec >= 1000000000L) {
        end.tv_sec += 1;
        end.tv_nsec -= 1000000000L;
      }
    }
  }
  bool expired() const {
    if (forever) return false;
    struct timespec now;
    clock_gettime(CLOCK_MONOTONIC, &now);
    if (now.tv_sec != end.tv_sec) return now.tv_sec > end.tv_sec;
    return now.tv_nsec >= end.tv_nsec;
  }
  bool forever;
  struct timespec end;
};

}  // namespace

extern "C" {

enum PstRingError {
  PST_RING_OK = 0,
  PST_RING_ERR_SYS = -1,       // errno-level failure
  PST_RING_ERR_ARGS = -2,
  PST_RING_ERR_TIMEOUT = -3,
  PST_RING_ERR_CLOSED = -4,    // producer closed and ring drained
  PST_RING_ERR_TOO_BIG = -5,   // message larger than capacity/2
  PST_RING_ERR_AGAIN = -6,     // nothing available right now
  PST_RING_ERR_CAPACITY = -7,  // caller buffer too small
};

PstRing* pst_ring_create(const char* name, uint64_t capacity) {
  if (!name || capacity < 4096) return nullptr;
  capacity = align8(capacity);
  shm_unlink(name);  // stale segment from a crashed run
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  size_t total = kHeaderSize + capacity;
  if (ftruncate(fd, total) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  auto* hdr = new (mem) PstRingHeader();
  hdr->capacity = capacity;
  hdr->head.store(0, std::memory_order_relaxed);
  hdr->tail.store(0, std::memory_order_relaxed);
  hdr->flags.store(0, std::memory_order_relaxed);
  hdr->producer_closed.store(0, std::memory_order_relaxed);
  hdr->magic = kMagic;  // set last: openers validate
  PstRing* ring = new PstRing();
  ring->hdr = hdr;
  ring->data = static_cast<uint8_t*>(mem) + kHeaderSize;
  ring->map_size = total;
  ring->owner = true;
  strncpy(ring->name, name, sizeof(ring->name) - 1);
  ring->name[sizeof(ring->name) - 1] = 0;
  return ring;
}

PstRing* pst_ring_open(const char* name) {
  if (!name) return nullptr;
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || static_cast<size_t>(st.st_size) <= kHeaderSize) {
    close(fd);
    return nullptr;
  }
  void* mem =
      mmap(nullptr, st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  auto* hdr = static_cast<PstRingHeader*>(mem);
  if (hdr->magic != kMagic ||
      kHeaderSize + hdr->capacity != static_cast<uint64_t>(st.st_size)) {
    munmap(mem, st.st_size);
    return nullptr;
  }
  PstRing* ring = new PstRing();
  ring->hdr = hdr;
  ring->data = static_cast<uint8_t*>(mem) + kHeaderSize;
  ring->map_size = st.st_size;
  ring->owner = false;
  strncpy(ring->name, name, sizeof(ring->name) - 1);
  ring->name[sizeof(ring->name) - 1] = 0;
  return ring;
}

void pst_ring_close(PstRing* ring) {
  if (!ring) return;
  munmap(ring->hdr, ring->map_size);
  delete ring;
}

int pst_ring_unlink(const char* name) {
  return shm_unlink(name) == 0 ? PST_RING_OK : PST_RING_ERR_SYS;
}

// --------------------------------------------------------------- producer

int pst_ring_write(PstRing* ring, const uint8_t* data, uint64_t len,
                   int timeout_ms) {
  if (!ring || (!data && len)) return PST_RING_ERR_ARGS;
  PstRingHeader* h = ring->hdr;
  const uint64_t cap = h->capacity;
  const uint64_t need = align8(4 + len);
  if (need > cap / 2) return PST_RING_ERR_TOO_BIG;

  Deadline deadline(timeout_ms);
  int spins = 0;
  for (;;) {
    uint64_t head = h->head.load(std::memory_order_relaxed);
    uint64_t tail = h->tail.load(std::memory_order_acquire);
    uint64_t idx = head % cap;
    uint64_t contiguous = cap - idx;
    // Reserve a wrap marker's worth when the message doesn't fit at the end.
    uint64_t effective_need = contiguous >= need ? need : contiguous + need;
    if (cap - (head - tail) >= effective_need) {
      if (contiguous < need) {
        if (contiguous >= 4) {
          memcpy(ring->data + idx, &kWrapMarker, 4);
        }
        head += contiguous;
        idx = 0;
      }
      uint32_t len32 = static_cast<uint32_t>(len);
      memcpy(ring->data + idx, &len32, 4);
      if (len) memcpy(ring->data + idx + 4, data, len);
      h->head.store(head + need, std::memory_order_release);
      return PST_RING_OK;
    }
    // Control flag set (FINISHED broadcast): abort instead of blocking on a
    // full ring nobody will drain.
    if (h->flags.load(std::memory_order_relaxed) != 0) {
      return PST_RING_ERR_CLOSED;
    }
    if (++spins < kSpinIters) continue;
    if (deadline.expired()) return PST_RING_ERR_TIMEOUT;
    nap();
  }
}

// Write with a 1-byte tag prefix without the caller having to concatenate
// (saves a full payload copy on the Python side).
int pst_ring_write_tagged(PstRing* ring, uint8_t tag, const uint8_t* data,
                          uint64_t len, int timeout_ms) {
  if (!ring || (!data && len)) return PST_RING_ERR_ARGS;
  PstRingHeader* h = ring->hdr;
  const uint64_t cap = h->capacity;
  const uint64_t total = 1 + len;
  const uint64_t need = align8(4 + total);
  if (need > cap / 2) return PST_RING_ERR_TOO_BIG;

  Deadline deadline(timeout_ms);
  int spins = 0;
  for (;;) {
    uint64_t head = h->head.load(std::memory_order_relaxed);
    uint64_t tail = h->tail.load(std::memory_order_acquire);
    uint64_t idx = head % cap;
    uint64_t contiguous = cap - idx;
    uint64_t effective_need = contiguous >= need ? need : contiguous + need;
    if (cap - (head - tail) >= effective_need) {
      if (contiguous < need) {
        if (contiguous >= 4) {
          memcpy(ring->data + idx, &kWrapMarker, 4);
        }
        head += contiguous;
        idx = 0;
      }
      uint32_t len32 = static_cast<uint32_t>(total);
      memcpy(ring->data + idx, &len32, 4);
      ring->data[idx + 4] = tag;
      if (len) memcpy(ring->data + idx + 5, data, len);
      h->head.store(head + need, std::memory_order_release);
      return PST_RING_OK;
    }
    if (h->flags.load(std::memory_order_relaxed) != 0) {
      return PST_RING_ERR_CLOSED;
    }
    if (++spins < kSpinIters) continue;
    if (deadline.expired()) return PST_RING_ERR_TIMEOUT;
    nap();
  }
}

void pst_ring_mark_closed(PstRing* ring) {
  if (ring) ring->hdr->producer_closed.store(1, std::memory_order_release);
}

// --------------------------------------------------------------- consumer

// Length of the next message, or AGAIN/CLOSED. Advances past wrap markers.
int pst_ring_peek(PstRing* ring, uint64_t* len_out) {
  if (!ring || !len_out) return PST_RING_ERR_ARGS;
  PstRingHeader* h = ring->hdr;
  const uint64_t cap = h->capacity;
  for (;;) {
    uint64_t tail = h->tail.load(std::memory_order_relaxed);
    uint64_t head = h->head.load(std::memory_order_acquire);
    if (head == tail) {
      if (h->producer_closed.load(std::memory_order_acquire)) {
        // Re-check: producer may have written between head load and flag.
        if (h->head.load(std::memory_order_acquire) == tail)
          return PST_RING_ERR_CLOSED;
        continue;
      }
      return PST_RING_ERR_AGAIN;
    }
    uint64_t idx = tail % cap;
    uint64_t contiguous = cap - idx;
    uint32_t len32;
    if (contiguous < 4) {
      // Too small even for a wrap marker: implicit wrap.
      h->tail.store(tail + contiguous, std::memory_order_release);
      continue;
    }
    memcpy(&len32, ring->data + idx, 4);
    if (len32 == kWrapMarker) {
      h->tail.store(tail + contiguous, std::memory_order_release);
      continue;
    }
    *len_out = len32;
    return PST_RING_OK;
  }
}

// Copy the next message into `out` and advance. Call after peek.
int pst_ring_pop(PstRing* ring, uint8_t* out, uint64_t out_capacity) {
  if (!ring) return PST_RING_ERR_ARGS;
  uint64_t len;
  int rc = pst_ring_peek(ring, &len);
  if (rc != PST_RING_OK) return rc;
  if (len > out_capacity) return PST_RING_ERR_CAPACITY;
  PstRingHeader* h = ring->hdr;
  const uint64_t cap = h->capacity;
  uint64_t tail = h->tail.load(std::memory_order_relaxed);
  uint64_t idx = tail % cap;
  if (len) memcpy(out, ring->data + idx + 4, len);
  h->tail.store(tail + align8(4 + len), std::memory_order_release);
  return PST_RING_OK;
}

// Blocking peek with timeout; adaptive spin then 0.2 ms naps.
int pst_ring_wait(PstRing* ring, uint64_t* len_out, int timeout_ms) {
  Deadline deadline(timeout_ms);
  int spins = 0;
  for (;;) {
    int rc = pst_ring_peek(ring, len_out);
    if (rc != PST_RING_ERR_AGAIN) return rc;
    if (++spins < kSpinIters) continue;
    if (deadline.expired()) return PST_RING_ERR_TIMEOUT;
    nap();
  }
}

uint64_t pst_ring_capacity(PstRing* ring) {
  return ring ? ring->hdr->capacity : 0;
}

uint64_t pst_ring_readable_bytes(PstRing* ring) {
  if (!ring) return 0;
  return ring->hdr->head.load(std::memory_order_acquire) -
         ring->hdr->tail.load(std::memory_order_acquire);
}

// Control word: either side may set/read (e.g. FINISHED broadcast).
void pst_ring_set_flags(PstRing* ring, uint32_t flags) {
  if (ring) ring->hdr->flags.store(flags, std::memory_order_release);
}

uint32_t pst_ring_get_flags(PstRing* ring) {
  return ring ? ring->hdr->flags.load(std::memory_order_acquire) : 0;
}

}  // extern "C"
