"""On-demand compilation of the C++ sources in ``petastorm_tpu/native/src``.

A tiny build system instead of a packaging-time ``build_ext``: sources are
compiled lazily on first use with ``g++`` into a content-hash-keyed shared
object under ``~/.cache/petastorm_tpu/native`` (override with
``PETASTORM_TPU_NATIVE_CACHE``), so editing a .cc file triggers exactly one
rebuild and concurrent processes race safely (atomic rename + lock file).
"""

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile
import threading

logger = logging.getLogger(__name__)

_SRC_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), 'src')
_LOCK = threading.Lock()
_LOADED = {}


def native_cache_dir():
    cache = os.environ.get('PETASTORM_TPU_NATIVE_CACHE')
    if not cache:
        cache = os.path.join(os.path.expanduser('~'), '.cache', 'petastorm_tpu', 'native')
    os.makedirs(cache, exist_ok=True)
    return cache


def source_path(filename):
    return os.path.join(_SRC_DIR, filename)


def _build_key(sources, compile_flags, link_flags):
    h = hashlib.sha256()
    for src in sources:
        with open(src, 'rb') as f:
            h.update(f.read())
        h.update(b'\0')
    h.update(' '.join(compile_flags + link_flags).encode())
    return h.hexdigest()[:16]


def build_and_load(name, sources, compile_flags=None, link_flags=None):
    """Compile ``sources`` (paths under src/) into lib<name>-<hash>.so and dlopen it.

    Returns a ``ctypes.CDLL``. Raises ``NativeBuildError`` when the toolchain
    or a dependency is missing; callers catch it and fall back to Python paths.
    """
    compile_flags = list(compile_flags or [])
    link_flags = list(link_flags or [])
    srcs = [s if os.path.isabs(s) else source_path(s) for s in sources]

    with _LOCK:
        cached = _LOADED.get(name)
        if cached is not None:
            return cached

        key = _build_key(srcs, compile_flags, link_flags)
        out_path = os.path.join(native_cache_dir(), 'lib{}-{}.so'.format(name, key))
        if not os.path.exists(out_path):
            # Cross-process lock: N spawned workers hitting a cold cache
            # should compile once, not N times.
            import fcntl
            with open(out_path + '.lock', 'w') as lock_file:  # pstlint: disable=lock-order-blocking(one-time lazy build path: serializing every in-process caller behind the flock'd compile IS the contract — N threads hitting a cold cache must produce one .so, then the _LOADED memo makes this branch unreachable)
                fcntl.flock(lock_file, fcntl.LOCK_EX)
                if not os.path.exists(out_path):
                    _compile(srcs, out_path, compile_flags, link_flags)
        lib = ctypes.CDLL(out_path)
        _LOADED[name] = lib
        return lib


class NativeBuildError(RuntimeError):
    pass


def _compile(srcs, out_path, compile_flags, link_flags):
    fd, tmp = tempfile.mkstemp(suffix='.so', dir=os.path.dirname(out_path))
    os.close(fd)
    cmd = (['g++', '-O3', '-std=c++17', '-fPIC', '-shared', '-pthread']
           + compile_flags + srcs + ['-o', tmp] + link_flags)
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    except (OSError, subprocess.TimeoutExpired) as exc:
        os.unlink(tmp)
        raise NativeBuildError('failed to run g++: {}'.format(exc))
    if proc.returncode != 0:
        os.unlink(tmp)
        raise NativeBuildError(
            'native build failed ({}):\n{}'.format(' '.join(cmd), proc.stderr[-4000:]))
    os.replace(tmp, out_path)  # atomic: concurrent builders converge on the same key
    logger.info('built native library %s', out_path)
