"""Native (C++) components of petastorm_tpu.

The reference keeps all native horsepower in dependencies (Arrow/Parquet C++,
libzmq, OpenCV — see SURVEY.md §2.9 / reference ``setup.py``). Here the hot
host-side paths are first-class C++ sources in this package, built on demand
with the system toolchain and loaded through ``ctypes``:

- :mod:`petastorm_tpu.native.image` — JPEG/PNG codec on libjpeg/libpng with a
  multithreaded batch decode (GIL released for the whole batch).
- :mod:`petastorm_tpu.native.shm_ring` — POSIX shared-memory ring buffer used
  as a zero-syscall results transport for the process pool (alternative to
  the reference's ZeroMQ tcp://127.0.0.1 sockets, ``process_pool.py:52-74``).
- :mod:`petastorm_tpu.native.parquet` — Parquet row-group reader linked
  against pyarrow's bundled libparquet/libarrow, exporting record batches
  zero-copy over the Arrow C Data Interface.

Every module degrades gracefully: ``available()`` returns False when the
toolchain or a library is missing and pure-Python/pyarrow paths take over.
"""

from petastorm_tpu.native.build import build_and_load, native_cache_dir  # noqa: F401
