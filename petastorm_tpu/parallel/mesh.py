"""Device-mesh helpers: the TPU-native stand-in for the reference's
rank-based multi-GPU coordination.

The reference coordinates multi-node training purely by static input sharding
(``cur_shard=rank, shard_count=world`` — ``petastorm/reader.py:485-502``,
SURVEY.md §5.8). Here the same rule is keyed by ``jax.process_index()`` /
``jax.process_count()``, and cross-chip data movement is XLA's ICI/DCN via
``jax.sharding`` — never hand-rolled collectives.
"""

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def process_shard():
    """``(cur_shard, shard_count)`` for this host — feed to make_reader.

    Parity target: BASELINE.json north-star ("cur_shard=jax.process_index()").
    """
    return jax.process_index(), jax.process_count()


class DeviceShardPlan(object):
    """Per-device slicing of a batch-dim-sharded host batch.

    ``devices[k]`` receives local rows ``bounds[k] = (start, stop)``; the
    staged shards stitch into the global array with
    ``jax.make_array_from_single_device_arrays(global_shape, sharding,
    shards)``. Because host batches are C-contiguous with a leading batch
    dim, every bound is a zero-copy contiguous sub-slice — the layout is
    computed once per (sharding, shape) and costs nothing per batch.
    """

    __slots__ = ('devices', 'bounds', 'global_shape')

    def __init__(self, devices, bounds, global_shape):
        self.devices = tuple(devices)
        self.bounds = tuple(bounds)
        self.global_shape = tuple(global_shape)

    @property
    def n_devices(self):
        return len(self.devices)


def replica_safe_concat(arrays):
    """Leading-dim concatenation safe on partially-replicated meshes.

    This jaxlib's SPMD ``jnp.concatenate`` lowering SUMS replicas into
    the result when inputs carry a replicated mesh axis (e.g. a
    ``('data', 'model')`` batch sharding — values come back multiplied by
    the replica count; observed on the forced-multi-device CPU platform,
    jax 0.4.37). Equal-shaped groups take a stack+reshape instead — the
    same concatenation through a lowering that keeps replicas
    replicated. A ragged group (only legal off-mesh, where the bug
    cannot occur) keeps the plain concatenate. Trace-safe: shapes are
    static under jit.
    """
    import jax.numpy as jnp
    head = arrays[0]
    if all(x.shape == head.shape for x in arrays[1:]):
        return jnp.stack(arrays).reshape(
            (len(arrays) * head.shape[0],) + tuple(head.shape[1:]))
    return jnp.concatenate(arrays)


def device_shard_plan(sharding, local_shape, process_count=None):
    """Plan per-device shard assembly for one field, or ``None``.

    Eligibility: the sharding partitions (at most) the leading batch dim —
    every addressable device's index is a unit-stride row range covering
    all non-batch dims — and the distinct row ranges are equal-sized and
    exactly tile the ``local_shape[0]`` host rows. Replication (e.g. a
    ``('data', 'model')`` mesh with the batch only on ``'data'``) is fine:
    replica devices share a bound and each receives its own put of the
    same sub-slice. Anything else (a sequence-sharded dim, uneven
    partitions, addressable shards that don't tile the local batch)
    returns ``None`` and the caller keeps the one-shot
    ``make_array_from_process_local_data`` path.

    Multi-host: the global batch is ``local_rows * process_count`` and the
    k-th distinct addressable row range (in global order) maps to the k-th
    local sub-slice — the same local-rows-in-global-order rule
    ``make_array_from_process_local_data`` applies, so the two paths stage
    identical global arrays.
    """
    local_shape = tuple(local_shape)
    if not local_shape or local_shape[0] <= 0:
        return None
    if process_count is None:
        process_count = jax.process_count()
    global_shape = (local_shape[0] * int(process_count),) + local_shape[1:]
    try:
        indices_map = sharding.addressable_devices_indices_map(global_shape)
    except (AttributeError, ValueError, TypeError):
        return None
    if not indices_map:
        return None
    entries = []
    for device, index in indices_map.items():
        if index is None:
            index = ()
        if not isinstance(index, tuple):
            index = (index,)
        if len(index) > len(global_shape):
            return None
        # Non-batch dims must be unsharded (full slices).
        for dim, idx in zip(global_shape[1:], index[1:]):
            if not isinstance(idx, slice):
                return None
            if idx.step not in (None, 1):
                return None
            if (idx.start not in (None, 0)
                    or idx.stop not in (None, dim)):
                return None
        lead = index[0] if index else slice(None)
        if not isinstance(lead, slice) or lead.step not in (None, 1):
            return None
        start = 0 if lead.start is None else int(lead.start)
        stop = global_shape[0] if lead.stop is None else int(lead.stop)
        if stop <= start:
            return None
        entries.append((device, start, stop))
    distinct = sorted({(start, stop) for _, start, stop in entries})
    sizes = {stop - start for start, stop in distinct}
    if len(sizes) != 1:
        return None
    shard_rows = sizes.pop()
    if shard_rows * len(distinct) != local_shape[0]:
        # The addressable shards must exactly tile this host's rows.
        return None
    local_bounds = {span: (k * shard_rows, (k + 1) * shard_rows)
                    for k, span in enumerate(distinct)}
    devices = [device for device, _, _ in entries]
    bounds = [local_bounds[(start, stop)] for _, start, stop in entries]
    return DeviceShardPlan(devices, bounds, global_shape)


def make_mesh(axis_shapes, devices=None):
    """Build a ``Mesh`` from ``{'axis': size}`` (``-1`` = fill with remaining).

    Example: ``make_mesh({'data': -1, 'model': 2})`` on 8 devices gives a
    (4, 2) mesh with axes ('data', 'model').
    """
    devices = list(devices if devices is not None else jax.devices())
    names = list(axis_shapes)
    sizes = list(axis_shapes.values())
    if sizes.count(-1) > 1:
        raise ValueError('At most one axis may be -1')
    known = int(np.prod([s for s in sizes if s != -1]))
    if len(devices) % known:
        raise ValueError('{} devices not divisible by fixed axes {}'.format(
            len(devices), axis_shapes))
    sizes = [len(devices) // known if s == -1 else s for s in sizes]
    if int(np.prod(sizes)) != len(devices):
        raise ValueError('Mesh {} does not cover {} devices'.format(
            dict(zip(names, sizes)), len(devices)))
    device_array = np.asarray(devices).reshape(sizes)
    return Mesh(device_array, tuple(names))


def batch_sharding(mesh, batch_axes='data'):
    """NamedSharding placing the leading (batch) dim on ``batch_axes``.

    Remaining dims are replicated — the standard data-parallel input layout.
    """
    if isinstance(batch_axes, str):
        batch_axes = (batch_axes,)
    return NamedSharding(mesh, PartitionSpec(tuple(batch_axes)))


def replicated_sharding(mesh):
    return NamedSharding(mesh, PartitionSpec())


def sequence_sharding(mesh, batch_axis='data', seq_axis='model', seq_dim=1):
    """NamedSharding for long-context inputs: batch dim on ``batch_axis``,
    sequence dim (``seq_dim``) on ``seq_axis``, rest replicated.

    The layout ring attention (``models/attention.py``) consumes: each device
    holds a ``[B/dp, T/sp, ...]`` tile, kv blocks rotate over ``seq_axis``'s
    ICI ring. Use as ``JaxLoader(..., sharding={'tokens': sequence_sharding(
    mesh)})`` (per-field dict: only sequence fields shard the T dim; labels
    etc. keep ``batch_sharding``).
    """
    if seq_dim < 1:
        raise ValueError('seq_dim must be >= 1 (0 is the batch dim)')
    spec = [None] * (seq_dim + 1)
    spec[0] = batch_axis
    spec[seq_dim] = seq_axis
    return NamedSharding(mesh, PartitionSpec(*spec))
