"""Device-mesh helpers: the TPU-native stand-in for the reference's
rank-based multi-GPU coordination.

The reference coordinates multi-node training purely by static input sharding
(``cur_shard=rank, shard_count=world`` — ``petastorm/reader.py:485-502``,
SURVEY.md §5.8). Here the same rule is keyed by ``jax.process_index()`` /
``jax.process_count()``, and cross-chip data movement is XLA's ICI/DCN via
``jax.sharding`` — never hand-rolled collectives.
"""

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def process_shard():
    """``(cur_shard, shard_count)`` for this host — feed to make_reader.

    Parity target: BASELINE.json north-star ("cur_shard=jax.process_index()").
    """
    return jax.process_index(), jax.process_count()


def make_mesh(axis_shapes, devices=None):
    """Build a ``Mesh`` from ``{'axis': size}`` (``-1`` = fill with remaining).

    Example: ``make_mesh({'data': -1, 'model': 2})`` on 8 devices gives a
    (4, 2) mesh with axes ('data', 'model').
    """
    devices = list(devices if devices is not None else jax.devices())
    names = list(axis_shapes)
    sizes = list(axis_shapes.values())
    if sizes.count(-1) > 1:
        raise ValueError('At most one axis may be -1')
    known = int(np.prod([s for s in sizes if s != -1]))
    if len(devices) % known:
        raise ValueError('{} devices not divisible by fixed axes {}'.format(
            len(devices), axis_shapes))
    sizes = [len(devices) // known if s == -1 else s for s in sizes]
    if int(np.prod(sizes)) != len(devices):
        raise ValueError('Mesh {} does not cover {} devices'.format(
            dict(zip(names, sizes)), len(devices)))
    device_array = np.asarray(devices).reshape(sizes)
    return Mesh(device_array, tuple(names))


def batch_sharding(mesh, batch_axes='data'):
    """NamedSharding placing the leading (batch) dim on ``batch_axes``.

    Remaining dims are replicated — the standard data-parallel input layout.
    """
    if isinstance(batch_axes, str):
        batch_axes = (batch_axes,)
    return NamedSharding(mesh, PartitionSpec(tuple(batch_axes)))


def replicated_sharding(mesh):
    return NamedSharding(mesh, PartitionSpec())


def sequence_sharding(mesh, batch_axis='data', seq_axis='model', seq_dim=1):
    """NamedSharding for long-context inputs: batch dim on ``batch_axis``,
    sequence dim (``seq_dim``) on ``seq_axis``, rest replicated.

    The layout ring attention (``models/attention.py``) consumes: each device
    holds a ``[B/dp, T/sp, ...]`` tile, kv blocks rotate over ``seq_axis``'s
    ICI ring. Use as ``JaxLoader(..., sharding={'tokens': sequence_sharding(
    mesh)})`` (per-field dict: only sequence fields shard the T dim; labels
    etc. keep ``batch_sharding``).
    """
    if seq_dim < 1:
        raise ValueError('seq_dim must be >= 1 (0 is the batch dim)')
    spec = [None] * (seq_dim + 1)
    spec[0] = batch_axis
    spec[seq_dim] = seq_axis
    return NamedSharding(mesh, PartitionSpec(*spec))
