"""Pod-safe input iteration: fail/finish together or not at all.

SURVEY §7 "hard parts": *one host's reader exception must not hang the other
hosts mid-collective*. The reference has nothing here — its failure model is
single-host (worker exceptions re-raise in the consumer, SURVEY §5.3); on a
TPU pod that model deadlocks: if host 3's input pipeline dies while hosts
0-2 enter the next step's collectives, the pod wedges until job timeout.

The fix is a periodic consensus: hosts contribute "I have a batch" to a
cross-process all-gather, and iteration ends on ALL hosts at the first
checked step where ANY host cannot proceed (exception or end-of-data).
Uneven shard tails get the same treatment, which also makes
``last_batch='drop'`` safe across hosts with unequal row counts.

Cost model: the consensus IS a blocking host-side collective (it must be —
the decision changes host control flow, so it cannot be folded into the
device step asynchronously). At ``consensus_interval=1`` every batch pays a
DCN round-trip gated on the slowest host's fetch; raise the interval to
amortize (checks every k-th step), trading up to k-1 steps of detection
latency. A host's own failure still surfaces locally at the step it happens
— the interval only delays when *peers* find out.
"""

import logging

import numpy as np

from petastorm_tpu.errors import PetastormTpuError

logger = logging.getLogger(__name__)


class PodAbortError(PetastormTpuError):
    """Raised on every host when any host's input pipeline failed."""


#: mesh ids whose sub-mesh coverage warning already fired (warn once per mesh).
_submesh_warned = set()


def global_all(local_ok, mesh=None):
    """True iff every process reports ``local_ok`` — one bool all-reduce.

    The consensus group is all JAX processes (a pod trains with all of them);
    ``mesh`` is accepted for symmetry with the loader APIs but the reduction
    always spans ``jax.process_count()``. Single-process is a no-op.
    """
    import jax

    if jax.process_count() == 1:
        return bool(local_ok)
    if mesh is not None and id(mesh) not in _submesh_warned:
        # Once per mesh object: this runs on the per-step consensus path.
        _submesh_warned.add(id(mesh))
        mesh_procs = {d.process_index for d in np.asarray(mesh.devices).flat}
        if len(mesh_procs) < jax.process_count():
            logger.warning(
                'global_all: mesh spans %d of %d processes, but consensus '
                'always covers ALL processes — a sub-mesh does not scope it',
                len(mesh_procs), jax.process_count())
    from jax.experimental import multihost_utils
    flags = multihost_utils.process_allgather(np.array([bool(local_ok)]))
    return bool(np.all(flags))


class PodSafeIterator(object):
    """Wraps a batch iterator with per-step pod consensus.

    :param iterator: local host's batch source (e.g. a ``JaxLoader``).
    :param mesh: the training ``Mesh`` (its devices define the consensus
        group). ``None`` degrades to single-host behavior.
    :param on_abort: ``'raise'`` (default) raises :class:`PodAbortError` on
        every healthy host when a peer failed; ``'stop'`` ends iteration
        quietly (treat a peer failure like end-of-data).
    :param consensus_interval: check peer health every k-th step (k=1, the
        default, checks every step; see the module docstring's cost model).
        A locally-failing host always joins one final consensus round — and
        round counts stay aligned, because that round is exactly the peers'
        next scheduled one. **k>1 is only safe when the training step itself
        has no cross-host collectives** (e.g. host-local eval or fully
        replicated inference): with collectives in the step, peers run up to
        k-1 steps the failed host can no longer participate in, and those
        device collectives deadlock before the next scheduled check — the
        very failure mode this wrapper exists to prevent. Keep k=1 for
        pjit/shard_map training loops.
    :param step_has_collectives: declare whether the *training step* contains
        cross-host collectives (pjit/shard_map programs over a multi-host
        mesh do). Defaults to True; combined with ``consensus_interval > 1``
        that configuration is the documented deadlock, so construction
        raises — pass ``step_has_collectives=False`` explicitly for
        collective-free steps to amortize the consensus.
    """

    def __init__(self, iterator, mesh=None, on_abort='raise',
                 consensus_interval=1, step_has_collectives=True):
        if on_abort not in ('raise', 'stop'):
            raise ValueError("on_abort must be 'raise' or 'stop'")
        if consensus_interval < 1:
            raise ValueError('consensus_interval must be >= 1')
        if consensus_interval > 1 and step_has_collectives:
            raise ValueError(
                'consensus_interval={} with step_has_collectives=True: peers '
                'would run up to {} steps whose device collectives a failed '
                'host can no longer join — that deadlocks the pod. Keep '
                'consensus_interval=1 for pjit/shard_map training loops, or '
                'pass step_has_collectives=False if the step really has no '
                'cross-host collectives.'.format(consensus_interval,
                                                 consensus_interval - 1))
        self._it = iter(iterator)
        self._mesh = mesh
        self._on_abort = on_abort
        self._interval = int(consensus_interval)
        self._step = 0
        self._done = False

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        batch, local_ok, local_exc = None, True, None
        try:
            batch = next(self._it)
        except StopIteration:
            local_ok = False
        except Exception as e:  # noqa: BLE001 - any input failure joins consensus
            local_ok = False
            local_exc = e
            logger.exception('Input pipeline failed on this host; '
                             'propagating abort to the pod')
        self._step += 1
        if local_ok and self._step % self._interval:
            return batch  # off-cycle healthy step: skip the collective
        peers_ok = global_all(local_ok, self._mesh)
        if local_ok and peers_ok:
            return batch
        # The consensus round informs peers; this host's own state decides
        # its exit, so a degenerate consensus can never yield a None batch.
        self._done = True
        if local_exc is not None:
            raise local_exc          # this host's own failure
        if not local_ok:
            raise StopIteration      # this host's clean end-of-data
        # A peer stopped (cleanly or not) while we still had a batch —
        # end here too, before the next collective can deadlock.
        if self._on_abort == 'raise':
            raise PodAbortError(
                'A peer host ended input mid-epoch (failure or uneven '
                'shard); aborting consistently on this host')
        raise StopIteration
