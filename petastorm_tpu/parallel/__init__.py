"""Mesh/sharding utilities for pod-scale input pipelines."""

from petastorm_tpu.parallel.mesh import (DeviceShardPlan,  # noqa: F401
                                         batch_sharding, device_shard_plan,
                                         make_mesh, process_shard,
                                         replica_safe_concat,
                                         replicated_sharding,
                                         sequence_sharding)
from petastorm_tpu.parallel.pod_guard import (PodAbortError,  # noqa: F401
                                              PodSafeIterator, global_all)
