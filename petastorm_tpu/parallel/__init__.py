"""Mesh/sharding utilities for pod-scale input pipelines."""

from petastorm_tpu.parallel.mesh import (batch_sharding, make_mesh,  # noqa: F401
                                         process_shard, replicated_sharding,
                                         sequence_sharding)
from petastorm_tpu.parallel.pod_guard import (PodAbortError,  # noqa: F401
                                              PodSafeIterator, global_all)
