"""Pipeline health supervision: heartbeats, stall diagnosis, recovery.

PR 1 made *discrete* failures survivable (retries, worker respawn,
row-group quarantine) and PR 2 made the staging hot path fast — but a
*stalled* pipeline (a hung ``device_put``, a dead data-service server, a
consumer that stopped draining, an arena pool wedged on GC holds) still
either hung the epoch silently or died with a bare timeout naming no
culprit. The tf.data-service literature (PAPERS.md) treats "which stage is
the bottleneck / which server is unhealthy" as first-class runtime state;
this module gives petastorm_tpu the same property:

:class:`Heartbeat` / :class:`HeartbeatRegistry`
    Every pipeline stage (reader ventilator, pool result handoff, staging
    assemble/dispatch threads, the JaxLoader consumer, the RemoteReader
    receive loop) registers a named heartbeat and *beats* on the hot path
    for the cost of two attribute writes — a ``time.monotonic()`` stamp
    plus a state label (``'reader-wait'``, ``'device_put'``, ...). No
    locks, no allocation: CPython attribute stores are atomic, and each
    heartbeat is written by exactly one thread. The state label is what
    turns a stale timestamp into a *diagnosis*: it says what the stage was
    last doing when it went quiet.

:class:`Watchdog`
    A supervisor thread with per-stage stall deadlines. On expiry it

    (a) **classifies** the stall (:func:`classify_stall`) from the beat
        ages + state labels + registered probe snapshots (queue depths,
        staging counters, worker liveness, per-server chunk ages);
    (b) emits a **diagnosis report** — an all-thread stack dump
        (``sys._current_frames``), the last-beat table, and every probe's
        snapshot — through the tracer and into
        ``Reader.diagnostics()`` / loader ``stats``;
    (c) runs **escalating recovery**: soft actions first (nudge queues,
        wake ventilators, fail a RemoteReader over to surviving servers),
        then — if the same stall persists past the escalation deadline —
        delivers a :class:`~petastorm_tpu.errors.PipelineStallError`
        carrying the full diagnosis instead of an anonymous hang.

Enable via ``watchdog=True`` (or per-stage ``stall_timeout_s``) on the
reader/loader factories, or process-wide with the
``PETASTORM_TPU_WATCHDOG`` environment variable (``1``/``true`` = on with
default deadlines; a number = on with that stall deadline in seconds;
``0``/``off``/unset = off). ``tests/test_chaos.py`` proves every
classification deterministically against the ``faults.py`` sites.
"""

import logging
import os
import sys
import threading
import time
import traceback

from petastorm_tpu.errors import PipelineStallError
from petastorm_tpu.membudget import (STATE_BREACH, STATE_DEGRADE,
                                     STATE_SHED)

logger = logging.getLogger(__name__)

ENV_VAR = 'PETASTORM_TPU_WATCHDOG'

#: Default per-stage stall deadline. Deliberately generous: a production
#: input pipeline that produces nothing for a minute is genuinely stuck,
#: while XLA compilation or a cold object-store read can take tens of
#: seconds without being a fault.
DEFAULT_STALL_TIMEOUT_S = 60.0

#: A stall that survives soft recovery for this multiple of its stage
#: deadline escalates to a hard :class:`PipelineStallError`.
DEFAULT_ESCALATION_FACTOR = 2.0

# Classification labels (the vocabulary tests and docs assert against).
READER_STARVED = 'reader-starved'
WORKER_POOL_DEAD = 'worker-pool-dead'
ASSEMBLE_STUCK = 'assemble-stuck'
DISPATCH_HUNG = 'dispatch-hung'
CONSUMER_NOT_DRAINING = 'consumer-not-draining'
ARENA_POOL_WEDGED = 'arena-pool-wedged'
REMOTE_SERVER_DEAD = 'remote-server-dead'
SERVER_DRAINING = 'server-draining'
SERVER_OVERLOADED = 'server-overloaded'
RESEQUENCER_STALLED = 'resequencer-stalled'
#: The host memory governor (``membudget.py``) sits at degrade-or-worse:
#: a quiet pipeline under active memory degradation is the *governor's*
#: episode (caches evicting, spill paused, ventilation paced), not a
#: stage fault. SOFT at degrade/shed — the governor owns the hard path
#: (a budget breach raises its own typed ``HostMemoryExceededError``
#: with a flight dump; escalating to a PipelineStallError here would
#: race it with a worse diagnosis).
MEMORY_PRESSURE = 'memory-pressure'

#: Governor ladder states that flip classification to MEMORY_PRESSURE
#: (the canonical constants — membudget's module surface is stdlib-only,
#: so the import is cycle-free and a renamed/added rung cannot silently
#: stop matching here). Breach is included: while the governor's typed
#: HostMemoryExceededError is in flight, a quiet pipeline must not be
#: hard-escalated as an ordinary stage stall racing it.
_MEM_DEGRADED_STATES = (STATE_DEGRADE, STATE_SHED, STATE_BREACH)

#: Classifications the memory ladder REINTERPRETS as memory-pressure
#: while degrade-or-worse holds: the starvation-shaped symptoms active
#: degradation deliberately causes (paced ventilation starves the
#: reader, shrunk pools starve the assembler, shedding servers refuse
#: consumers). Deliberately NOT the whole vocabulary: a dead worker, a
#: wedged publish behind the resequencer, or a hung device_put is a
#: genuine fault that memory pressure does not explain — those keep
#: their own classification (and their hard escalation), or a pipeline
#: parked at 90% of budget could hang forever behind a soft-only label.
_MEM_REINTERPRETED = frozenset({READER_STARVED, ARENA_POOL_WEDGED,
                                SERVER_OVERLOADED})
#: Pseudo-classification: every stale stage is parked in a *waiting* state
#: (on upstream or the consumer) and no culpable stage has crossed its own
#: deadline yet — not an actionable stall, so the watchdog records nothing
#: and re-checks next tick.
PIPELINE_WAITING = 'pipeline-waiting'

#: Classifications that never escalate to a hard error: a consumer that
#: stopped draining is the *trainer's* choice (long compile, eval loop,
#: checkpoint write) — killing the pipeline under it would turn normal
#: training-loop pauses into failures; a draining data-service server is
#: an *operator's* choice mid-rollout and ends in a clean END broadcast
#: (or a failover) on its own. The diagnosis is still recorded.
SOFT_ONLY = frozenset({CONSUMER_NOT_DRAINING, SERVER_DRAINING,
                       MEMORY_PRESSURE})

#: States in which a stage is parked waiting on its *upstream* (or on the
#: consumer) rather than doing its own work: a stale heartbeat in one of
#: these is a symptom, not a culprit — classification walks past it.
_WAITING_STATES = frozenset({'stageq-get', 'stageq-put', 'queue-wait',
                             'poll', 'idle'})


def watchdog_enabled(explicit=None):
    """Resolve the ``watchdog=`` knob against the environment default.

    ``explicit`` wins when not None; otherwise ``PETASTORM_TPU_WATCHDOG``
    decides (unset/empty/0/off = disabled)."""
    if explicit is not None:
        return bool(explicit)
    raw = os.environ.get(ENV_VAR, '').strip().lower()
    return raw not in ('', '0', 'off', 'false', 'no')


def env_stall_timeout():
    """A numeric ``PETASTORM_TPU_WATCHDOG`` value is the default stall
    deadline in seconds; any other truthy value keeps the built-in."""
    raw = os.environ.get(ENV_VAR, '').strip()
    try:
        value = float(raw)
    except ValueError:
        return None
    return value if value > 0 else None


def dump_all_stacks():
    """Formatted stack traces of every live thread (the ``faulthandler``
    view, but as a string we can embed in errors and diagnostics)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    chunks = []
    for ident, frame in sorted(sys._current_frames().items()):
        name = names.get(ident, '?')
        chunks.append('Thread {} ({}):\n{}'.format(
            name, ident, ''.join(traceback.format_stack(frame))))
    return '\n'.join(chunks)


class Heartbeat(object):
    """One stage's liveness record. Beaten by exactly one thread; read by
    the watchdog. ``beat()`` is two attribute writes — safe and cheap on
    any hot path."""

    __slots__ = ('name', 'stall_timeout_s', 'last_beat', 'state', 'beats')

    def __init__(self, name, stall_timeout_s):
        self.name = name
        self.stall_timeout_s = stall_timeout_s
        self.last_beat = time.monotonic()
        self.state = 'idle'
        self.beats = 0

    def beat(self, state=None):
        if state is not None:
            self.state = state
        self.last_beat = time.monotonic()
        self.beats += 1

    def age(self, now=None):
        return (now if now is not None else time.monotonic()) - self.last_beat

    def stalled(self, now=None):
        # 'idle' is explicit quiescence (stage not started yet, or cleanly
        # finished) — a loader built long before its first fetch, or an
        # exhausted epoch, must never read as a stall.
        if self.state == 'idle':
            return False
        return (self.stall_timeout_s is not None
                and self.age(now) > self.stall_timeout_s)


class HeartbeatRegistry(object):
    """Named heartbeats + probes + recovery actions for one pipeline.

    Stage threads call :meth:`register` once and then beat lock-free;
    everything else (probes, recoveries, snapshots) runs off the hot path
    under a lock. ``stall_timeouts`` maps stage name (or ``'default'``) to
    a deadline in seconds; a scalar applies to every stage.
    """

    def __init__(self, stall_timeouts=None):
        # Sanitizer hookup: lock-order-recorded when PETASTORM_TPU_SANITIZE
        # is armed (name matches pstlint's static graph node).
        from petastorm_tpu.analysis import sanitize
        self._lock = sanitize.tracked_lock(
            'petastorm_tpu.health:HeartbeatRegistry._lock')
        self._beats = {}
        self._probes = {}
        self._recoveries = {}     # classification label -> [fn, ...]
        env_default = env_stall_timeout()
        if stall_timeouts is None:
            stall_timeouts = {}
        elif not isinstance(stall_timeouts, dict):
            stall_timeouts = {'default': float(stall_timeouts)}
        self._timeouts = dict(stall_timeouts)
        if 'default' not in self._timeouts:
            self._timeouts['default'] = (env_default
                                         if env_default is not None
                                         else DEFAULT_STALL_TIMEOUT_S)

    def timeout_for(self, name):
        return self._timeouts.get(name, self._timeouts['default'])

    def register(self, name, stall_timeout_s=None):
        """Create (or return the existing) heartbeat for ``name``."""
        with self._lock:
            hb = self._beats.get(name)
            if hb is None:
                hb = Heartbeat(name, stall_timeout_s
                               if stall_timeout_s is not None
                               else self.timeout_for(name))
                self._beats[name] = hb
            return hb

    def unregister(self, name):
        with self._lock:
            self._beats.pop(name, None)
            self._probes.pop(name, None)

    def register_probe(self, name, fn):
        """``fn() -> dict`` sampled into every diagnosis (queue depths,
        staging counters, worker liveness...). Must be cheap-ish and must
        not block; exceptions are swallowed into the snapshot."""
        with self._lock:
            self._probes[name] = fn

    def register_recovery(self, classification, fn):
        """``fn(diagnosis) -> bool`` soft-recovery action for a stall
        classified as ``classification`` (True = acted). Runs on the
        watchdog thread: it must only touch thread-safe state."""
        with self._lock:
            self._recoveries.setdefault(classification, []).append(fn)

    def recoveries_for(self, classification):
        with self._lock:
            return list(self._recoveries.get(classification, ()))

    def beat_table(self, now=None):
        now = now if now is not None else time.monotonic()
        with self._lock:
            return {name: {'age_s': round(hb.age(now), 3),
                           'state': hb.state,
                           'beats': hb.beats,
                           'stall_timeout_s': hb.stall_timeout_s}
                    for name, hb in self._beats.items()}

    def probe_snapshot(self):
        with self._lock:
            probes = list(self._probes.items())
        out = {}
        for name, fn in probes:
            try:
                out[name] = fn()
            except Exception as e:  # noqa: BLE001 - probes must not kill the dog
                out[name] = {'probe_error': repr(e)}
        return out

    def stalled(self, now=None):
        """Heartbeats past their deadline, most-stale first."""
        now = now if now is not None else time.monotonic()
        with self._lock:
            late = [hb for hb in self._beats.values() if hb.stalled(now)]
        return sorted(late, key=lambda hb: hb.age(now), reverse=True)

    def min_timeout(self):
        with self._lock:
            timeouts = [hb.stall_timeout_s for hb in self._beats.values()
                        if hb.stall_timeout_s is not None]
        timeouts.append(self._timeouts['default'])
        return min(timeouts)


def classify_stall(beats, probes):
    """(classification, stage, detail) for a stall, from the beat table
    (name -> {age_s, state, stall_timeout_s}) and probe snapshots.

    Walks from the most upstream culpable stage down: a stage parked in a
    *waiting* state (on its upstream or its consumer) is a symptom, so
    blame lands on whoever was last seen doing (or failing to do) actual
    work. The returned ``detail`` is one human sentence.

    Memory-pressure overlay: with the governor armed at degrade-or-worse,
    starvation-shaped results (:data:`_MEM_REINTERPRETED`) reinterpret as
    the soft-only ``memory-pressure`` — intended load-shedding, not a
    fault — while genuine faults (dead workers, wedged publishes, hung
    transfers) keep their own classification and escalation.
    """
    classification, stage, detail = _classify_stall_stages(beats, probes)
    memory = probes.get('memory') or {}
    if memory.get('armed') and memory.get('state') in _MEM_DEGRADED_STATES \
            and classification in _MEM_REINTERPRETED:
        return (MEMORY_PRESSURE, 'memory',
                'host memory governor at {!r} ({} of {} budget bytes, '
                '{:.0%}) — would otherwise classify {}: {}'.format(
                    memory.get('state'), memory.get('accounted_bytes'),
                    memory.get('budget_bytes'), memory.get('frac') or 0.0,
                    classification, detail))
    return classification, stage, detail


def _classify_stall_stages(beats, probes):
    def stale(name):
        entry = beats.get(name)
        return (entry is not None and entry['stall_timeout_s'] is not None
                and entry['state'] != 'idle'     # explicit quiescence
                and entry['age_s'] > entry['stall_timeout_s'])

    def state(name):
        entry = beats.get(name, None)
        return entry['state'] if entry else None

    # A dead worker process outranks every downstream symptom: whatever
    # else went quiet sits downstream of a decode tier that lost a
    # process (respawn pending on the consumer thread, or budget spent).
    pool = probes.get('worker-pool', {})
    dead_workers = pool.get('dead_workers') or []
    if dead_workers:
        return (WORKER_POOL_DEAD, 'worker-pool',
                'worker process(es) {} are dead (PR-1 supervision will '
                'respawn on the next get_results poll if budget remains)'
                .format(dead_workers))

    # Deterministic mode: chunks buffered behind a ventilation-seq hole
    # while the handoff went quiet means the stream is held hostage by ONE
    # unpublished item (a wedged worker publish) — the other workers kept
    # producing, so worker-pool/reader symptoms look healthy. Checked
    # after dead-workers (a respawned worker re-delivers the hole) and
    # before the starvation rules (which would mis-blame the decode tier).
    resequencer = probes.get('resequencer') or {}
    if resequencer.get('buffered', 0) > 0 \
            and resequencer.get('waiting_s', 0) > 0 \
            and (stale('reader-handoff') or stale('consumer')
                 or (stale('assemble')
                     and state('assemble') == 'reader-wait')):
        return (RESEQUENCER_STALLED, 'resequencer',
                'deterministic resequencer has held {} chunk(s) for {}s '
                'waiting for ventilation seq {} — one item never '
                'published'.format(resequencer.get('buffered'),
                                   resequencer.get('waiting_s'),
                                   resequencer.get('expected_seq')))

    if stale('assemble'):
        st = state('assemble')
        if st == 'arena-wait':
            return (ARENA_POOL_WEDGED, 'assemble',
                    'assemble thread has waited {}s for a free host arena '
                    '(all arenas pinned by GC holds / undelivered batches)'
                    .format(beats['assemble']['age_s']))
        # 'reader-wait' is handled BELOW the remote-recv check: on a
        # data-service pipeline a starved assembler is the downstream echo
        # of a quiet receive loop, and the rpc probe must get to decide
        # dead-server vs merely-slow first.
        if st != 'reader-wait' and st not in _WAITING_STATES:
            return (ASSEMBLE_STUCK, 'assemble',
                    'assemble thread silent for {}s inside {!r} (collate/'
                    'shape-policy/transform work wedged)'.format(
                        beats['assemble']['age_s'], st))

    if stale('dispatch'):
        st = state('dispatch')
        if st in ('device_put', 'ready-wait'):
            return (DISPATCH_HUNG, 'dispatch',
                    'dispatch thread stuck {}s in {!r} — a device_put/'
                    'transfer fence never completed (wedged device or '
                    'interconnect)'.format(beats['dispatch']['age_s'], st))
        if st == 'out-put':
            return (CONSUMER_NOT_DRAINING, 'dispatch',
                    'dispatch thread blocked {}s handing a staged batch to '
                    'a full consumer queue'.format(
                        beats['dispatch']['age_s']))

    if stale('consumer'):
        st = state('consumer')
        # Inline staging (prefetch=0): the consumer thread runs the
        # pipeline itself, so its states carry the same meanings as the
        # engine threads' and classify identically.
        if st == 'device_put':
            return (DISPATCH_HUNG, 'consumer',
                    'inline device staging (prefetch=0) stuck {}s in a '
                    'device_put that never completed'.format(
                        beats['consumer']['age_s']))
        if st == 'reader-wait':
            return (READER_STARVED, 'consumer',
                    'inline consumer (prefetch=0) has waited {}s for the '
                    'reader'.format(beats['consumer']['age_s']))
        # Consumer walked away: stale in the 'delivered' state (it took a
        # batch and never came back). Always the soft-only classification
        # — a paused training loop is a choice, not a fault.
        if st == 'delivered':
            depth = probes.get('consumer', {}).get('queue_depth')
            return (CONSUMER_NOT_DRAINING, 'consumer',
                    'consumer has not requested a batch for {}s ({} staged '
                    'batch(es) waiting)'.format(
                        beats['consumer']['age_s'], depth))

    # Remote tier — checked only AFTER the downstream rules: a paused
    # consumer also quiets the receive loop (backpressure), and blaming
    # the servers for that would escalate a healthy pipeline. Reaching
    # here means nothing downstream explains the quiet, so the receive
    # loop's silence is genuine: a server fault when an rpc liveness
    # probe agrees, merely-slow servers otherwise.
    if stale('remote-recv'):
        remote = probes.get('remote-recv', {})
        dead = remote.get('dead_endpoints') or []
        if dead:
            return (REMOTE_SERVER_DEAD, 'remote-recv',
                    'data-service server(s) dead (lease expired or '
                    'unreachable over rpc): {}'.format(sorted(dead)))
        draining = remote.get('draining_endpoints') or []
        if draining:
            # An operator event, not a fault: the server announced the
            # drain in its lease heartbeats and will END (or a failover
            # will cover it) on its own. Soft-only.
            return (SERVER_DRAINING, 'remote-recv',
                    'data-service server(s) draining (graceful shutdown '
                    'announced in lease heartbeats): {}'.format(
                        sorted(draining)))
        refused = remote.get('refused_endpoints') or {}
        if refused:
            return (SERVER_OVERLOADED, 'remote-recv',
                    'data-service server(s) refused this consumer '
                    '(admission control at capacity): {}'.format(
                        sorted(refused)))
        return (READER_STARVED, 'remote-recv',
                'no chunks from any data-service server for {}s but all '
                'rpc probes answer — decode tier is slow, not dead'
                .format(beats['remote-recv']['age_s']))

    if stale('assemble') and state('assemble') == 'reader-wait':
        return (READER_STARVED, 'assemble',
                'assemble thread has waited {}s for the reader '
                '(decode/IO tier produced nothing)'
                .format(beats['assemble']['age_s']))

    # Reader-only pipelines (no staging engine): the handoff heartbeat is
    # beaten 'poll' entering the pool wait and 'handoff' when a row leaves
    # the reader — stale 'poll' is starvation, stale 'handoff' means the
    # consumer stopped pulling.
    if stale('reader-handoff'):
        st = state('reader-handoff')
        if st == 'handoff':
            return (CONSUMER_NOT_DRAINING, 'reader-handoff',
                    'no one has pulled a row from the reader for {}s'.format(
                        beats['reader-handoff']['age_s']))
        if st != 'idle':        # 'poll': parked waiting on the decode tier
            return (READER_STARVED, 'reader-handoff',
                    'reader produced nothing for {}s'.format(
                        beats['reader-handoff']['age_s']))
    if stale('ventilator') and state('ventilator') not in _WAITING_STATES:
        return (READER_STARVED, 'ventilator',
                'ventilator made no progress for {}s'.format(
                    beats['ventilator']['age_s']))

    # Fallback: name the most-stale stage doing actual work; stages parked
    # in waiting states are symptoms (the culprit's own deadline simply
    # hasn't expired yet) — report pipeline-waiting, which the watchdog
    # treats as "check again next tick", not as a stall episode.
    worst = max((n for n in beats
                 if stale(n) and beats[n]['state'] != 'idle'
                 and beats[n]['state'] not in _WAITING_STATES),
                key=lambda n: beats[n]['age_s'], default=None)
    if worst is None:
        return (PIPELINE_WAITING, 'unknown',
                'every stale stage is parked waiting on another; no '
                'culpable stage has crossed its own deadline yet')
    return ('{}-stalled'.format(worst), worst,
            'stage {!r} silent for {}s in state {!r}'.format(
                worst, beats[worst]['age_s'], beats[worst]['state']))


class StallDiagnosis(dict):
    """The report attached to trace events, diagnostics, and
    :class:`PipelineStallError`: classification + stage + detail + the
    last-beat table + probe snapshots + an all-thread stack dump."""

    @classmethod
    def capture(cls, registry, classification, stage, detail,
                beats=None, probes=None):
        """``beats``/``probes`` accept the snapshots that already drove the
        classification — probes can be expensive (rpc liveness sweeps), so
        the diagnosis must not pay for them twice (and must report exactly
        the evidence the classifier saw, not a second, possibly different,
        sample)."""
        return cls(classification=classification, stage=stage, detail=detail,
                   beats=beats if beats is not None else registry.beat_table(),
                   probes=(probes if probes is not None
                           else registry.probe_snapshot()),
                   stacks=dump_all_stacks(),
                   captured_at=time.time())

    def summary(self):
        """The diagnosis minus the (large) stack dump — what rides in
        ``stats`` / ``diagnostics`` without bloating them."""
        return {k: v for k, v in self.items() if k != 'stacks'}

    def format(self):
        lines = ['pipeline stall: {} (stage {!r}): {}'.format(
            self['classification'], self['stage'], self['detail'])]
        lines.append('last beats: {}'.format(
            {n: '{}s/{}'.format(b['age_s'], b['state'])
             for n, b in sorted(self['beats'].items())}))
        if self['probes']:
            lines.append('probes: {}'.format(self['probes']))
        lines.append('--- all-thread stack dump ---')
        lines.append(self['stacks'])
        return '\n'.join(lines)


class Watchdog(object):
    """Supervisor thread over a :class:`HeartbeatRegistry`.

    Ticks at a fraction of the tightest stage deadline. On a stall it
    classifies, records + traces the diagnosis, and runs the soft
    recoveries registered for that classification; a stall that persists
    past ``escalation * deadline`` (and is not in :data:`SOFT_ONLY`)
    becomes a hard :class:`PipelineStallError` handed to ``on_hard_stall``
    — which delivers it into the consumer's queue so the training loop
    raises a diagnosed error instead of hanging.
    """

    def __init__(self, registry, on_hard_stall=None, tracer=None,
                 escalation=DEFAULT_ESCALATION_FACTOR, poll_interval_s=None,
                 name='pst-watchdog', flight_recorder=None):
        from petastorm_tpu import metrics
        self._registry = registry
        self._on_hard_stall = on_hard_stall
        if tracer is None:
            from petastorm_tpu.trace import NullTracer
            tracer = NullTracer()
        self._tracer = tracer
        #: Optional petastorm_tpu.flight_recorder.FlightRecorder: sampled
        #: every check pass, dumped on hard escalation so the stall's trace
        #: ring + metric history survive the process.
        self._flight_recorder = flight_recorder
        self._m_stalls = metrics.counter(
            'pst_watchdog_stalls_total',
            'Stall episodes detected, by classification',
            labelnames=('classification',))
        self._m_soft = metrics.counter(
            'pst_watchdog_soft_recoveries_total',
            'Stall episodes where a soft recovery action ran')
        self._m_hard = metrics.counter(
            'pst_watchdog_hard_stalls_total',
            'Stalls escalated to PipelineStallError, by classification',
            labelnames=('classification',))
        self._escalation = max(1.0, float(escalation))
        self._poll_interval_s = poll_interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=name)
        self._lock = threading.Lock()
        # Current stall episode: (stage, classification, started_at,
        # hard_fired). A fresh beat on the stage ends the episode.
        self._episode = None
        self.stalls_detected = 0
        self.soft_recoveries = 0
        self.hard_stalls = 0
        self.last_diagnosis = None

    def start(self):
        self._thread.start()
        return self

    def stop(self, join_timeout_s=5):
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=join_timeout_s)

    @property
    def alive(self):
        return self._thread.is_alive()

    @property
    def episode_active(self):
        """True while a stall episode is in progress (detected and not yet
        recovered). The autotuner (``autotune.py``) pauses on this — knob
        changes mid-recovery would blur the diagnosis and can mask the
        stall the watchdog is escalating."""
        return self._episode is not None

    def _interval(self):
        if self._poll_interval_s is not None:
            return self._poll_interval_s
        # Four checks per tightest deadline, clamped to something humane.
        return min(max(self._registry.min_timeout() / 4.0, 0.02), 5.0)

    def _loop(self):
        while not self._stop.wait(self._interval()):
            try:
                self.check()
            except Exception:  # noqa: BLE001 - the dog must not die of a bug
                logger.exception('watchdog check failed')

    def check(self, now=None):
        """One supervision pass (also called directly by tests)."""
        now = now if now is not None else time.monotonic()
        if self._flight_recorder is not None:
            try:
                self._flight_recorder.sample()
            except Exception:  # noqa: BLE001 - recording must not kill the dog
                logger.debug('flight recorder sample failed', exc_info=True)
        stalled = self._registry.stalled(now)
        if not stalled:
            self._episode = None
            return None
        beats = self._registry.beat_table(now)
        probes = self._registry.probe_snapshot()
        classification, stage, detail = classify_stall(beats, probes)
        if classification == PIPELINE_WAITING:
            self._episode = None
            return None
        episode = self._episode
        if episode is None or episode[0] != stage or episode[1] != classification:
            # New stall episode: diagnose, trace, soft-recover.
            diagnosis = StallDiagnosis.capture(
                self._registry, classification, stage, detail,
                beats=beats, probes=probes)
            with self._lock:
                self.stalls_detected += 1
                self.last_diagnosis = diagnosis
            self._m_stalls.labels(classification).inc()
            self._tracer.instant('stall:{}'.format(classification),
                                 cat='watchdog')
            logger.warning('pipeline stall detected: %s (stage %r): %s',
                           classification, stage, detail)
            acted = False
            for fn in self._registry.recoveries_for(classification):
                try:
                    acted = bool(fn(diagnosis)) or acted
                except Exception:  # noqa: BLE001
                    logger.exception('soft recovery for %s failed',
                                     classification)
            if acted:
                with self._lock:
                    self.soft_recoveries += 1
                self._m_soft.inc()
                self._tracer.instant('stall-recovery:{}'.format(classification),
                                     cat='watchdog')
            self._episode = (stage, classification, now, False)
            return diagnosis
        # Ongoing episode: escalate once past escalation * deadline.
        _, _, started_at, hard_fired = episode
        deadline = self._registry.timeout_for(stage)
        hb_entry = beats.get(stage)
        if hb_entry is not None and hb_entry['stall_timeout_s'] is not None:
            deadline = hb_entry['stall_timeout_s']
        if (not hard_fired and classification not in SOFT_ONLY
                and now - started_at >= self._escalation * deadline):
            diagnosis = StallDiagnosis.capture(
                self._registry, classification, stage, detail,
                beats=beats, probes=probes)
            with self._lock:
                self.hard_stalls += 1
                self.last_diagnosis = diagnosis
            self._episode = (stage, classification, started_at, True)
            self._m_hard.labels(classification).inc()
            self._tracer.instant('stall-hard:{}'.format(classification),
                                 cat='watchdog')
            if self._flight_recorder is not None:
                # Dump BEFORE delivering the error: the post-mortem must
                # exist even if the consumer's teardown kills the process,
                # and the dump path rides the diagnosis into the error text.
                try:
                    dump_path = self._flight_recorder.dump(
                        diagnosis, reason=classification)
                    if dump_path is not None:
                        diagnosis['flight_dump'] = dump_path
                except Exception:  # noqa: BLE001 - best-effort by contract
                    logger.exception('flight recorder dump failed')
            error = PipelineStallError(diagnosis.format(),
                                       diagnosis=diagnosis)
            logger.error('pipeline stall escalated to hard error: %s '
                         '(stage %r)', classification, stage)
            if self._on_hard_stall is not None:
                try:
                    self._on_hard_stall(error)
                except Exception:  # noqa: BLE001
                    logger.exception('hard-stall delivery failed')
            return diagnosis
        return None

    def stats(self):
        with self._lock:
            last = self.last_diagnosis
            out = {'stalls_detected': self.stalls_detected,
                   'soft_recoveries': self.soft_recoveries,
                   'hard_stalls': self.hard_stalls,
                   'episode_active': self.episode_active,
                   'last_stall': last.summary() if last is not None else None}
        if self._flight_recorder is not None:
            out['flight_dumps'] = list(self._flight_recorder.dumps)
        return out


class HealthMonitor(object):
    """Registry + watchdog pair with one owner (a Reader or a JaxLoader).

    ``attach_health(registry)`` protocols let a loader share its registry
    with the reader underneath it, so one watchdog supervises the whole
    pipeline; a reader used standalone owns its own monitor.
    """

    def __init__(self, stall_timeouts=None, on_hard_stall=None, tracer=None,
                 escalation=DEFAULT_ESCALATION_FACTOR, poll_interval_s=None,
                 flight_recorder=None):
        self.registry = HeartbeatRegistry(stall_timeouts)
        if flight_recorder is None:
            # Env-armed stall flight recorder (PETASTORM_TPU_FLIGHT_RECORDER
            # = a directory): every supervised pipeline then dumps its
            # trace ring + metrics on a hard stall with no code change.
            from petastorm_tpu import flight_recorder as flight_mod
            flight_recorder = flight_mod.maybe_from_env(tracer=tracer)
        self.flight_recorder = flight_recorder
        self.watchdog = Watchdog(self.registry, on_hard_stall=on_hard_stall,
                                 tracer=tracer, escalation=escalation,
                                 poll_interval_s=poll_interval_s,
                                 flight_recorder=flight_recorder)

    def start(self):
        self.watchdog.start()
        return self

    def stop(self):
        self.watchdog.stop()

    def stats(self):
        out = self.watchdog.stats()
        out['beats'] = self.registry.beat_table()
        return out
