"""Steady-state worker supervision bookkeeping for out-of-process pools.

The ZeroMQ and shm-ring process pools both dispatch ventilated row-group
items round-robin to per-worker channels. Before this module a worker
process dying mid-epoch silently stranded whatever items were queued on (or
being processed by) it: the consumer's ``get_results`` hung until its
timeout with no clue why. Supervision turns that into a first-class event
(the tf.data-service stance, PAPERS.md): the pool detects the death, respawns
the worker within a restart budget, and **re-ventilates exactly the items
that were in flight on the dead worker** — everything else keeps flowing.

:class:`InFlightRegistry` is the transport-agnostic part: it assigns each
ventilated item a monotonically increasing sequence number, remembers which
worker slot holds which items, and suppresses the duplicates that a
respawn can produce. The duplicate window is real: a worker publishes its
data chunk(s) *then* the item-processed ack, so a kill between the two
leaves the parent holding data for an item it must also re-ventilate (it
cannot know the data made it out). Re-processing then re-publishes the same
chunks. The registry resolves this exactly-once at **chunk granularity**:
every publish within an item carries ``(seq, chunk_index)``, a pair that was
already delivered is dropped on re-arrival, and an ack for a seq that
already acked is ignored. Chunk indices (rather than a per-seq
at-most-once rule) keep workers free to publish several results per
ventilated item — the pre-supervision pool contract. Untagged publishes
(``seq is None``, e.g. from ``initialize()``) bypass deduplication
entirely.

Memory stays bounded: a seq's delivery record is forgotten at its first ack
unless the item was requeued by a respawn (``maybe-dup``), and the
maybe-dup set is capped by restart-budget x in-flight-items.
"""

import logging
import threading
import time
from collections import OrderedDict

import dill

logger = logging.getLogger(__name__)


class InFlightRegistry(object):
    """Thread-safe seq assignment + per-slot in-flight item bookkeeping.

    ``ventilate()`` runs on the ventilator thread while acks/data/respawns
    run on the consumer thread, so every mutation holds one lock.
    """

    def __init__(self, slots):
        self._lock = threading.Lock()
        self._inflight = [OrderedDict() for _ in range(slots)]
        self._seq_slot = {}
        self._next_seq = 0
        self._rr = 0
        self._delivered = {}   # seq -> set of delivered chunk indices
        self._maybe_dup = set()
        self.requeues = 0

    # -- dispatch ----------------------------------------------------------

    def assign(self, item):
        """New ventilated item -> ``(seq, slot)`` (round-robin)."""
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
            slot = self._rr % len(self._inflight)
            self._rr += 1
            self._inflight[slot][seq] = item
            self._seq_slot[seq] = slot
            return seq, slot

    def requeue(self, seq, item):
        """Re-dispatch a known seq (after its worker died) -> new slot.

        The seq keeps its identity so late replay of its original data/ack
        (already in transit when the worker died) is recognized as a
        duplicate rather than double-delivered.
        """
        with self._lock:
            self._maybe_dup.add(seq)
            slot = self._rr % len(self._inflight)
            self._rr += 1
            self._inflight[slot][seq] = item
            self._seq_slot[seq] = slot
            self.requeues += 1
            return slot

    # -- result-side events ------------------------------------------------

    def ack(self, seq):
        """Item-processed ack for ``seq``. Returns False for a stale
        duplicate (replayed ack of an already-completed item) that must NOT
        decrement in-flight counters again."""
        with self._lock:
            slot = self._seq_slot.pop(seq, None)
            if slot is None:
                # Stale ack: the replay of a requeued item finished too; its
                # delivery record can never be consulted again.
                self._maybe_dup.discard(seq)
                self._delivered.pop(seq, None)
                return False
            self._inflight[slot].pop(seq, None)
            if seq not in self._maybe_dup:
                # No replay can exist for a never-requeued item; forget it.
                self._delivered.pop(seq, None)
            return True

    def mark_delivered(self, seq, chunk_index):
        """About to hand chunk ``chunk_index`` of item ``seq`` to the
        consumer. Returns False when exactly this chunk was already
        delivered (respawn replay of a chunk that made it out before the
        worker died) — the caller must drop the message. ``seq=None``
        (untagged publish) is never deduplicated."""
        if seq is None:
            return True
        with self._lock:
            chunks = self._delivered.setdefault(seq, set())
            if chunk_index in chunks:
                return False
            chunks.add(chunk_index)
            return True

    # -- worker death ------------------------------------------------------

    def take_slot_items(self, slot):
        """All in-flight ``(seq, item)`` pairs of a dead worker, removed from
        its slot (caller requeues them via :meth:`requeue`)."""
        with self._lock:
            items = list(self._inflight[slot].items())
            self._inflight[slot].clear()
            for seq, _ in items:
                self._seq_slot.pop(seq, None)
            return items

    # -- introspection -----------------------------------------------------

    def in_flight_count(self, slot=None):
        with self._lock:
            if slot is not None:
                return len(self._inflight[slot])
            return sum(len(d) for d in self._inflight)

    def describe(self):
        """Human-readable in-flight summary for timeout/lost-worker errors."""
        with self._lock:
            per_slot = {}
            for slot, items in enumerate(self._inflight):
                if items:
                    per_slot[slot] = [self.describe_item(item)
                                      for item in list(items.values())[:4]]
            return per_slot

    @staticmethod
    def describe_item(item):
        args, kwargs = item
        if isinstance(kwargs, dict) and 'piece_index' in kwargs:
            return 'piece_index={}'.format(kwargs['piece_index'])
        return repr(args)[:60]


def format_worker_status(processes):
    """``[(slot, pid, exitcode-or-'alive'), ...]`` for error messages."""
    status = []
    for slot, process in enumerate(processes):
        if process is None:
            status.append((slot, None, 'never-started'))
            continue
        code = process.poll()
        status.append((slot, process.pid, 'alive' if code is None else code))
    return status


#: Liveness poll throttle inside ``get_results`` (supervised pools).
HEALTH_CHECK_INTERVAL_S = 0.25
#: Default worker-respawn budget over a pool's lifetime.
DEFAULT_MAX_WORKER_RESTARTS = 2


class SupervisedPoolMixin(object):
    """Transport-agnostic half of worker supervision, shared by the ZeroMQ
    and shm-ring process pools (so their policies cannot drift).

    The concrete pool provides the transport half:

    * ``_rescue_dead_worker_output(slot)`` — salvage whatever complete
      results the dead worker published before dying (may call
      ``_on_item_processed`` for rescued acks); best-effort;
    * ``_discard_pending_work(slot)`` — drop the slot's queued-but-unsent
      payloads (their items are about to be re-ventilated from the
      in-flight registry);
    * ``_respawn_worker_transport(slot)`` — tear down the dead worker's
      channel, build a fresh one, and spawn the replacement process;
    * ``_enqueue_work(slot, payload)`` — queue an already-serialized work
      item for ``slot`` (sent by the consumer thread's flush);

    and the shared state: ``_processes``, ``_registry``
    (:class:`InFlightRegistry`), ``_stopped``, ``_count_lock``,
    ``_ventilated_unprocessed``, ``_ventilator``, ``quarantine_sink``,
    ``_max_worker_restarts``. ``_pool_kind`` labels error messages.
    """

    _pool_kind = 'Worker'

    def _init_supervision(self, max_worker_restarts):
        self._max_worker_restarts = max_worker_restarts
        self._restarts = 0
        self._last_health_check = 0.0

    # -- result-side bookkeeping ------------------------------------------

    def _on_item_processed(self, seq):
        """Ack bookkeeping; False for a stale duplicate ack (respawn
        replay) that must not decrement in-flight counters again."""
        if seq is not None and not self._registry.ack(seq):
            logger.warning('Ignoring duplicate item-processed ack for seq %s',
                           seq)
            return False
        with self._count_lock:
            self._ventilated_unprocessed -= 1
        if self._ventilator is not None:
            self._ventilator.processed_item()
        return True

    def _handle_quarantine(self, record):
        from petastorm_tpu.workers import deliver_quarantine
        try:
            deliver_quarantine(self, record)
        except Exception:
            self.stop()
            self.join()
            raise

    # -- liveness ----------------------------------------------------------

    def _check_worker_health(self, force=False):
        """Detect dead workers; respawn within budget and re-ventilate their
        in-flight items, else raise WorkerLostError."""
        if self._stopped or not self._processes:
            return
        now = time.monotonic()
        if not force and now - self._last_health_check < HEALTH_CHECK_INTERVAL_S:
            return
        self._last_health_check = now
        for slot, process in enumerate(self._processes):
            if process is not None and process.poll() is not None:
                self._handle_dead_worker(slot, process.returncode)

    def _handle_dead_worker(self, slot, exitcode):
        from petastorm_tpu import metrics
        from petastorm_tpu.errors import WorkerLostError
        from petastorm_tpu.trace import get_global_tracer

        get_global_tracer().instant('worker-lost:{}'.format(slot), cat='fault')
        metrics.counter('pst_worker_deaths_total',
                        'Pool worker processes found dead').inc()
        self._rescue_dead_worker_output(slot)
        # Discard the slot's unsent payloads BEFORE snapshotting its
        # in-flight items: the ventilator thread may assign a new item to
        # this slot at any moment, and this order guarantees such an item is
        # either (a) enqueued after the discard — its payload survives and
        # flushes to the replacement worker — or (b) captured by
        # take_slot_items below and requeued. Were the discard to happen
        # after the snapshot, an item landing in between would be silently
        # dropped and hang the epoch. The overlap of (a) and (b) can
        # double-send an item; the (seq, chunk) delivery dedup absorbs that.
        self._discard_pending_work(slot)
        self._restarts += 1
        stranded = self._registry.take_slot_items(slot)
        if self._restarts > self._max_worker_restarts:
            details = ('{} {} (pid {}) exited with code {} and the restart '
                       'budget ({}) is exhausted. Worker status: {}. Stranded '
                       'in-flight items: {}.'.format(
                           self._pool_kind, slot, self._processes[slot].pid,
                           exitcode, self._max_worker_restarts,
                           format_worker_status(self._processes),
                           [self._registry.describe_item(item)
                            for _, item in stranded[:6]]))
            self.stop()
            raise WorkerLostError(details)

        logger.warning('%s %d exited with code %s mid-epoch; respawning '
                       '(%d/%d restarts used), re-ventilating %d in-flight '
                       'item(s)', self._pool_kind, slot, exitcode,
                       self._restarts, self._max_worker_restarts,
                       len(stranded))
        metrics.counter('pst_worker_respawns_total',
                        'Dead pool workers respawned within budget').inc()
        self._respawn_worker_transport(slot)
        for seq, item in stranded:
            new_slot = self._registry.requeue(seq, item)
            self._enqueue_work(new_slot, dill.dumps((seq,) + item))

    def _timeout_details(self, timeout):
        status = format_worker_status(self._processes)
        alive = [(slot, pid) for slot, pid, state in status if state == 'alive']
        dead = [(slot, pid, state) for slot, pid, state in status if state != 'alive']
        return ('No results for {}s. Workers alive: {}; dead: {}. Items in '
                'flight: {} (per-worker sample: {}). Respawns used: {}/{}.'
                .format(timeout, alive, dead,
                        self._registry.in_flight_count(),
                        self._registry.describe(), self._restarts,
                        self._max_worker_restarts))

    def _supervision_diagnostics(self):
        diag = {'worker_respawns': self._restarts,
                'max_worker_restarts': self._max_worker_restarts}
        if self._registry is not None:
            diag['items_in_flight'] = self._registry.in_flight_count()
        return diag
