"""In-process thread pool with ventilator feed and bounded results queue.

Parity: reference ``petastorm/workers_pool/thread_pool.py`` — per-worker
threads polling the ventilation queue (``thread_pool.py:61``), bounded
results queue with stop-aware put (``:200-214``), end-of-data detection
(queue empty AND all ventilated items processed AND ventilator completed,
``:155-160``), worker exceptions re-raised in the consumer (``:68-73``,
``:169-172``), and optional per-thread cProfile (``:48-49``, ``:190-198``).
"""

import pstats
import queue
import threading

from petastorm_tpu.workers import (EmptyResultError, RowGroupQuarantined,
                                   TimeoutWaitingForResultError,
                                   VentilatedItemProcessedMessage,
                                   deliver_quarantine, quarantine_record_for)

_DEFAULT_RESULTS_QUEUE_SIZE = 50
_VENTILATION_POLL_TIMEOUT_S = 0.001
_RESULTS_POLL_TIMEOUT_S = 0.01


class _WorkerTerminationRequested(Exception):
    pass


class WorkerThread(threading.Thread):
    def __init__(self, pool, worker, profiling_enabled=False):
        super().__init__(daemon=True)
        self._pool = pool
        self._worker = worker
        self._profiling_enabled = profiling_enabled
        self.profile = None

    def run(self):
        if self._profiling_enabled:
            import cProfile
            self.profile = cProfile.Profile()
            try:
                self.profile.enable()
            except ValueError:
                # Python 3.12 allows one active profiler per thread; another
                # tool (e.g. an outer profiler on a reused thread) wins —
                # degrade to unprofiled rather than kill the worker.
                self.profile = None
        try:
            self._worker.initialize()
            while not self._pool._stop_event.is_set():
                try:
                    args, kwargs = self._pool._ventilator_queue.get(
                        timeout=_VENTILATION_POLL_TIMEOUT_S)
                except queue.Empty:
                    continue
                try:
                    self._worker.process(*args, **kwargs)
                    self._pool._put_result(VentilatedItemProcessedMessage())
                except _WorkerTerminationRequested:
                    return
                except Exception as e:  # noqa: BLE001 - surfaces to consumer
                    record = quarantine_record_for(self._worker, e, args, kwargs)
                    self._pool._put_result(record if record is not None else e)
        except _WorkerTerminationRequested:
            return
        finally:
            if self._profiling_enabled and self.profile is not None:
                self.profile.disable()
            self._worker.shutdown()


class ThreadPool(object):
    def __init__(self, workers_count, results_queue_size=_DEFAULT_RESULTS_QUEUE_SIZE,
                 profiling_enabled=False):
        self._workers_count = workers_count
        self._results_queue = queue.Queue(maxsize=results_queue_size)
        self._ventilator_queue = queue.Queue()
        self._stop_event = threading.Event()
        self._workers = []
        self._ventilator = None
        self._profiling_enabled = profiling_enabled
        self._ventilated_unprocessed = 0
        self._count_lock = threading.Lock()
        #: Set by the Reader when ``error_budget`` is enabled; receives
        #: RowGroupQuarantined records (and raises when the budget is spent).
        self.quarantine_sink = None
        #: Optional health.Heartbeat (set by ``Reader.attach_health``).
        self.health_heartbeat = None

    @property
    def workers_count(self):
        return self._workers_count

    def start(self, worker_class, worker_args=None, ventilator=None):
        if self._workers:
            raise RuntimeError('ThreadPool already started')
        for worker_id in range(self._workers_count):
            worker = worker_class(worker_id, self._put_result, worker_args)
            thread = WorkerThread(self, worker, self._profiling_enabled)
            self._workers.append(thread)
            thread.start()
        self._ventilator = ventilator
        if ventilator is not None:
            ventilator._ventilate_fn = self.ventilate
            ventilator.start()

    def ventilate(self, *args, **kwargs):
        with self._count_lock:
            self._ventilated_unprocessed += 1
        self._ventilator_queue.put((args, kwargs))

    def _put_result(self, data):
        # Stop-aware bounded put (parity: thread_pool.py:200-214): never block
        # forever on a full queue if the pool is being stopped.
        from petastorm_tpu.faults import maybe_inject
        maybe_inject('queue-stall')
        while True:
            if self._stop_event.is_set():
                raise _WorkerTerminationRequested()
            try:
                self._results_queue.put(data, timeout=_RESULTS_POLL_TIMEOUT_S)
                return
            except queue.Full:
                continue

    def inject_consumer_error(self, exc):
        """Watchdog delivery path: surface ``exc`` to a consumer parked in
        :meth:`get_results` (whose default timeout is unbounded). Unlike a
        worker exception, an injected error does NOT stop/join the pool —
        the very point is that a worker may be wedged and unjoinable; the
        caller owns teardown."""
        self._injected_error = exc

    _injected_error = None

    def get_results(self, timeout=None):
        import time
        deadline = time.monotonic() + timeout if timeout is not None else None
        while True:
            if self._injected_error is not None and self._results_queue.empty():
                # Still no results: the diagnosed stall stands. (With
                # results available the pipeline recovered — deliver them
                # and drop the stale injection below.)
                error, self._injected_error = self._injected_error, None
                raise error
            if self.health_heartbeat is not None:
                self.health_heartbeat.beat('poll')
            try:
                result = self._results_queue.get(timeout=_RESULTS_POLL_TIMEOUT_S)
            except queue.Empty:
                if self._all_done():
                    raise EmptyResultError()
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutWaitingForResultError()
                continue
            if isinstance(result, VentilatedItemProcessedMessage):
                with self._count_lock:
                    self._ventilated_unprocessed -= 1
                if self._ventilator is not None:
                    self._ventilator.processed_item()
                continue
            if isinstance(result, RowGroupQuarantined):
                # Quarantine counts as item-processed (the row-group is
                # skipped, not retried); the sink enforces the budget.
                with self._count_lock:
                    self._ventilated_unprocessed -= 1
                if self._ventilator is not None:
                    self._ventilator.processed_item()
                try:
                    deliver_quarantine(self, result)
                except Exception:
                    self.stop()
                    self.join()
                    raise
                continue
            if isinstance(result, Exception):
                self.stop()
                self.join()
                raise result
            self._injected_error = None   # results flow again: recovered
            return result

    def _all_done(self):
        # Order matters: observe `completed` FIRST. After it is set no further
        # ventilation can occur, so the subsequent counter/queue reads cannot
        # miss in-flight items (they only drain monotonically).
        ventilator_done = self._ventilator is None or self._ventilator.completed()
        if not ventilator_done:
            return False
        with self._count_lock:
            nothing_in_flight = self._ventilated_unprocessed == 0
        return (nothing_in_flight
                and self._results_queue.empty() and self._ventilator_queue.empty())

    def stop(self):
        if self._ventilator is not None:
            self._ventilator.stop()
        self._stop_event.set()

    def join(self):
        for thread in self._workers:
            thread.join()
        if self._profiling_enabled:
            self._print_profiles()
        self._workers = []

    def _print_profiles(self):
        # A worker that never got ventilated work has an empty profile, which
        # pstats.Stats() rejects with TypeError — skip those.
        profiles = [t.profile for t in self._workers
                    if t.profile is not None and t.profile.getstats()]
        if not profiles:
            return
        stats = None
        for profile in profiles:
            if stats is None:
                stats = pstats.Stats(profile)
            else:
                stats.add(profile)
        if stats is not None:
            stats.sort_stats('cumulative').print_stats(30)

    @property
    def diagnostics(self):
        return {'output_queue_size': self._results_queue.qsize(),
                'ventilation_queue_size': self._ventilator_queue.qsize(),
                'ventilated_unprocessed': self._ventilated_unprocessed}

    @property
    def results_qsize(self):
        return self._results_queue.qsize()
