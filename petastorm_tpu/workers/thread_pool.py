"""In-process thread pool with ventilator feed and bounded results queue.

Parity: reference ``petastorm/workers_pool/thread_pool.py`` — per-worker
threads polling the ventilation queue (``thread_pool.py:61``), bounded
results queue with stop-aware put (``:200-214``), end-of-data detection
(queue empty AND all ventilated items processed AND ventilator completed,
``:155-160``), worker exceptions re-raised in the consumer (``:68-73``,
``:169-172``), and optional per-thread cProfile (``:48-49``, ``:190-198``).
"""

import pstats
import queue
import threading
from collections import deque

from petastorm_tpu.membudget import approx_nbytes, get_governor
from petastorm_tpu.utils import drain_queue
from petastorm_tpu.workers import (EmptyResultError, RowGroupQuarantined,
                                   TimeoutWaitingForResultError,
                                   VentilatedItemProcessedMessage,
                                   deliver_quarantine, quarantine_record_for)

_DEFAULT_RESULTS_QUEUE_SIZE = 50
#: Ventilation-queue bound when no ventilator declares a window (manual
#: ventilate() callers): far above any real in-flight cap, but no longer
#: the one genuinely unbounded cross-thread channel in the package —
#: start() re-sizes it down to the ventilator's actual window.
_DEFAULT_VENTILATION_QUEUE_SIZE = 1024
_VENTILATION_POLL_TIMEOUT_S = 0.001
_RESULTS_POLL_TIMEOUT_S = 0.01
#: Ventilation-queue headroom over the worker count after a live resize
#: (mirrors the reader's workers + extra in-flight convention).
_RESIZE_VENT_SLACK = 4


class _WorkerTerminationRequested(Exception):
    pass


class WorkerThread(threading.Thread):
    def __init__(self, pool, worker, profiling_enabled=False):
        super().__init__(daemon=True,
                         name='pst-pool-worker-{}'.format(worker.worker_id))
        self._pool = pool
        self._worker = worker
        self._profiling_enabled = profiling_enabled
        self.profile = None

    def run(self):
        if self._profiling_enabled:
            import cProfile
            self.profile = cProfile.Profile()
            try:
                self.profile.enable()
            except ValueError:
                # Python 3.12 allows one active profiler per thread; another
                # tool (e.g. an outer profiler on a reused thread) wins —
                # degrade to unprofiled rather than kill the worker.
                self.profile = None
        try:
            self._worker.initialize()
            while not self._pool._stop_event.is_set():
                # Retire check sits BETWEEN items only: a worker that has
                # already popped a ventilated item always processes it, so
                # a shrinking resize() can never drop work on the floor.
                if self._pool._should_retire(self):
                    return
                try:
                    args, kwargs = self._pool._ventilator_queue.get(
                        timeout=_VENTILATION_POLL_TIMEOUT_S)
                except queue.Empty:
                    continue
                try:
                    self._worker.process(*args, **kwargs)
                    self._pool._put_result(VentilatedItemProcessedMessage())
                except _WorkerTerminationRequested:
                    return
                except Exception as e:  # noqa: BLE001 - surfaces to consumer
                    record = quarantine_record_for(self._worker, e, args, kwargs)
                    self._pool._put_result(record if record is not None else e)
        except _WorkerTerminationRequested:
            return
        finally:
            if self._profiling_enabled and self.profile is not None:
                self.profile.disable()
            self._worker.shutdown()


class ThreadPool(object):
    def __init__(self, workers_count, results_queue_size=_DEFAULT_RESULTS_QUEUE_SIZE,
                 profiling_enabled=False):
        self._workers_count = workers_count
        self._results_queue = queue.Queue(maxsize=results_queue_size)
        self._ventilator_queue = queue.Queue(
            maxsize=_DEFAULT_VENTILATION_QUEUE_SIZE)
        self._stop_event = threading.Event()
        self._workers = []
        self._retired_workers = []
        self._ventilator = None
        self._profiling_enabled = profiling_enabled
        self._ventilated_unprocessed = 0
        self._count_lock = threading.Lock()
        # Live-resize state (autotune.py): the target count may differ from
        # len(_workers) while retire requests are pending.
        self._resize_lock = threading.Lock()
        self._retire_requests = 0
        self._next_worker_id = workers_count
        self._worker_class = None
        self._worker_args = None
        # Consumer-local drain buffer: get_results() moves every already-
        # ready result here under ONE queue-mutex acquisition instead of
        # paying a lock round trip per pop (the warm-cache chunk rate is
        # queue-pop bound — PROFILE_r05 §2). Touched only by the consumer
        # thread.
        self._pending_results = deque()
        #: Ventilator backpressure watermark: when set, the ventilator
        #: stops feeding new row-groups while the results queue holds this
        #: many items (bounding peak queue depth / decoded-block memory
        #: instead of racing ahead of a slow consumer). ``None`` = off.
        self.results_watermark = None
        self._results_peak = 0
        #: Set by the Reader when ``error_budget`` is enabled; receives
        #: RowGroupQuarantined records (and raises when the budget is spent).
        self.quarantine_sink = None
        #: Optional health.Heartbeat (set by ``Reader.attach_health``).
        self.health_heartbeat = None
        #: EMA of one published result's bytes (written by worker threads,
        #: racy float rebinds tolerated — it feeds an *estimate*): the
        #: memory governor's results-queue accounting is depth x this.
        self.result_nbytes_ema = 0.0
        #: Optional ``decode_budget.PoolShare`` (set by the Reader): this
        #: pool's registered stake in the process-wide native decode-
        #: thread budget. ``resize()`` re-divides it so every worker's
        #: next decode call sees the new fair share.
        self.decode_share = None

    @property
    def workers_count(self):
        return self._workers_count

    def start(self, worker_class, worker_args=None, ventilator=None):
        if self._workers:
            raise RuntimeError('ThreadPool already started')
        self._worker_class = worker_class
        self._worker_args = worker_args
        for worker_id in range(self._workers_count):
            self._spawn_worker(worker_id)
        self._ventilator = ventilator
        if ventilator is not None:
            # Size the ventilation queue from the ventilator's in-flight
            # window: the feeder caps outstanding items (queued + being
            # processed) at the window, so the queue can never legitimately
            # hold more — a tight bound that makes queued decode work a
            # *visible*, bounded quantity instead of an open-ended pile.
            # Rebuilt here (before ventilator.start(), so it is empty):
            # the window isn't known at construction. set_max_in_flight may
            # later raise the cap past this bound — ventilate()'s
            # stop-aware put then briefly backpressures the feeder instead
            # of deadlocking shutdown.
            window = getattr(ventilator, '_max_ventilation_queue_size', None)
            if window:
                self._ventilator_queue = queue.Queue(maxsize=max(1, int(window)))
            ventilator._ventilate_fn = self.ventilate
            if getattr(ventilator, 'backpressure_fn', None) is None:
                ventilator.backpressure_fn = self._results_backpressure
            ventilator.start()

    def _spawn_worker(self, worker_id):
        worker = self._worker_class(worker_id, self._put_result,
                                    self._worker_args)
        thread = WorkerThread(self, worker, self._profiling_enabled)
        with self._count_lock:
            self._workers.append(thread)
        thread.start()

    def resize(self, n):
        """Grow or shrink the live worker count to ``n`` (autotune hookup).

        Growing spawns fresh workers immediately; shrinking posts retire
        requests that workers honor **between** items — each request
        retires exactly one worker, and a worker that already popped work
        always finishes it first, so no ventilated item is ever lost or
        double-processed. Returns the new target count."""
        n = int(n)
        if n < 1:
            raise ValueError('workers_count must be >= 1, got {}'.format(n))
        with self._resize_lock:
            if self._worker_class is None:
                raise RuntimeError('ThreadPool.resize() requires a started pool')
            if self._stop_event.is_set():
                return self._workers_count
            with self._count_lock:
                delta = n - self._workers_count
                if delta == 0:
                    return n
                if delta < 0:
                    self._retire_requests += -delta
                    self._workers_count = n
                    if self.decode_share is not None:
                        # Shrinks widen the survivors' fair share on
                        # their next decode call.
                        self.decode_share.resize(n)
                    return n
                # Growing: outstanding retire requests are cancelled first —
                # resurrecting a not-yet-retired worker is cheaper than a
                # retire/spawn churn pair.
                cancelled = min(self._retire_requests, delta)
                self._retire_requests -= cancelled
                spawn = delta - cancelled
                self._workers_count = n
                worker_id = self._next_worker_id
                self._next_worker_id += spawn
            for i in range(spawn):
                self._spawn_worker(worker_id + i)
            # Grow the ventilation-queue bound with the pool: the reader's
            # resize hook raises the ventilator's in-flight cap to track
            # the worker count, and a queue still sized for the old window
            # would quietly re-backpressure the feeder to the old width.
            vent_queue = self._ventilator_queue
            with vent_queue.mutex:
                if vent_queue.maxsize and n + _RESIZE_VENT_SLACK > vent_queue.maxsize:
                    vent_queue.maxsize = n + _RESIZE_VENT_SLACK
                    vent_queue.not_full.notify_all()
            if self.decode_share is not None:
                # Re-divide the process decode-thread budget: N workers
                # each took total//old_n native threads per batch call;
                # the next call fair-shares against the new count.
                self.decode_share.resize(n)
            return n

    def _should_retire(self, thread):
        """Exactly-once retire claim (called by worker threads between
        items): consumes one pending retire request, moving the thread to
        the retired list so join() still reaps it."""
        if self._retire_requests <= 0:   # lock-free fast path: this check
            return False                 # runs every ventilation poll
        with self._count_lock:
            if self._retire_requests <= 0:
                return False
            self._retire_requests -= 1
            try:
                self._workers.remove(thread)
            except ValueError:  # pragma: no cover - stop/retire race
                pass
            self._retired_workers.append(thread)
            return True

    def _results_backpressure(self):
        """Ventilator saturation signal. ``None`` while no watermark is set
        (the signal is unarmed: the ventilator keeps its plain bursty
        feeding); with a watermark, True while undelivered results sit
        at/over it. Counts the consumer's drain buffer too — the bulk pop
        moves the whole queue there, and a watermark blind to it would
        release the moment the consumer took one result, while the full
        backlog still sits in memory."""
        watermark = self.results_watermark
        if watermark is None:
            return None
        return (self._results_queue.qsize()
                + len(self._pending_results)) >= watermark

    def ventilate(self, *args, **kwargs):
        with self._count_lock:
            self._ventilated_unprocessed += 1
        # Stop-aware bounded put (mirrors _put_result): the ventilation
        # queue is bounded now, and the feeder thread must never wedge
        # stop()/join() by blocking into a pool that is shutting down. An
        # item dropped at stop time must also retract its in-flight count
        # — _all_done() requires the counter to reach zero, and a leaked
        # +1 would spin a concurrently-stopping consumer forever.
        while True:
            if self._stop_event.is_set():
                with self._count_lock:
                    self._ventilated_unprocessed -= 1
                return
            try:
                self._ventilator_queue.put((args, kwargs),
                                           timeout=_RESULTS_POLL_TIMEOUT_S)
                return
            except queue.Full:
                continue

    def _put_result(self, data):
        # Stop-aware bounded put (parity: thread_pool.py:200-214): never block
        # forever on a full queue if the pool is being stopped.
        from petastorm_tpu.faults import maybe_inject
        maybe_inject('queue-stall')
        if not isinstance(data, VentilatedItemProcessedMessage):
            # Weighed only while a governor is armed: the size walk is
            # cheap but non-zero, and pipelines that never opt in must not
            # pay it per published chunk.
            if get_governor().armed:
                self.result_nbytes_ema += 0.25 * (approx_nbytes(data)
                                                  - self.result_nbytes_ema)
        while True:
            if self._stop_event.is_set():
                raise _WorkerTerminationRequested()
            try:
                self._results_queue.put(data, timeout=_RESULTS_POLL_TIMEOUT_S)
            except queue.Full:
                continue
            depth = (self._results_queue.qsize()
                     + len(self._pending_results))
            if depth > self._results_peak:   # racy double-check is fine: a
                with self._count_lock:       # lost update costs one sample
                    if depth > self._results_peak:
                        self._results_peak = depth
            return

    def inject_consumer_error(self, exc):
        """Watchdog delivery path: surface ``exc`` to a consumer parked in
        :meth:`get_results` (whose default timeout is unbounded). Unlike a
        worker exception, an injected error does NOT stop/join the pool —
        the very point is that a worker may be wedged and unjoinable; the
        caller owns teardown."""
        self._injected_error = exc

    _injected_error = None

    def _pop_result(self):
        """One result off the consumer-local drain buffer, refilled from
        the results queue in bulk: a single mutex acquisition moves a
        batch of already-ready items over (vs one lock round trip per
        pop), and producers blocked on the bounded put wake immediately
        for the freed capacity. The batch is capped at a quarter of the
        queue's capacity: every drained slot is capacity the workers
        refill, so an uncapped drain would let undelivered results reach
        ~2x the configured queue bound — the cap keeps the overshoot
        small while still amortizing the mutex. Raises ``queue.Empty`` on
        a dry poll."""
        if self._pending_results:
            return self._pending_results.popleft()
        result = self._results_queue.get(timeout=_RESULTS_POLL_TIMEOUT_S)
        drain_queue(self._results_queue, self._pending_results,
                    self._results_queue.maxsize // 4)
        return result

    def get_results(self, timeout=None):
        import time
        deadline = time.monotonic() + timeout if timeout is not None else None
        while True:
            if (self._injected_error is not None
                    and not self._pending_results
                    and self._results_queue.empty()):
                # Still no results: the diagnosed stall stands. (With
                # results available the pipeline recovered — deliver them
                # and drop the stale injection below.)
                error, self._injected_error = self._injected_error, None
                raise error
            if self.health_heartbeat is not None:
                self.health_heartbeat.beat('poll')
            try:
                result = self._pop_result()
            except queue.Empty:
                if self._all_done():
                    raise EmptyResultError()
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutWaitingForResultError()
                continue
            if isinstance(result, VentilatedItemProcessedMessage):
                with self._count_lock:
                    self._ventilated_unprocessed -= 1
                if self._ventilator is not None:
                    self._ventilator.processed_item()
                continue
            if isinstance(result, RowGroupQuarantined):
                # Quarantine counts as item-processed (the row-group is
                # skipped, not retried); the sink enforces the budget.
                with self._count_lock:
                    self._ventilated_unprocessed -= 1
                if self._ventilator is not None:
                    self._ventilator.processed_item()
                try:
                    deliver_quarantine(self, result)
                except Exception:
                    self.stop()
                    self.join()
                    raise
                continue
            if isinstance(result, Exception):
                self.stop()
                self.join()
                raise result
            self._injected_error = None   # results flow again: recovered
            return result

    def _all_done(self):
        # Order matters: observe `completed` FIRST. After it is set no further
        # ventilation can occur, so the subsequent counter/queue reads cannot
        # miss in-flight items (they only drain monotonically).
        ventilator_done = self._ventilator is None or self._ventilator.completed()
        if not ventilator_done:
            return False
        with self._count_lock:
            nothing_in_flight = self._ventilated_unprocessed == 0
        return (nothing_in_flight and not self._pending_results
                and self._results_queue.empty() and self._ventilator_queue.empty())

    def stop(self):
        if self._ventilator is not None:
            self._ventilator.stop()
        self._stop_event.set()

    def join(self):
        # The resize lock orders this snapshot after any in-flight
        # resize(): a grow that passed its stop check concurrently with
        # stop()/join() finishes spawning first, so its workers are in the
        # snapshot and get reaped — join() must never leave a thread
        # running against a store the owner is about to close.
        with self._resize_lock:
            with self._count_lock:
                threads = list(self._workers) + list(self._retired_workers)
        for thread in threads:
            thread.join()
        if self._profiling_enabled:
            self._print_profiles()
        self._workers = []
        self._retired_workers = []

    def _print_profiles(self):
        # A worker that never got ventilated work has an empty profile, which
        # pstats.Stats() rejects with TypeError — skip those.
        profiles = [t.profile for t in self._workers + self._retired_workers
                    if t.profile is not None and t.profile.getstats()]
        if not profiles:
            return
        stats = None
        for profile in profiles:
            if stats is None:
                stats = pstats.Stats(profile)
            else:
                stats.add(profile)
        if stats is not None:
            stats.sort_stats('cumulative').print_stats(30)

    @property
    def diagnostics(self):
        with self._count_lock:
            live = sum(1 for t in self._workers if t.is_alive())
        return {'output_queue_size': (self._results_queue.qsize()
                                      + len(self._pending_results)),
                'ventilation_queue_size': self._ventilator_queue.qsize(),
                'ventilated_unprocessed': self._ventilated_unprocessed,
                'workers_count': self._workers_count,
                'live_worker_threads': live,
                'results_queue_peak': self._results_peak,
                'results_watermark': self.results_watermark}

    @property
    def results_qsize(self):
        return self._results_queue.qsize() + len(self._pending_results)

    @property
    def results_capacity(self):
        return self._results_queue.maxsize

    def results_nbytes(self):
        """Estimated decoded bytes parked in the results queue (+ the
        consumer's drain buffer): depth x the published-result size EMA —
        the memory governor's ``results-queue`` accounting hook."""
        return int(self.results_qsize * self.result_nbytes_ema)
