"""Ventilator: backpressure-controlled work feeder.

Parity: reference ``petastorm/workers_pool/ventilator.py`` —
``Ventilator`` ABC (``:26-52``) and ``ConcurrentVentilator`` (``:55-166``):
runs on its own daemon thread, caps in-flight items at
``max_ventilation_queue_size``, optionally reshuffles item order every epoch,
``iterations=None`` means infinite epochs, and exposes the
``processed_item()`` / ``completed()`` / ``reset()`` protocol.

TPU-first improvement: shuffling is **seeded and reproducible**
(``random_seed``), unlike the reference's unseeded ``random.shuffle``
(``ventilator.py:143-144``) — determinism across pod hosts matters for
synchronized input pipelines (SURVEY.md §7 "Determinism across hosts").
"""

import hashlib
import random
import threading


class Ventilator(object):
    def __init__(self, ventilate_fn):
        self._ventilate_fn = ventilate_fn

    def start(self):
        raise NotImplementedError

    def processed_item(self):
        raise NotImplementedError

    def completed(self):
        raise NotImplementedError

    def reset(self):
        raise NotImplementedError

    def stop(self):
        raise NotImplementedError


class ConcurrentVentilator(Ventilator):
    def __init__(self, ventilate_fn, items_to_ventilate,
                 iterations=1, randomize_item_order=False,
                 random_seed=None,
                 max_ventilation_queue_size=None,
                 ventilation_interval=0.01,
                 inline=False,
                 backpressure_fn=None):
        """
        :param ventilate_fn: called with ``**item`` for each ventilated item.
        :param items_to_ventilate: list of dicts of kwargs.
        :param iterations: number of epochs; ``None`` = infinite.
        :param randomize_item_order: reshuffle before each epoch.
        :param random_seed: seed for reproducible shuffling (``None`` = os random).
        :param max_ventilation_queue_size: cap on unprocessed in-flight items;
            defaults to ``len(items_to_ventilate)``.
        :param backpressure_fn: optional saturation signal ``() -> None |
            bool``: ``None`` = unarmed (plain bursty feeding), ``True`` =
            hold ventilation even below the in-flight cap, ``False`` =
            armed but clear — feeding proceeds *paced* (one item per
            ``ventilation_interval`` or per ``processed_item()`` ack), so
            the signal gets to see each fed item's results land before the
            next feed; an unpaced burst would fill the whole in-flight
            window before any watermark could react. The worker pools wire
            this to a results-queue watermark so a saturated downstream
            stops new row-groups from being fed (bounding decoded-block
            memory and tail latency). Assignable after construction.
        :param inline: no ventilation thread — the consumer drives
            ventilation by calling :meth:`pump` (synchronous pools). A
            ventilator thread next to an inline pool is pure overhead: on a
            single-core host the GIL ping-pong between the feeder thread
            and the consumer measured ~50% of the whole per-row read path
            (round-4 profile, PROFILE_r04.md).
        """
        if iterations is not None and iterations <= 0:
            raise ValueError('iterations must be positive or None, got {}'.format(iterations))
        super().__init__(ventilate_fn)
        self._items_to_ventilate = list(items_to_ventilate)
        self._iterations = iterations
        self._iterations_remaining = iterations
        self._randomize_item_order = randomize_item_order
        self._rng = random.Random(random_seed)
        self._max_ventilation_queue_size = (max_ventilation_queue_size
                                            if max_ventilation_queue_size is not None
                                            else len(self._items_to_ventilate))
        self._ventilation_interval = ventilation_interval
        self.inline = inline
        self.backpressure_fn = backpressure_fn

        self._current_item_to_ventilate = 0
        self._in_flight = 0
        self._in_flight_lock = threading.Lock()
        # Batch provenance (petastorm_tpu.lineage): which epoch is being
        # fed and a digest of THIS epoch's item order — what pins "what
        # the shuffle chose" into ledgered batch records. epochs_started
        # counts feed epochs (1-based once start() ran).
        self.epochs_started = 0
        self._epoch_order_digest = None
        self._ventilation_thread = None
        self._started = False
        self._stop_event = threading.Event()
        self._wakeup = threading.Event()
        self._completed_flag = threading.Event()
        #: Optional :class:`petastorm_tpu.health.Heartbeat` (set by
        #: ``Reader.attach_health``): beaten every feeder-loop iteration so
        #: the watchdog can prove the ventilation thread itself is alive
        #: (state 'ventilating' / 'backpressure' / 'idle' once done).
        self.heartbeat = None
        #: Optional observer ``(item_dict) -> None`` called just before an
        #: item is fed to the pool — i.e. in exact dispatch order,
        #: ``max_ventilation_queue_size`` items ahead of the workers. The
        #: reader wires the NVMe chunk store's madvise/WILLNEED readahead
        #: here so the next scheduled row-group's extents are page-cache
        #: resident before a worker touches them. Must be cheap and must
        #: not raise (exceptions are swallowed: advice, not work).
        self.on_ventilate = None

    def start(self):
        if self._started:
            raise RuntimeError('Ventilator already started')
        self._started = True
        if not self._items_to_ventilate or (self._iterations is not None and self._iterations == 0):
            self._completed_flag.set()
            return
        if self._randomize_item_order:
            self._rng.shuffle(self._items_to_ventilate)
        self._on_epoch_order()
        if self.inline:
            return
        self._ventilation_thread = threading.Thread(target=self._ventilate, daemon=True)
        self._ventilation_thread.start()

    def _advance_epoch(self):
        """At the end of an item list, roll to the next epoch (reshuffling)
        or mark completion. Returns False when all iterations are done."""
        if self._current_item_to_ventilate >= len(self._items_to_ventilate):
            if self._iterations_remaining is not None:
                self._iterations_remaining -= 1
                if self._iterations_remaining <= 0:
                    self._completed_flag.set()
                    return False
            self._current_item_to_ventilate = 0
            if self._randomize_item_order:
                self._rng.shuffle(self._items_to_ventilate)
            self._on_epoch_order()
        return True

    def _on_epoch_order(self):
        """A new epoch's item order is fixed: bump the epoch counter and
        invalidate the order-digest memo. The digest itself (by each
        item's JSON-safe identity keys — what lets the provenance ledger
        prove two runs claiming the same seed fed identically) is O(items)
        and only ever read by lineage probes, so it is computed lazily on
        first probe rather than stalling every epoch roll for pipelines
        that never arm lineage."""
        self.epochs_started += 1
        self._epoch_order_digest = None

    def lineage_state(self):
        """``{'epoch', 'order_digest', 'position'}`` — the live shuffle
        state stamped into provenance records (advisory near epoch rolls:
        a multi-worker pool interleaves chunks across the boundary, and a
        roll may invalidate the memo mid-probe)."""
        epoch = self.epochs_started
        memo = self._epoch_order_digest
        if memo is None or memo[0] != epoch:
            digest = hashlib.md5()
            for index, item in enumerate(self._items_to_ventilate):
                identity = (item.get('piece_index', index),
                            item.get('shuffle_row_drop_partition')) \
                    if isinstance(item, dict) else index
                digest.update(repr(identity).encode())
            memo = (epoch, digest.hexdigest()[:12])
            self._epoch_order_digest = memo
        return {'epoch': epoch,
                'order_digest': memo[1],
                'position': self._current_item_to_ventilate}

    def _backpressured(self):
        """Tri-state sample of the saturation signal: ``None`` = no signal
        armed (no fn, fn says unarmed, or fn died), ``False`` = armed but
        clear, ``True`` = hold ventilation. Armed-but-clear still matters:
        it selects paced feeding (see ``_ventilate``)."""
        fn = self.backpressure_fn
        if fn is None:
            return None
        try:
            value = fn()
        except Exception:  # noqa: BLE001 - a dying probe must not stop feeding
            return None
        return None if value is None else bool(value)

    def pump(self):
        """Inline mode: ventilate items up to the backpressure cap from the
        CALLING thread. Returns the number of items ventilated."""
        assert self.inline, 'pump() is for inline ventilators'
        pumped = 0
        while (not self._stop_event.is_set()
               and not self._completed_flag.is_set()):
            if self.heartbeat is not None:
                self.heartbeat.beat('ventilating')
            if self._in_flight >= self._max_ventilation_queue_size:
                break
            if self._backpressured():
                break
            if not self._advance_epoch():
                break
            item = self._items_to_ventilate[self._current_item_to_ventilate]
            self._current_item_to_ventilate += 1
            self._in_flight += 1   # single-threaded: no lock needed
            self._observe(item)
            self._ventilate_fn(**item)
            pumped += 1
        return pumped

    def _observe(self, item):
        observer = self.on_ventilate
        if observer is not None:
            try:
                observer(item)
            except Exception:  # noqa: BLE001 - advisory hook must not stop feeding
                pass

    def _ventilate(self):
        while not self._stop_event.is_set():
            heartbeat = self.heartbeat
            if not self._advance_epoch():
                if heartbeat is not None:
                    heartbeat.beat('idle')   # all epochs fed: quiet != stalled
                return
            with self._in_flight_lock:
                below_cap = self._in_flight < self._max_ventilation_queue_size
            backpressure = self._backpressured() if below_cap else None
            if below_cap and not backpressure:
                if heartbeat is not None:
                    heartbeat.beat('ventilating')
                item = self._items_to_ventilate[self._current_item_to_ventilate]
                self._current_item_to_ventilate += 1
                with self._in_flight_lock:
                    self._in_flight += 1
                self._observe(item)
                self._ventilate_fn(**item)
                if backpressure is not None:
                    # Paced feeding while a saturation signal is ARMED
                    # (even when currently clear): the just-fed item's
                    # results haven't landed yet, so an unpaced loop would
                    # fill the whole in-flight window before the signal
                    # could react — a cap-sized result burst the watermark
                    # exists to prevent. One item per interval, or per
                    # consumer ack (processed_item() sets the wakeup),
                    # whichever comes sooner.
                    self._wakeup.clear()
                    self._wakeup.wait(self._ventilation_interval)
            else:
                if heartbeat is not None:
                    heartbeat.beat('backpressure')
                self._wakeup.wait(self._ventilation_interval)
                self._wakeup.clear()

    def processed_item(self):
        with self._in_flight_lock:
            self._in_flight = max(0, self._in_flight - 1)
        self._wakeup.set()

    def set_max_in_flight(self, n):
        """Retarget the in-flight cap at runtime (autotune hookup: the cap
        tracks the resized worker count). A raised cap wakes a parked
        feeder immediately; a lowered one simply stops new ventilation
        until in-flight items drain below it."""
        self._max_ventilation_queue_size = max(1, int(n))
        self._wakeup.set()

    def completed(self):
        return self._completed_flag.is_set()

    def reset(self):
        """Restart ventilation for another round of `iterations` epochs.

        Parity: reference ``ventilator.py:118-134`` (used by ``Reader.reset()``).
        """
        if self._ventilation_thread is not None:
            if self._completed_flag.is_set():
                # Completed but possibly still in final teardown — wait it out
                # rather than spuriously refusing the reset.
                self._ventilation_thread.join()
            elif self._ventilation_thread.is_alive():
                raise RuntimeError('Cannot reset a ventilator that is still ventilating')
        elif self._started and self.inline and not self._completed_flag.is_set():
            raise RuntimeError('Cannot reset a ventilator that is still ventilating')
        self._ventilation_thread = None
        self._started = False
        self._iterations_remaining = self._iterations
        self._current_item_to_ventilate = 0
        with self._in_flight_lock:
            self._in_flight = 0
        self._completed_flag.clear()
        self._stop_event.clear()
        self.start()

    def stop(self):
        self._stop_event.set()
        self._wakeup.set()
        if self._ventilation_thread is not None:
            self._ventilation_thread.join()
            self._ventilation_thread = None
