"""Ventilator: backpressure-controlled work feeder.

Parity: reference ``petastorm/workers_pool/ventilator.py`` —
``Ventilator`` ABC (``:26-52``) and ``ConcurrentVentilator`` (``:55-166``):
runs on its own daemon thread, caps in-flight items at
``max_ventilation_queue_size``, optionally reshuffles item order every epoch,
``iterations=None`` means infinite epochs, and exposes the
``processed_item()`` / ``completed()`` / ``reset()`` protocol.

TPU-first improvement: shuffling is **seeded and reproducible**
(``random_seed``), unlike the reference's unseeded ``random.shuffle``
(``ventilator.py:143-144``) — determinism across pod hosts matters for
synchronized input pipelines (SURVEY.md §7 "Determinism across hosts").

Deterministic mode (``deterministic=`` dict, armed by ``Reader`` when built
with ``deterministic=True``) goes further: the stateful ``random.Random``
epoch shuffle is replaced by the counter-based Feistel permutation of
``petastorm_tpu.determinism`` keyed by ``(seed, epoch)`` — epoch order is a
pure function of scalars, so any process recomputes it and resume
*fast-forwards* to a cursor position instead of replaying RNG history. Each
fed item additionally carries a ``pst_det`` tag (host-local ``seq`` for the
consumer-side resequencer, absolute ``epoch`` and global ``pos`` for the
stream cursor), and ``cur_shard``/``shard_count`` is applied here as a
stride over the *global* order — the reshard-invariance mechanism (see the
``determinism`` module docstring).
"""

import hashlib
import random
import threading

from petastorm_tpu import determinism


class Ventilator(object):
    def __init__(self, ventilate_fn):
        self._ventilate_fn = ventilate_fn

    def start(self):
        raise NotImplementedError

    def processed_item(self):
        raise NotImplementedError

    def completed(self):
        raise NotImplementedError

    def reset(self):
        raise NotImplementedError

    def stop(self):
        raise NotImplementedError


class ConcurrentVentilator(Ventilator):
    def __init__(self, ventilate_fn, items_to_ventilate,
                 iterations=1, randomize_item_order=False,
                 random_seed=None,
                 max_ventilation_queue_size=None,
                 ventilation_interval=0.01,
                 inline=False,
                 backpressure_fn=None,
                 deterministic=None):
        """
        :param ventilate_fn: called with ``**item`` for each ventilated item.
        :param items_to_ventilate: list of dicts of kwargs.
        :param iterations: number of epochs; ``None`` = infinite.
        :param randomize_item_order: reshuffle before each epoch.
        :param random_seed: seed for reproducible shuffling (``None`` = os random).
        :param max_ventilation_queue_size: cap on unprocessed in-flight items;
            defaults to ``len(items_to_ventilate)``.
        :param backpressure_fn: optional saturation signal ``() -> None |
            bool``: ``None`` = unarmed (plain bursty feeding), ``True`` =
            hold ventilation even below the in-flight cap, ``False`` =
            armed but clear — feeding proceeds *paced* (one item per
            ``ventilation_interval`` or per ``processed_item()`` ack), so
            the signal gets to see each fed item's results land before the
            next feed; an unpaced burst would fill the whole in-flight
            window before any watermark could react. The worker pools wire
            this to a results-queue watermark so a saturated downstream
            stops new row-groups from being fed (bounding decoded-block
            memory and tail latency). Assignable after construction.
        :param deterministic: ``None`` (default, classic seeded shuffle) or
            a dict ``{'seed', 'cur_shard', 'shard_count', 'start_epoch',
            'start_pos'}`` arming seed-stable deterministic feeding: epoch
            order comes from the counter-based Feistel permutation
            (``determinism.epoch_order``), sharding is a stride over the
            global order, ``start_epoch``/``start_pos`` fast-forward to a
            resume cursor, and every fed item gains a ``pst_det`` tag
            (``seq``/``epoch``/``pos``) the workers echo on published
            chunks for the consumer-side resequencer.
        :param inline: no ventilation thread — the consumer drives
            ventilation by calling :meth:`pump` (synchronous pools). A
            ventilator thread next to an inline pool is pure overhead: on a
            single-core host the GIL ping-pong between the feeder thread
            and the consumer measured ~50% of the whole per-row read path
            (round-4 profile, PROFILE_r04.md).
        """
        if iterations is not None and iterations <= 0:
            raise ValueError('iterations must be positive or None, got {}'.format(iterations))
        super().__init__(ventilate_fn)
        self._items_to_ventilate = list(items_to_ventilate)
        self._iterations = iterations
        self._iterations_remaining = iterations
        self._randomize_item_order = randomize_item_order
        self._rng = random.Random(random_seed)
        self._max_ventilation_queue_size = (max_ventilation_queue_size
                                            if max_ventilation_queue_size is not None
                                            else len(self._items_to_ventilate))
        self._ventilation_interval = ventilation_interval
        self.inline = inline
        self.backpressure_fn = backpressure_fn

        # Deterministic mode (petastorm_tpu.determinism): epoch order is
        # the counter-based Feistel permutation, sharding is a stride over
        # the global order, and every fed item carries a pst_det tag.
        self._det = dict(deterministic) if deterministic is not None else None
        self._det_epoch = 0          # absolute epoch being fed (1-based)
        self._det_order = None       # epoch_order(...) of the current epoch
        self._det_positions = None   # this shard's global positions
        self._det_epoch_base = 0     # resume base of the current epoch
        self._det_phase = 0          # round-robin offset from earlier epochs
        self._det_seq = 0            # host-local seq (resequencer ordering)

        self._current_item_to_ventilate = 0
        self._in_flight = 0
        self._in_flight_lock = threading.Lock()
        # Batch provenance (petastorm_tpu.lineage): which epoch is being
        # fed and a digest of THIS epoch's item order — what pins "what
        # the shuffle chose" into ledgered batch records. epochs_started
        # counts feed epochs (1-based once start() ran).
        self.epochs_started = 0
        self._epoch_order_digest = None
        self._ventilation_thread = None
        self._started = False
        self._stop_event = threading.Event()
        self._wakeup = threading.Event()
        self._completed_flag = threading.Event()
        #: Optional :class:`petastorm_tpu.health.Heartbeat` (set by
        #: ``Reader.attach_health``): beaten every feeder-loop iteration so
        #: the watchdog can prove the ventilation thread itself is alive
        #: (state 'ventilating' / 'backpressure' / 'idle' once done).
        self.heartbeat = None
        #: Optional observer ``(item_dict) -> None`` called just before an
        #: item is fed to the pool — i.e. in exact dispatch order,
        #: ``max_ventilation_queue_size`` items ahead of the workers. The
        #: reader wires the NVMe chunk store's madvise/WILLNEED readahead
        #: here so the next scheduled row-group's extents are page-cache
        #: resident before a worker touches them. Must be cheap and must
        #: not raise (exceptions are swallowed: advice, not work).
        self.on_ventilate = None

    def start(self):
        if self._started:
            raise RuntimeError('Ventilator already started')
        self._started = True
        if not self._items_to_ventilate or (self._iterations is not None and self._iterations == 0):
            self._completed_flag.set()
            return
        if self._det is not None:
            if not self._det_start():
                # The resume cursor already sits past the final epoch.
                self._completed_flag.set()
                return
        elif self._randomize_item_order:
            self._rng.shuffle(self._items_to_ventilate)
        self._on_epoch_order()
        if self.inline:
            return
        self._ventilation_thread = threading.Thread(target=self._ventilate, daemon=True,
                                                    name='pst-ventilator')
        self._ventilation_thread.start()

    def _det_start(self):
        """Position the deterministic feed at the resume cursor. False
        when the cursor's epoch already exhausted a finite iteration
        budget (nothing left to feed)."""
        det = self._det
        start_epoch = max(1, int(det.get('start_epoch') or 1))
        if self._iterations is not None:
            self._iterations_remaining = self._iterations - (start_epoch - 1)
            if self._iterations_remaining <= 0:
                return False
        self._det_seq = 0
        self._det_epoch_setup(start_epoch, int(det.get('start_pos') or 0),
                              phase=0)
        return True

    def _det_epoch_setup(self, epoch, base, phase):
        """Fix one epoch's deterministic feed plan: the full permuted
        order (recomputed from scalars — O(items), comparable to the
        classic mode's Fisher-Yates shuffle) and this shard's stride
        positions over it. ``phase`` carries the round-robin offset
        accumulated by earlier epochs (see ``determinism.shard_positions``)
        so host assignment stays continuous across epoch rolls."""
        det = self._det
        n = len(self._items_to_ventilate)
        self._det_epoch = epoch
        self._det_epoch_base = base
        self._det_phase = phase
        self._det_order = determinism.epoch_order(
            n, det.get('seed'), epoch, shuffle=det.get('shuffle', True))
        self._det_positions = determinism.shard_positions(
            n, base, det.get('cur_shard') or 0, det.get('shard_count') or 1,
            phase=phase)

    def _epoch_items(self):
        """How many items this feeder ventilates in the current epoch."""
        return (len(self._det_positions) if self._det is not None
                else len(self._items_to_ventilate))

    def _next_item(self):
        """The next item to feed (advancing the epoch position). In
        deterministic mode the canonical item is resolved through the
        epoch permutation and tagged with its ``pst_det`` identity."""
        i = self._current_item_to_ventilate
        self._current_item_to_ventilate += 1
        if self._det is None:
            return self._items_to_ventilate[i]
        pos = self._det_positions[i]
        item = dict(self._items_to_ventilate[self._det_order[pos]])
        item['pst_det'] = {'seq': self._det_seq,
                           'epoch': self._det_epoch,
                           'pos': pos}
        self._det_seq += 1
        return item

    def _advance_epoch(self):
        """At the end of an item list, roll to the next epoch (reshuffling)
        or mark completion. Returns False when all iterations are done.
        A ``while`` (not ``if``): a deterministic shard whose stride got
        no positions in the resume epoch (cursor near the epoch's end)
        rolls straight through to the next epoch."""
        while self._current_item_to_ventilate >= self._epoch_items():
            if self._iterations_remaining is not None:
                self._iterations_remaining -= 1
                if self._iterations_remaining <= 0:
                    self._completed_flag.set()
                    return False
            self._current_item_to_ventilate = 0
            if self._det is not None:
                # Advance the stride phase by the positions ALL hosts fed
                # in the finished epoch, keeping the global round-robin
                # continuous across the roll (an epoch length that is not
                # a multiple of shard_count would otherwise desync hosts).
                n = len(self._items_to_ventilate)
                shard_count = self._det.get('shard_count') or 1
                phase = (self._det_phase
                         + n - self._det_epoch_base) % shard_count
                self._det_epoch_setup(self._det_epoch + 1, 0, phase)
            elif self._randomize_item_order:
                self._rng.shuffle(self._items_to_ventilate)
            self._on_epoch_order()
        return True

    def _on_epoch_order(self):
        """A new epoch's item order is fixed: bump the epoch counter and
        invalidate the order-digest memo. The digest itself (by each
        item's JSON-safe identity keys — what lets the provenance ledger
        prove two runs claiming the same seed fed identically) is O(items)
        and only ever read by lineage probes, so it is computed lazily on
        first probe rather than stalling every epoch roll for pipelines
        that never arm lineage."""
        if self._det is not None:
            # Deterministic epochs are absolute (resume fast-forwards past
            # prior sessions' epochs without replaying them).
            self.epochs_started = self._det_epoch
        else:
            self.epochs_started += 1
        self._epoch_order_digest = None

    def lineage_state(self):
        """``{'epoch', 'order_digest', 'position'}`` — the live shuffle
        state stamped into provenance records (advisory near epoch rolls:
        a multi-worker pool interleaves chunks across the boundary, and a
        roll may invalidate the memo mid-probe)."""
        epoch = self.epochs_started
        memo = self._epoch_order_digest
        if memo is None or memo[0] != epoch:
            if self._det is not None:
                # The fed order is the epoch permutation, not the list
                # order — digest what actually feeds, so two hosts of one
                # deterministic job (and a resumed session) agree.
                value = determinism.order_digest(self._items_to_ventilate,
                                                 self._det_order)
            else:
                digest = hashlib.md5()
                for index, item in enumerate(self._items_to_ventilate):
                    identity = (item.get('piece_index', index),
                                item.get('shuffle_row_drop_partition')) \
                        if isinstance(item, dict) else index
                    digest.update(repr(identity).encode())
                value = digest.hexdigest()[:12]
            memo = (epoch, value)
            self._epoch_order_digest = memo
        return {'epoch': epoch,
                'order_digest': memo[1],
                'position': self._current_item_to_ventilate}

    def _backpressured(self):
        """Tri-state sample of the saturation signal: ``None`` = no signal
        armed (no fn, fn says unarmed, or fn died), ``False`` = armed but
        clear, ``True`` = hold ventilation. Armed-but-clear still matters:
        it selects paced feeding (see ``_ventilate``)."""
        fn = self.backpressure_fn
        if fn is None:
            return None
        try:
            value = fn()
        except Exception:  # noqa: BLE001 - a dying probe must not stop feeding
            return None
        return None if value is None else bool(value)

    def pump(self):
        """Inline mode: ventilate items up to the backpressure cap from the
        CALLING thread. Returns the number of items ventilated."""
        assert self.inline, 'pump() is for inline ventilators'
        pumped = 0
        while (not self._stop_event.is_set()
               and not self._completed_flag.is_set()):
            if self.heartbeat is not None:
                self.heartbeat.beat('ventilating')
            if self._in_flight >= self._max_ventilation_queue_size:
                break
            if self._backpressured():
                break
            if not self._advance_epoch():
                break
            item = self._next_item()
            self._in_flight += 1   # single-threaded: no lock needed
            self._observe(item)
            self._ventilate_fn(**item)
            pumped += 1
        return pumped

    def _observe(self, item):
        observer = self.on_ventilate
        if observer is not None:
            try:
                observer(item)
            except Exception:  # noqa: BLE001 - advisory hook must not stop feeding
                pass

    def _ventilate(self):
        while not self._stop_event.is_set():
            heartbeat = self.heartbeat
            if not self._advance_epoch():
                if heartbeat is not None:
                    heartbeat.beat('idle')   # all epochs fed: quiet != stalled
                return
            with self._in_flight_lock:
                below_cap = self._in_flight < self._max_ventilation_queue_size
            backpressure = self._backpressured() if below_cap else None
            if below_cap and not backpressure:
                if heartbeat is not None:
                    heartbeat.beat('ventilating')
                item = self._next_item()
                with self._in_flight_lock:
                    self._in_flight += 1
                self._observe(item)
                self._ventilate_fn(**item)
                if backpressure is not None:
                    # Paced feeding while a saturation signal is ARMED
                    # (even when currently clear): the just-fed item's
                    # results haven't landed yet, so an unpaced loop would
                    # fill the whole in-flight window before the signal
                    # could react — a cap-sized result burst the watermark
                    # exists to prevent. One item per interval, or per
                    # consumer ack (processed_item() sets the wakeup),
                    # whichever comes sooner.
                    self._wakeup.clear()
                    self._wakeup.wait(self._ventilation_interval)
            else:
                if heartbeat is not None:
                    heartbeat.beat('backpressure')
                self._wakeup.wait(self._ventilation_interval)
                self._wakeup.clear()

    def processed_item(self):
        with self._in_flight_lock:
            self._in_flight = max(0, self._in_flight - 1)
        self._wakeup.set()

    def set_max_in_flight(self, n):
        """Retarget the in-flight cap at runtime (autotune hookup: the cap
        tracks the resized worker count). A raised cap wakes a parked
        feeder immediately; a lowered one simply stops new ventilation
        until in-flight items drain below it."""
        self._max_ventilation_queue_size = max(1, int(n))
        self._wakeup.set()

    def completed(self):
        return self._completed_flag.is_set()

    def reset(self):
        """Restart ventilation for another round of `iterations` epochs.

        Parity: reference ``ventilator.py:118-134`` (used by ``Reader.reset()``).
        """
        if self._ventilation_thread is not None:
            if self._completed_flag.is_set():
                # Completed but possibly still in final teardown — wait it out
                # rather than spuriously refusing the reset.
                self._ventilation_thread.join()
            elif self._ventilation_thread.is_alive():
                raise RuntimeError('Cannot reset a ventilator that is still ventilating')
        elif self._started and self.inline and not self._completed_flag.is_set():
            raise RuntimeError('Cannot reset a ventilator that is still ventilating')
        self._ventilation_thread = None
        self._started = False
        self._iterations_remaining = self._iterations
        self._current_item_to_ventilate = 0
        if self._det is not None:
            # A reset is a fresh round: the resume cursor was consumed by
            # the first start. Re-applying it here would replay only the
            # prior session's tail (and nothing at all for a cursor
            # normalized past the final epoch) instead of `iterations`
            # full epochs, unlike a default-mode reset.
            self._det['start_epoch'] = 1
            self._det['start_pos'] = 0
        with self._in_flight_lock:
            self._in_flight = 0
        self._completed_flag.clear()
        self._stop_event.clear()
        self.start()

    def stop(self):
        self._stop_event.set()
        self._wakeup.set()
        if self._ventilation_thread is not None:
            self._ventilation_thread.join()
            self._ventilation_thread = None
