"""Cross-process payload serializers.

Parity: reference ``petastorm/reader_impl/{pickle,pyarrow,arrow_table}_serializer.py``.
(``pyarrow.serialize`` is long removed from Arrow, so the Arrow path here is the
IPC record-batch stream, matching ``arrow_table_serializer.py:18-33``.)
"""

import pickle

import pyarrow as pa


class PickleSerializer(object):
    def serialize(self, rows):
        return pickle.dumps(rows, protocol=pickle.HIGHEST_PROTOCOL)

    def deserialize(self, blob):
        return pickle.loads(blob)


class ArrowTableSerializer(object):
    """Serializes ``pa.Table`` via the Arrow IPC stream format (zero pickle)."""

    def serialize(self, table):
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, table.schema) as writer:
            writer.write_table(table)
        return sink.getvalue().to_pybytes()

    def deserialize(self, blob):
        with pa.ipc.open_stream(pa.BufferReader(blob)) as reader:
            return reader.read_all()
