"""Out-of-process worker pool over the native shared-memory ring transport.

Same protocol and consumer semantics as :class:`ProcessPool` (the reference's
ZeroMQ design, ``workers_pool/process_pool.py:52-74``) but the worker <->
consumer channels are mmap'd SPSC rings (``native/src/shm_ring.cc``): no
sockets, no syscalls on the steady-state path, single memcpy per message.

Channel layout per worker i (generation g — bumped on every respawn):
  work ring  ``/pst_<pid>_<uid>_i_g<g>_in``   parent -> worker, pickled
             (seq, args, kwargs)
  result ring ``/pst_<pid>_<uid>_i_g<g>_out`` worker -> parent, 1-byte tag +
             payload
    tag b'C': pickled control (started / item-processed / quarantine / error)
    tag b'S': two little-endian int64s — (item seq, chunk index) of the data
              payload that follows (separate tiny message so large payloads
              need no re-copy; seq -1 = untagged publish)
    tag b'D': serializer payload (row-group data), possibly final chunk
    tag b'P': non-final chunk of a payload larger than half the ring
              (chunks are contiguous per ring — SPSC ordering — so the
              consumer reassembles per-ring; no message size limit)

FINISHED broadcast = setting the control flag word on both rings; blocked ring
writes abort with RingClosed so shutdown can't deadlock on a full ring
(the reference needs an explicit drain loop for this, ``process_pool.py:287-304``).

Worker supervision mirrors :class:`ProcessPool` (see ``supervision.py``):
round-robin dispatch with known assignment, dead-worker detection inside
``get_results``, respawn-with-fresh-rings within ``max_worker_restarts``,
re-ventilation of the dead worker's in-flight items, and seq-based duplicate
suppression. On a death the old result ring is drained first — complete
messages the dead worker managed to publish are preserved (and their acks
processed) before the ring is discarded, which keeps delivery exactly-once.
All ring writes happen on the consumer thread (ventilation goes through
pending queues) so respawn can swap rings without racing the ventilator.
"""

import logging
import os
import pickle
import struct
import threading
import time
import uuid
from collections import deque

import dill

from petastorm_tpu.workers import (EmptyResultError, RowGroupQuarantined,
                                   TimeoutWaitingForResultError,
                                   VentilatedItemProcessedMessage)
from petastorm_tpu.workers.exec_in_new_process import exec_in_new_process
from petastorm_tpu.workers.process_pool import (_run_worker_item,
                                                _start_orphan_watchdog,
                                                _WorkerError)
from petastorm_tpu.workers.serializers import PickleSerializer
from petastorm_tpu.workers.supervision import (DEFAULT_MAX_WORKER_RESTARTS,
                                               InFlightRegistry,
                                               SupervisedPoolMixin)

logger = logging.getLogger(__name__)

_WORKER_STARTED = '__worker_started__'
_FLAG_FINISHED = 1
_TAG_CONTROL = b'C'
_TAG_SEQ = b'S'
_TAG_DATA = b'D'
_TAG_PARTIAL = b'P'  # chunk of an oversized data payload; 'D' terminates it
_DEFAULT_TIMEOUT_S = 60
_STARTUP_TIMEOUT_S = 120
_WORK_RING_BYTES = 1 << 20          # pickled work items are tiny
_DEFAULT_RESULT_RING_BYTES = 64 << 20


def shm_transport_available():
    from petastorm_tpu.native import shm_ring
    return shm_ring.available()


def _ring_names(base, worker_id, generation):
    prefix = '{}_{}_g{}'.format(base, worker_id, generation)
    return prefix + '_in', prefix + '_out'


class ShmProcessPool(SupervisedPoolMixin):
    """Drop-in alternative to ProcessPool; rings instead of zmq sockets.

    :param result_ring_bytes: per-worker results ring capacity. Decoded
        row-groups must fit in half of this (ring message limit).
    :param max_worker_restarts: total worker respawns tolerated before a
        further death raises :class:`~petastorm_tpu.errors.WorkerLostError`.
    """

    _pool_kind = 'Shm worker'

    def __init__(self, workers_count, results_queue_size=50, serializer=None,
                 result_ring_bytes=_DEFAULT_RESULT_RING_BYTES,
                 max_worker_restarts=DEFAULT_MAX_WORKER_RESTARTS):
        self._workers_count = workers_count
        self._serializer = serializer or PickleSerializer()
        self._result_ring_bytes = result_ring_bytes
        self._init_supervision(max_worker_restarts)
        del results_queue_size  # bounded by ring bytes, not message count

        self._base = None
        self._generations = []
        self._work_rings = []
        self._result_rings = []
        self._pending_sends = []
        self._send_lock = threading.Lock()
        self._processes = []
        self._worker_class = None
        self._worker_args = None
        self._ventilator = None
        self._ventilated_unprocessed = 0
        self._count_lock = threading.Lock()
        self._stopped = False
        self._poll_cursor = 0
        self._partials = {}   # slot -> accumulated 'P' chunks
        self._ring_seq = {}   # slot -> announced (seq, chunk_idx) of the next 'D'
        self._drained = deque()  # messages rescued off dead workers' rings
        self._registry = None
        #: Set by the Reader when ``error_budget`` is enabled.
        self.quarantine_sink = None
        #: Optional health.Heartbeat (set by ``Reader.attach_health``):
        #: beaten each ``get_results`` poll ('poll') and on every delivered
        #: payload ('deliver') — proves the pump is alive and flowing.
        self.health_heartbeat = None

    @property
    def workers_count(self):
        return self._workers_count

    def start(self, worker_class, worker_args=None, ventilator=None):
        from petastorm_tpu.native.shm_ring import ShmRing

        if self._processes:
            raise RuntimeError('ShmProcessPool already started')
        self._worker_class = worker_class
        self._worker_args = worker_args
        self._registry = InFlightRegistry(self._workers_count)
        self._base = '/pst_{}_{}'.format(os.getpid(), uuid.uuid4().hex[:8])
        self._generations = [0] * self._workers_count
        for worker_id in range(self._workers_count):
            in_name, out_name = _ring_names(self._base, worker_id, 0)
            self._work_rings.append(ShmRing.create(in_name, _WORK_RING_BYTES))
            self._result_rings.append(
                ShmRing.create(out_name, self._result_ring_bytes))
            self._pending_sends.append([])
        for worker_id in range(self._workers_count):
            self._processes.append(self._spawn_worker(worker_id))

        started = 0
        deadline = time.monotonic() + _STARTUP_TIMEOUT_S
        while started < self._workers_count:
            if time.monotonic() > deadline:
                self.stop()
                raise RuntimeError(
                    'Timed out waiting for {} shm workers to start ({} started)'.format(
                        self._workers_count, started))
            message = self._poll_once(timeout_ms=1000)
            if message is None:
                self._check_workers_alive()
                continue
            if message[0] == 'control':
                control = pickle.loads(message[1])
                if control == _WORKER_STARTED:
                    started += 1
                elif isinstance(control, _WorkerError):
                    self.stop()
                    self.join()
                    raise control.exception

        self._ventilator = ventilator
        if ventilator is not None:
            ventilator._ventilate_fn = self.ventilate
            ventilator.start()

    def _spawn_worker(self, worker_id):
        in_name, out_name = _ring_names(self._base, worker_id,
                                        self._generations[worker_id])
        return exec_in_new_process(
            _shm_worker_bootstrap, self._worker_class, worker_id,
            self._worker_args, in_name, out_name, type(self._serializer),
            os.getpid())

    def _check_workers_alive(self):
        dead = [p.pid for p in self._processes if p.poll() is not None]
        if dead:
            self.stop()
            raise RuntimeError('shm worker process(es) {} died during startup'.format(dead))

    def ventilate(self, *args, **kwargs):
        with self._count_lock:
            self._ventilated_unprocessed += 1
        seq, slot = self._registry.assign((args, kwargs))
        # dill: work items may close over lambdas (predicates/transforms).
        # No ring write here — rings are SPSC and belong to the consumer
        # thread (which swaps them on respawn); it flushes pending sends on
        # every poll iteration.
        self._enqueue_work(slot, dill.dumps((seq, args, kwargs)))

    def _enqueue_work(self, slot, payload):
        with self._send_lock:
            self._pending_sends[slot].append(payload)

    def _flush_pending(self):
        """Consumer-thread-only: push queued work onto the work rings."""
        from petastorm_tpu.native.shm_ring import RingClosed, RingTimeout

        for slot, ring in enumerate(self._work_rings):
            while True:
                with self._send_lock:
                    if not self._pending_sends[slot]:
                        break
                    payload = self._pending_sends[slot][0]
                try:
                    ring.write(payload, timeout_ms=0)
                except (RingTimeout, RingClosed):
                    break  # full ring or tearing down; retry next iteration
                with self._send_lock:
                    self._pending_sends[slot].pop(0)

    # --- result-ring reading ----------------------------------------------

    def _read_ring_once(self, slot):
        """One message off worker ``slot``'s result ring.

        Returns ``None`` (nothing complete), ``('again',)`` (absorbed a
        seq/partial frame — poll the same ring again), ``('control',
        payload)``, or ``('data', (seq, chunk_idx) | None, payload)`` with
        chunked payloads reassembled (chunks never interleave within one
        ring — SPSC).
        """
        from petastorm_tpu.native.shm_ring import RingClosed

        ring = self._result_rings[slot]
        try:
            message = ring.read(timeout_ms=0)
        except RingClosed:
            return None
        if message is None:
            return None
        tag = bytes(message[:1])
        if tag == _TAG_SEQ:
            self._ring_seq[slot] = struct.unpack('<qq', bytes(message[1:17]))
            return ('again',)
        if tag == _TAG_PARTIAL:
            self._partials.setdefault(slot, []).append(message[1:])
            return ('again',)
        if tag == _TAG_DATA:
            payload = message[1:]
            pending = self._partials.pop(slot, None)
            if pending is not None:
                pending.append(payload)
                payload = memoryview(b''.join(pending))
            return ('data', self._ring_seq.pop(slot, None), payload)
        if tag == _TAG_CONTROL:
            return ('control', message[1:])
        raise RuntimeError('Unexpected shm ring tag {!r}'.format(tag))

    def _poll_once(self, timeout_ms):
        """One complete message from any ring (or the rescue queue):
        ``('control', payload)`` / ``('data', seq, payload)`` / None."""
        if self._drained:
            return self._drained.popleft()
        deadline = time.monotonic() + timeout_ms / 1000.0
        while True:
            for _ in range(self._workers_count):
                slot = self._poll_cursor % self._workers_count
                # Advance BEFORE reading so a successful read doesn't pin the
                # sweep on one busy ring (round-robin fairness: the other
                # workers' bounded rings must keep draining or they stall).
                self._poll_cursor += 1
                while True:
                    message = self._read_ring_once(slot)
                    if message is None or message[0] != 'again':
                        break
                if message is not None:
                    return message
            if time.monotonic() >= deadline:
                return None
            time.sleep(0.001)

    def get_results(self, timeout=_DEFAULT_TIMEOUT_S):
        deadline = time.monotonic() + timeout if timeout is not None else None
        while True:
            if self.health_heartbeat is not None:
                self.health_heartbeat.beat('poll')
            self._flush_pending()
            self._check_worker_health()
            message = self._poll_once(timeout_ms=50)
            if message is not None:
                if message[0] == 'data':
                    _, header, payload = message
                    seq, chunk_index = header if header else (None, 0)
                    if seq is not None and seq >= 0 \
                            and not self._registry.mark_delivered(seq, chunk_index):
                        logger.warning('Dropping duplicate data for seq %s '
                                       'chunk %s (respawn replay)', seq,
                                       chunk_index)
                        continue
                    if self.health_heartbeat is not None:
                        self.health_heartbeat.beat('deliver')
                    return self._serializer.deserialize(payload)
                control = pickle.loads(message[1])
                if control == _WORKER_STARTED:
                    continue
                if isinstance(control, VentilatedItemProcessedMessage):
                    self._on_item_processed(control.seq)
                    continue
                if isinstance(control, RowGroupQuarantined):
                    if self._on_item_processed(control.seq):
                        self._handle_quarantine(control)
                    continue
                if isinstance(control, _WorkerError):
                    self.stop()
                    self.join()
                    logger.error('Worker traceback:\n%s', control.traceback_str)
                    raise control.exception
                raise RuntimeError('Unexpected control message: {!r}'.format(control))
            if self._all_done():
                raise EmptyResultError()
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutWaitingForResultError(self._timeout_details(timeout))

    # --- worker supervision: transport hooks (SupervisedPoolMixin) ---------

    def _rescue_dead_worker_output(self, slot):
        """Drain the dead worker's result ring before discarding it: complete
        messages (incl. acks) survive — a torn trailing write is invisible to
        ring.read — so their items won't be needlessly re-ventilated."""
        while True:
            message = self._read_ring_once(slot)
            if message is None:
                break
            if message[0] != 'again':
                self._drained.append(message)
        self._partials.pop(slot, None)
        self._ring_seq.pop(slot, None)
        # Rescued acks must land before the mixin calls take_slot_items so
        # completed items drop out of the in-flight set. A quarantine counts
        # as an ack too (workers/__init__) — without this, an item the dead
        # worker already quarantined would be re-ventilated, re-fail on the
        # replacement, and have its second quarantine dropped as stale.
        still_drained = deque()
        for message in self._drained:
            if message[0] == 'control':
                control = pickle.loads(message[1])
                if isinstance(control, VentilatedItemProcessedMessage):
                    self._on_item_processed(control.seq)
                    continue
                if isinstance(control, RowGroupQuarantined):
                    if self._on_item_processed(control.seq):
                        self._handle_quarantine(control)
                    continue
            still_drained.append(message)
        self._drained = still_drained

    def _discard_pending_work(self, slot):
        with self._send_lock:
            self._pending_sends[slot] = []

    def _respawn_worker_transport(self, slot):
        from petastorm_tpu.native.shm_ring import ShmRing

        self._work_rings[slot].close()
        self._result_rings[slot].close()
        self._generations[slot] += 1
        in_name, out_name = _ring_names(self._base, slot, self._generations[slot])
        self._work_rings[slot] = ShmRing.create(in_name, _WORK_RING_BYTES)
        self._result_rings[slot] = ShmRing.create(out_name, self._result_ring_bytes)
        self._processes[slot] = self._spawn_worker(slot)

    # --- lifecycle ---------------------------------------------------------

    def _all_done(self):
        # `completed` must be observed FIRST (see thread_pool._all_done).
        ventilator_done = self._ventilator is None or self._ventilator.completed()
        if not ventilator_done:
            return False
        with self._count_lock:
            return self._ventilated_unprocessed == 0

    def stop(self):
        if self._ventilator is not None:
            self._ventilator.stop()
        self._stopped = True
        # FINISHED: flags on both rings; aborts any blocked worker write.
        for ring in self._work_rings + self._result_rings:
            ring.set_flags(_FLAG_FINISHED)

    def join(self):
        if not self._stopped:
            self.stop()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if all(p.poll() is not None for p in self._processes):
                break
            time.sleep(0.05)
        for process in self._processes:
            if process.poll() is None:  # pragma: no cover - hung worker
                process.kill()
        for ring in self._work_rings + self._result_rings:
            ring.close()
        self._processes = []
        self._work_rings = []
        self._result_rings = []
        self._pending_sends = []
        self._partials = {}
        self._ring_seq = {}
        self._drained = deque()

    @property
    def diagnostics(self):
        with self._count_lock:
            unprocessed = self._ventilated_unprocessed
        diag = {'ventilated_unprocessed': unprocessed,
                'workers_count': self._workers_count,
                'transport': 'shm_ring'}
        diag.update(self._supervision_diagnostics())
        return diag

    @property
    def results_qsize(self):
        return sum(1 for ring in self._result_rings if ring.readable_bytes)


def _shm_worker_bootstrap(worker_class, worker_id, worker_args, in_name,
                          out_name, serializer_type, parent_pid):
    """Entry point of a spawned shm worker process."""
    import traceback

    from petastorm_tpu.faults import maybe_inject
    from petastorm_tpu.native.shm_ring import RingClosed, ShmRing
    from petastorm_tpu.trace import install_worker_tracer

    serializer = serializer_type()
    work_ring = ShmRing.open(in_name)
    result_ring = ShmRing.open(out_name)

    _start_orphan_watchdog(parent_pid)
    # Cross-process tracing: sidecar-spilling global tracer when
    # PETASTORM_TPU_TRACE_DIR is set (see process_pool._worker_bootstrap).
    worker_tracer = install_worker_tracer(
        role='worker-{}'.format(worker_id))

    def send_control(obj):
        result_ring.write_tagged(_TAG_CONTROL, pickle.dumps(obj), timeout_ms=-1)

    # Payloads bigger than the ring allows are streamed in chunks; keep a
    # safety margin under capacity/2 for framing.
    chunk_limit = max(4096, result_ring.capacity // 2 - 4096)

    current_seq = [None, 0]  # [item seq, chunk index within the item]

    def publish(data):
        maybe_inject('queue-stall')
        payload = serializer.serialize(data)
        seq = -1 if current_seq[0] is None else current_seq[0]
        result_ring.write_tagged(_TAG_SEQ,
                                 struct.pack('<qq', seq, current_seq[1]),
                                 timeout_ms=-1)
        current_seq[1] += 1
        view = memoryview(payload)
        while len(view) > chunk_limit:
            result_ring.write_tagged(_TAG_PARTIAL, view[:chunk_limit], timeout_ms=-1)
            view = view[chunk_limit:]
        result_ring.write_tagged(_TAG_DATA, view, timeout_ms=-1)

    worker = worker_class(worker_id, publish, worker_args)
    try:
        worker.initialize()
    except Exception as e:  # noqa: BLE001
        send_control(_WorkerError(e, traceback.format_exc()))
        return

    send_control(_WORKER_STARTED)
    try:
        while not (work_ring.get_flags() & _FLAG_FINISHED):
            try:
                item = work_ring.read(timeout_ms=100)
            except RingClosed:
                break
            if item is None:
                continue
            seq, args, kwargs = dill.loads(item)
            current_seq[0], current_seq[1] = seq, 0
            error = _run_worker_item(worker, seq, args, kwargs, send_control)
            if error is not None:
                send_control(error)
            current_seq[0] = None
    except RingClosed:
        pass
    finally:
        worker.shutdown()
        if worker_tracer is not None:
            worker_tracer.close()
        work_ring.close()
        result_ring.close()
