"""Out-of-process worker pool over the native shared-memory ring transport.

Same protocol and consumer semantics as :class:`ProcessPool` (the reference's
ZeroMQ design, ``workers_pool/process_pool.py:52-74``) but the worker <->
consumer channels are mmap'd SPSC rings (``native/src/shm_ring.cc``): no
sockets, no syscalls on the steady-state path, single memcpy per message.

Channel layout per worker i:
  work ring  ``/pst_<pid>_<uid>_i_in``   parent -> worker, pickled (args, kwargs)
  result ring ``/pst_<pid>_<uid>_i_out`` worker -> parent, 1-byte tag + payload
    tag b'C': pickled control (started / item-processed / error)
    tag b'D': serializer payload (row-group data), possibly final chunk
    tag b'P': non-final chunk of a payload larger than half the ring
              (chunks are contiguous per ring — SPSC ordering — so the
              consumer reassembles per-ring; no message size limit)

FINISHED broadcast = setting the control flag word on both rings; blocked ring
writes abort with RingClosed so shutdown can't deadlock on a full ring
(the reference needs an explicit drain loop for this, ``process_pool.py:287-304``).
"""

import logging
import os
import pickle
import threading
import time
import uuid

import dill

from petastorm_tpu.workers import (EmptyResultError, TimeoutWaitingForResultError,
                                   VentilatedItemProcessedMessage)
from petastorm_tpu.workers.exec_in_new_process import exec_in_new_process
from petastorm_tpu.workers.process_pool import _start_orphan_watchdog, _WorkerError
from petastorm_tpu.workers.serializers import PickleSerializer

logger = logging.getLogger(__name__)

_WORKER_STARTED = '__worker_started__'
_FLAG_FINISHED = 1
_TAG_CONTROL = b'C'
_TAG_DATA = b'D'
_TAG_PARTIAL = b'P'  # chunk of an oversized data payload; 'D' terminates it
_DEFAULT_TIMEOUT_S = 60
_STARTUP_TIMEOUT_S = 120
_WORK_RING_BYTES = 1 << 20          # pickled work items are tiny
_DEFAULT_RESULT_RING_BYTES = 64 << 20


def shm_transport_available():
    from petastorm_tpu.native import shm_ring
    return shm_ring.available()


class ShmProcessPool(object):
    """Drop-in alternative to ProcessPool; rings instead of zmq sockets.

    :param result_ring_bytes: per-worker results ring capacity. Decoded
        row-groups must fit in half of this (ring message limit).
    """

    def __init__(self, workers_count, results_queue_size=50, serializer=None,
                 result_ring_bytes=_DEFAULT_RESULT_RING_BYTES):
        self._workers_count = workers_count
        self._serializer = serializer or PickleSerializer()
        self._result_ring_bytes = result_ring_bytes
        del results_queue_size  # bounded by ring bytes, not message count

        self._work_rings = []
        self._result_rings = []
        self._processes = []
        self._ventilator = None
        self._ventilated_unprocessed = 0
        self._count_lock = threading.Lock()
        self._stopped = False
        self._next_worker = 0
        self._poll_cursor = 0
        self._partials = {}  # ring index -> accumulated 'P' chunks

    @property
    def workers_count(self):
        return self._workers_count

    def start(self, worker_class, worker_args=None, ventilator=None):
        from petastorm_tpu.native.shm_ring import ShmRing

        if self._processes:
            raise RuntimeError('ShmProcessPool already started')
        base = '/pst_{}_{}'.format(os.getpid(), uuid.uuid4().hex[:8])
        for worker_id in range(self._workers_count):
            self._work_rings.append(
                ShmRing.create('{}_{}_in'.format(base, worker_id), _WORK_RING_BYTES))
            self._result_rings.append(
                ShmRing.create('{}_{}_out'.format(base, worker_id),
                               self._result_ring_bytes))
        for worker_id in range(self._workers_count):
            process = exec_in_new_process(
                _shm_worker_bootstrap, worker_class, worker_id, worker_args,
                base, type(self._serializer), os.getpid())
            self._processes.append(process)

        started = 0
        deadline = time.monotonic() + _STARTUP_TIMEOUT_S
        while started < self._workers_count:
            if time.monotonic() > deadline:
                self.stop()
                raise RuntimeError(
                    'Timed out waiting for {} shm workers to start ({} started)'.format(
                        self._workers_count, started))
            message = self._poll_once(timeout_ms=1000)
            if message is None:
                self._check_workers_alive()
                continue
            tag, payload = message
            if tag == _TAG_CONTROL:
                control = pickle.loads(payload)
                if control == _WORKER_STARTED:
                    started += 1
                elif isinstance(control, _WorkerError):
                    self.stop()
                    self.join()
                    raise control.exception

        self._ventilator = ventilator
        if ventilator is not None:
            ventilator._ventilate_fn = self.ventilate
            ventilator.start()

    def _check_workers_alive(self):
        dead = [p.pid for p in self._processes if p.poll() is not None]
        if dead:
            self.stop()
            raise RuntimeError('shm worker process(es) {} died during startup'.format(dead))

    def ventilate(self, *args, **kwargs):
        with self._count_lock:
            self._ventilated_unprocessed += 1
        # Round-robin dispatch (zmq PUSH does the same across peers).
        ring = self._work_rings[self._next_worker % self._workers_count]
        self._next_worker += 1
        # dill: work items may close over lambdas (predicates/transforms)
        ring.write(dill.dumps((args, kwargs)), timeout_ms=-1)

    def _poll_once(self, timeout_ms):
        """One sweep over all result rings; returns (tag, payload) or None.

        Reassembles chunked payloads: 'P' chunks accumulate per ring until
        the terminating 'D' arrives (chunks never interleave within one
        ring — it's SPSC).
        """
        from petastorm_tpu.native.shm_ring import RingClosed

        deadline = time.monotonic() + timeout_ms / 1000.0
        while True:
            for _ in range(self._workers_count):
                ring_index = self._poll_cursor % self._workers_count
                ring = self._result_rings[ring_index]
                self._poll_cursor += 1
                try:
                    message = ring.read(timeout_ms=0)
                except RingClosed:
                    continue
                if message is None:
                    continue
                tag, payload = message[:1], message[1:]
                if tag == _TAG_PARTIAL:
                    self._partials.setdefault(ring_index, []).append(payload)
                    continue
                pending = self._partials.pop(ring_index, None)
                if pending is not None and tag == _TAG_DATA:
                    pending.append(payload)
                    payload = memoryview(b''.join(pending))
                return tag, payload
            if time.monotonic() >= deadline:
                return None
            time.sleep(0.001)

    def get_results(self, timeout=_DEFAULT_TIMEOUT_S):
        deadline = time.monotonic() + timeout if timeout is not None else None
        while True:
            message = self._poll_once(timeout_ms=50)
            if message is not None:
                tag, payload = message
                if tag == _TAG_DATA:
                    return self._serializer.deserialize(payload)
                control = pickle.loads(payload)
                if control == _WORKER_STARTED:
                    continue
                if isinstance(control, VentilatedItemProcessedMessage):
                    with self._count_lock:
                        self._ventilated_unprocessed -= 1
                    if self._ventilator is not None:
                        self._ventilator.processed_item()
                    continue
                if isinstance(control, _WorkerError):
                    self.stop()
                    self.join()
                    logger.error('Worker traceback:\n%s', control.traceback_str)
                    raise control.exception
                raise RuntimeError('Unexpected control message: {!r}'.format(control))
            if self._all_done():
                raise EmptyResultError()
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutWaitingForResultError()

    def _all_done(self):
        # `completed` must be observed FIRST (see thread_pool._all_done).
        ventilator_done = self._ventilator is None or self._ventilator.completed()
        if not ventilator_done:
            return False
        with self._count_lock:
            return self._ventilated_unprocessed == 0

    def stop(self):
        if self._ventilator is not None:
            self._ventilator.stop()
        self._stopped = True
        # FINISHED: flags on both rings; aborts any blocked worker write.
        for ring in self._work_rings + self._result_rings:
            ring.set_flags(_FLAG_FINISHED)

    def join(self):
        if not self._stopped:
            self.stop()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if all(p.poll() is not None for p in self._processes):
                break
            time.sleep(0.05)
        for process in self._processes:
            if process.poll() is None:  # pragma: no cover - hung worker
                process.kill()
        for ring in self._work_rings + self._result_rings:
            ring.close()
        self._processes = []
        self._work_rings = []
        self._result_rings = []
        self._partials = {}

    @property
    def diagnostics(self):
        with self._count_lock:
            return {'ventilated_unprocessed': self._ventilated_unprocessed,
                    'workers_count': self._workers_count,
                    'transport': 'shm_ring'}

    @property
    def results_qsize(self):
        return sum(1 for ring in self._result_rings if ring.readable_bytes)


def _shm_worker_bootstrap(worker_class, worker_id, worker_args, base,
                          serializer_type, parent_pid):
    """Entry point of a spawned shm worker process."""
    import traceback

    from petastorm_tpu.native.shm_ring import RingClosed, ShmRing

    serializer = serializer_type()
    work_ring = ShmRing.open('{}_{}_in'.format(base, worker_id))
    result_ring = ShmRing.open('{}_{}_out'.format(base, worker_id))

    _start_orphan_watchdog(parent_pid)

    def send_control(obj):
        result_ring.write_tagged(_TAG_CONTROL, pickle.dumps(obj), timeout_ms=-1)

    # Payloads bigger than the ring allows are streamed in chunks; keep a
    # safety margin under capacity/2 for framing.
    chunk_limit = max(4096, result_ring.capacity // 2 - 4096)

    def publish(data):
        payload = serializer.serialize(data)
        view = memoryview(payload)
        while len(view) > chunk_limit:
            result_ring.write_tagged(_TAG_PARTIAL, view[:chunk_limit], timeout_ms=-1)
            view = view[chunk_limit:]
        result_ring.write_tagged(_TAG_DATA, view, timeout_ms=-1)

    worker = worker_class(worker_id, publish, worker_args)
    try:
        worker.initialize()
    except Exception as e:  # noqa: BLE001
        send_control(_WorkerError(e, traceback.format_exc()))
        return

    send_control(_WORKER_STARTED)
    try:
        while not (work_ring.get_flags() & _FLAG_FINISHED):
            try:
                item = work_ring.read(timeout_ms=100)
            except RingClosed:
                break
            if item is None:
                continue
            args, kwargs = dill.loads(item)
            try:
                worker.process(*args, **kwargs)
                send_control(VentilatedItemProcessedMessage())
            except Exception as e:  # noqa: BLE001
                send_control(_WorkerError(e, traceback.format_exc()))
    except RingClosed:
        pass
    finally:
        worker.shutdown()
        work_ring.close()
        result_ring.close()
