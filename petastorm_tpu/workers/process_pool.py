"""Out-of-process worker pool over ZeroMQ.

Parity: reference ``petastorm/workers_pool/process_pool.py`` — PUSH
(ventilate) / PUB (control) / PULL (results) sockets on random localhost TCP
ports (protocol diagram ``:52-74``); workers spawned, never forked (``:15-17``)
via :func:`exec_in_new_process`; startup barrier waiting for a started
indicator per worker (``:208-214``); results as 2-part multipart
``[control-pickle, data(serializer)]`` (``:317-321``); orphan watchdog thread
killing the worker if the parent dies (``:324-331``); slow-joiner-safe
shutdown rebroadcasting FINISHED (``:287-304``).

On TPU-VM hosts this pool sidesteps the GIL for CPU-bound python decode;
spawning keeps libtpu/JAX client state out of data workers.
"""

import logging
import os
import pickle
import threading
import time

import dill
import zmq

from petastorm_tpu.workers import (EmptyResultError, TimeoutWaitingForResultError,
                                   VentilatedItemProcessedMessage)
from petastorm_tpu.workers.exec_in_new_process import exec_in_new_process
from petastorm_tpu.workers.serializers import PickleSerializer

logger = logging.getLogger(__name__)

_WORKER_STARTED = '__worker_started__'
_CONTROL_FINISHED = b'FINISHED'
_SOCKET_LINGER_MS = 1000
_DEFAULT_TIMEOUT_S = 60
_STARTUP_TIMEOUT_S = 120
_JOIN_REBROADCAST_INTERVAL_S = 0.2


class _WorkerError(object):
    def __init__(self, exception, traceback_str):
        self.exception = exception
        self.traceback_str = traceback_str


class ProcessPool(object):
    def __init__(self, workers_count, results_queue_size=50, serializer=None,
                 zmq_copy_buffers=True):
        self._workers_count = workers_count
        self._results_queue_size = results_queue_size
        self._serializer = serializer or PickleSerializer()
        self._zmq_copy_buffers = zmq_copy_buffers

        self._context = None
        self._ventilator_send = None
        self._control_sender = None
        self._results_receiver = None
        self._processes = []
        self._ventilator = None
        self._ventilated_unprocessed = 0
        self._count_lock = threading.Lock()
        self._stopped = False

    @property
    def workers_count(self):
        return self._workers_count

    def start(self, worker_class, worker_args=None, ventilator=None):
        if self._processes:
            raise RuntimeError('ProcessPool already started')
        self._context = zmq.Context()

        self._ventilator_send = self._context.socket(zmq.PUSH)
        ventilator_port = self._ventilator_send.bind_to_random_port('tcp://127.0.0.1')
        self._control_sender = self._context.socket(zmq.PUB)
        control_port = self._control_sender.bind_to_random_port('tcp://127.0.0.1')
        self._results_receiver = self._context.socket(zmq.PULL)
        self._results_receiver.set(zmq.RCVHWM, self._results_queue_size)
        results_port = self._results_receiver.bind_to_random_port('tcp://127.0.0.1')

        for worker_id in range(self._workers_count):
            process = exec_in_new_process(
                _worker_bootstrap, worker_class, worker_id, worker_args,
                ventilator_port, control_port, results_port,
                type(self._serializer), os.getpid())
            self._processes.append(process)

        # Startup barrier (parity: process_pool.py:208-214).
        started = 0
        deadline = time.monotonic() + _STARTUP_TIMEOUT_S
        while started < self._workers_count:
            if time.monotonic() > deadline:
                self.stop()
                raise RuntimeError('Timed out waiting for {} worker processes to start '
                                   '({} started)'.format(self._workers_count, started))
            if self._results_receiver.poll(1000):
                message = self._results_receiver.recv_multipart()
                control = pickle.loads(message[0])
                if control == _WORKER_STARTED:
                    started += 1

        self._ventilator = ventilator
        if ventilator is not None:
            ventilator._ventilate_fn = self.ventilate
            ventilator.start()

    def ventilate(self, *args, **kwargs):
        with self._count_lock:
            self._ventilated_unprocessed += 1
        # dill, not pickle: ventilated items may close over lambdas
        # (predicates/transforms), same as worker_args in exec_in_new_process.
        self._ventilator_send.send(dill.dumps((args, kwargs)))

    def get_results(self, timeout=_DEFAULT_TIMEOUT_S):
        deadline = time.monotonic() + timeout if timeout is not None else None
        while True:
            if self._results_receiver.poll(50):
                message = self._results_receiver.recv_multipart()
                control = pickle.loads(message[0])
                if control == _WORKER_STARTED:
                    continue
                if isinstance(control, VentilatedItemProcessedMessage):
                    with self._count_lock:
                        self._ventilated_unprocessed -= 1
                    if self._ventilator is not None:
                        self._ventilator.processed_item()
                    continue
                if isinstance(control, _WorkerError):
                    self.stop()
                    self.join()
                    logger.error('Worker traceback:\n%s', control.traceback_str)
                    raise control.exception
                # Data message: payload in the second frame.
                return self._serializer.deserialize(message[1])
            if self._all_done():
                raise EmptyResultError()
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutWaitingForResultError()

    def _all_done(self):
        # `completed` must be observed FIRST (see thread_pool._all_done).
        ventilator_done = self._ventilator is None or self._ventilator.completed()
        if not ventilator_done:
            return False
        with self._count_lock:
            return self._ventilated_unprocessed == 0

    def stop(self):
        if self._ventilator is not None:
            self._ventilator.stop()
        self._stopped = True
        if self._control_sender is not None:
            self._control_sender.send(_CONTROL_FINISHED)

    def join(self):
        # Slow-joiner-safe shutdown: rebroadcast FINISHED until every worker
        # exits (parity: process_pool.py:287-304).
        if not self._stopped:
            self.stop()
        while True:
            alive = [p for p in self._processes if p.poll() is None]
            if not alive:
                break
            self._control_sender.send(_CONTROL_FINISHED)
            # Drain results so workers blocked on a full PUSH can exit.
            while self._results_receiver.poll(0):
                self._results_receiver.recv_multipart()
            time.sleep(_JOIN_REBROADCAST_INTERVAL_S)
        for sock in (self._ventilator_send, self._control_sender, self._results_receiver):
            if sock is not None:
                sock.close(linger=_SOCKET_LINGER_MS)
        if self._context is not None:
            self._context.term()
        self._processes = []

    @property
    def diagnostics(self):
        with self._count_lock:
            return {'ventilated_unprocessed': self._ventilated_unprocessed,
                    'workers_count': self._workers_count}

    @property
    def results_qsize(self):
        return 0  # unknown for zmq transport


def _worker_bootstrap(worker_class, worker_id, worker_args,
                      ventilator_port, control_port, results_port,
                      serializer_type, parent_pid):
    """Entry point of a spawned worker process.

    Parity: reference ``process_pool.py:334-417``.
    """
    import traceback

    serializer = serializer_type()
    context = zmq.Context()

    work_receiver = context.socket(zmq.PULL)
    work_receiver.connect('tcp://127.0.0.1:{}'.format(ventilator_port))
    control_receiver = context.socket(zmq.SUB)
    control_receiver.connect('tcp://127.0.0.1:{}'.format(control_port))
    control_receiver.setsockopt(zmq.SUBSCRIBE, b'')
    results_sender = context.socket(zmq.PUSH)
    results_sender.connect('tcp://127.0.0.1:{}'.format(results_port))

    _start_orphan_watchdog(parent_pid)

    def publish(data):
        results_sender.send_multipart([pickle.dumps('data'), serializer.serialize(data)])

    worker = worker_class(worker_id, publish, worker_args)
    try:
        worker.initialize()
    except Exception as e:  # noqa: BLE001
        results_sender.send_multipart([
            pickle.dumps(_WorkerError(e, traceback.format_exc())), b''])
        return

    results_sender.send_multipart([pickle.dumps(_WORKER_STARTED), b''])

    poller = zmq.Poller()
    poller.register(work_receiver, zmq.POLLIN)
    poller.register(control_receiver, zmq.POLLIN)
    try:
        while True:
            socks = dict(poller.poll())
            if socks.get(control_receiver) == zmq.POLLIN:
                if control_receiver.recv() == _CONTROL_FINISHED:
                    break
            if socks.get(work_receiver) == zmq.POLLIN:
                args, kwargs = dill.loads(work_receiver.recv())
                try:
                    worker.process(*args, **kwargs)
                    results_sender.send_multipart([
                        pickle.dumps(VentilatedItemProcessedMessage()), b''])
                except Exception as e:  # noqa: BLE001
                    results_sender.send_multipart([
                        pickle.dumps(_WorkerError(e, traceback.format_exc())), b''])
    finally:
        worker.shutdown()
        for sock in (work_receiver, control_receiver, results_sender):
            sock.close(linger=_SOCKET_LINGER_MS)
        context.term()


def _start_orphan_watchdog(parent_pid):
    """Kill this worker if the parent process dies (parity: ``:324-331``)."""
    import psutil

    def watch():
        while True:
            if not psutil.pid_exists(parent_pid):
                os._exit(1)
            time.sleep(1)

    threading.Thread(target=watch, daemon=True).start()
