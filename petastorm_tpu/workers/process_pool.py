"""Out-of-process worker pool over ZeroMQ.

Parity: reference ``petastorm/workers_pool/process_pool.py`` — PUSH
(ventilate) / PUB (control) / PULL (results) sockets on random localhost TCP
ports (protocol diagram ``:52-74``); workers spawned, never forked (``:15-17``)
via :func:`exec_in_new_process`; startup barrier waiting for a started
indicator per worker (``:208-214``); results as 2-part multipart
``[control-pickle, data(serializer)]`` (``:317-321``); orphan watchdog thread
killing the worker if the parent dies (``:324-331``); slow-joiner-safe
shutdown rebroadcasting FINISHED (``:287-304``).

On TPU-VM hosts this pool sidesteps the GIL for CPU-bound python decode;
spawning keeps libtpu/JAX client state out of data workers.

Robustness extensions over the reference (``supervision.py`` has the full
rationale):

* **per-worker PUSH sockets** (the reference shares one PUSH across all
  workers): round-robin dispatch with *known* assignment, so the pool can
  tell which row-group items a dead worker took down with it;
* **steady-state supervision**: ``get_results`` polls worker liveness,
  respawns a dead worker within ``max_worker_restarts``, re-ventilates
  its in-flight items (seq-deduped — exactly-once delivery), and raises
  :class:`~petastorm_tpu.errors.WorkerLostError` past the budget;
* **poison row-group quarantine**: a worker skips-and-reports a failing
  item instead of crashing when the reader opted in (``workers/__init__``);
* socket writes are confined to the consumer thread (ventilation goes
  through per-worker pending queues) so respawn can swap sockets without
  racing the ventilator thread.
"""

import logging
import os
import pickle
import threading
import time

import dill
import zmq

from petastorm_tpu.workers import (EmptyResultError, RowGroupQuarantined,
                                   TimeoutWaitingForResultError,
                                   VentilatedItemProcessedMessage,
                                   quarantine_record_for)
from petastorm_tpu.workers.exec_in_new_process import exec_in_new_process
from petastorm_tpu.workers.serializers import PickleSerializer
from petastorm_tpu.workers.supervision import (DEFAULT_MAX_WORKER_RESTARTS,
                                               InFlightRegistry,
                                               SupervisedPoolMixin)

logger = logging.getLogger(__name__)

_WORKER_STARTED = '__worker_started__'
_CONTROL_FINISHED = b'FINISHED'
_SOCKET_LINGER_MS = 1000
_DEFAULT_TIMEOUT_S = 60
_STARTUP_TIMEOUT_S = 120
_JOIN_REBROADCAST_INTERVAL_S = 0.2


class _WorkerError(object):
    def __init__(self, exception, traceback_str):
        self.exception = exception
        self.traceback_str = traceback_str


class ProcessPool(SupervisedPoolMixin):
    _pool_kind = 'Worker'

    def __init__(self, workers_count, results_queue_size=50, serializer=None,
                 zmq_copy_buffers=True,
                 max_worker_restarts=DEFAULT_MAX_WORKER_RESTARTS):
        """:param max_worker_restarts: total worker respawns tolerated over
        the pool's lifetime before a further death raises
        :class:`~petastorm_tpu.errors.WorkerLostError`."""
        self._workers_count = workers_count
        self._results_queue_size = results_queue_size
        self._serializer = serializer or PickleSerializer()
        self._zmq_copy_buffers = zmq_copy_buffers
        self._init_supervision(max_worker_restarts)

        self._context = None
        self._worker_sockets = []
        self._worker_ports = []
        self._pending_sends = []
        self._send_lock = threading.Lock()
        self._control_sender = None
        self._results_receiver = None
        self._control_port = None
        self._results_port = None
        self._processes = []
        self._worker_class = None
        self._worker_args = None
        self._ventilator = None
        self._ventilated_unprocessed = 0
        self._count_lock = threading.Lock()
        self._stopped = False
        self._registry = None
        # Data/error messages pulled off the results socket during a
        # dead-worker rescue drain; served (in order) before fresh polls.
        self._rescued = []
        #: Set by the Reader when ``error_budget`` is enabled; receives
        #: RowGroupQuarantined records (and raises when the budget is spent).
        self.quarantine_sink = None
        #: Optional health.Heartbeat (set by ``Reader.attach_health``):
        #: beaten each ``get_results`` poll ('poll') and on every delivered
        #: message ('deliver') — proves the consumer-side pump is alive.
        self.health_heartbeat = None

    @property
    def workers_count(self):
        return self._workers_count

    def start(self, worker_class, worker_args=None, ventilator=None):
        if self._processes:
            raise RuntimeError('ProcessPool already started')
        self._context = zmq.Context()
        self._worker_class = worker_class
        self._worker_args = worker_args
        self._registry = InFlightRegistry(self._workers_count)

        self._control_sender = self._context.socket(zmq.PUB)
        self._control_port = self._control_sender.bind_to_random_port('tcp://127.0.0.1')
        self._results_receiver = self._context.socket(zmq.PULL)
        self._results_receiver.set(zmq.RCVHWM, self._results_queue_size)
        self._results_port = self._results_receiver.bind_to_random_port('tcp://127.0.0.1')

        for worker_id in range(self._workers_count):
            sock = self._context.socket(zmq.PUSH)
            port = sock.bind_to_random_port('tcp://127.0.0.1')
            self._worker_sockets.append(sock)
            self._worker_ports.append(port)
            self._pending_sends.append([])
            self._processes.append(self._spawn_worker(worker_id, port))

        # Startup barrier (parity: process_pool.py:208-214).
        started = 0
        deadline = time.monotonic() + _STARTUP_TIMEOUT_S
        while started < self._workers_count:
            if time.monotonic() > deadline:
                self.stop()
                raise RuntimeError('Timed out waiting for {} worker processes to start '
                                   '({} started)'.format(self._workers_count, started))
            if self._rescued:
                # A death during startup drains the results socket; peers'
                # startup acks land in the stash and must still count.
                message = self._rescued.pop(0)
            elif self._results_receiver.poll(1000):
                message = self._results_receiver.recv_multipart()
            else:
                self._check_worker_health(force=True)
                continue
            control = pickle.loads(message[0])
            if control == _WORKER_STARTED:
                started += 1
            elif isinstance(control, _WorkerError):
                self.stop()
                self.join()
                logger.error('Worker traceback:\n%s', control.traceback_str)
                raise control.exception

        self._ventilator = ventilator
        if ventilator is not None:
            ventilator._ventilate_fn = self.ventilate
            ventilator.start()

    def _spawn_worker(self, worker_id, ventilator_port):
        return exec_in_new_process(
            _worker_bootstrap, self._worker_class, worker_id, self._worker_args,
            ventilator_port, self._control_port, self._results_port,
            type(self._serializer), os.getpid())

    def ventilate(self, *args, **kwargs):
        with self._count_lock:
            self._ventilated_unprocessed += 1
        seq, slot = self._registry.assign((args, kwargs))
        # dill, not pickle: ventilated items may close over lambdas
        # (predicates/transforms), same as worker_args in exec_in_new_process.
        # No socket write here — ventilate() runs on the ventilator thread,
        # but the per-worker sockets belong to the consumer thread (which
        # may close/recreate them on respawn). The consumer flushes pending
        # sends on every get_results poll iteration.
        self._enqueue_work(slot, dill.dumps((seq, args, kwargs)))

    def _enqueue_work(self, slot, payload):
        with self._send_lock:
            self._pending_sends[slot].append(payload)

    def _flush_pending(self):
        """Consumer-thread-only: push queued work onto worker sockets."""
        for slot, sock in enumerate(self._worker_sockets):
            while True:
                with self._send_lock:
                    if not self._pending_sends[slot]:
                        break
                    payload = self._pending_sends[slot][0]
                try:
                    sock.send(payload, flags=zmq.DONTWAIT)
                except zmq.Again:
                    break  # worker not connected yet / HWM reached; later
                with self._send_lock:
                    self._pending_sends[slot].pop(0)

    def get_results(self, timeout=_DEFAULT_TIMEOUT_S):
        deadline = time.monotonic() + timeout if timeout is not None else None
        while True:
            if self.health_heartbeat is not None:
                self.health_heartbeat.beat('poll')
            self._flush_pending()
            self._check_worker_health()
            if self._rescued:
                message = self._rescued.pop(0)
                control = pickle.loads(message[0])
            elif self._results_receiver.poll(50):
                message = self._results_receiver.recv_multipart()
                control = pickle.loads(message[0])
            else:
                message = None
            if message is not None:
                if control == _WORKER_STARTED:
                    continue
                if isinstance(control, VentilatedItemProcessedMessage):
                    self._on_item_processed(control.seq)
                    continue
                if isinstance(control, RowGroupQuarantined):
                    if self._on_item_processed(control.seq):
                        self._handle_quarantine(control)
                    continue
                if isinstance(control, _WorkerError):
                    self.stop()
                    self.join()
                    logger.error('Worker traceback:\n%s', control.traceback_str)
                    raise control.exception
                if isinstance(control, tuple) and control and control[0] == 'data':
                    seq, chunk_index = control[1], control[2]
                    if not self._registry.mark_delivered(seq, chunk_index):
                        logger.warning('Dropping duplicate data for seq %s '
                                       'chunk %s (respawn replay)', seq,
                                       chunk_index)
                        continue
                    if self.health_heartbeat is not None:
                        self.health_heartbeat.beat('deliver')
                    return self._serializer.deserialize(message[1])
                # Legacy untagged payload (custom workers publishing through
                # an old-style bootstrap).
                if self.health_heartbeat is not None:
                    self.health_heartbeat.beat('deliver')
                return self._serializer.deserialize(message[1])
            if self._all_done():
                raise EmptyResultError()
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutWaitingForResultError(self._timeout_details(timeout))

    # --- worker supervision: transport hooks (SupervisedPoolMixin) ---------

    def _rescue_dead_worker_output(self, slot):
        """Drain the shared results socket before re-ventilating the dead
        worker's items: acks/quarantines it managed to send must land first,
        or a completed (or already-quarantined) item would be needlessly
        reprocessed — and a stale quarantine could burn a budget unit for a
        row-group the replacement then successfully delivers. Data and
        error messages are stashed (in order) for get_results. A short
        quiet-period poll catches messages still in the zmq io thread; a
        straggler that slips past is still delivery-safe via the
        (seq, chunk) dedup. The drain is bounded (time + message count) so
        live workers' ongoing output can't grow the stash without limit."""
        drain_deadline = time.monotonic() + 0.25
        max_stash = len(self._rescued) + 2 * self._results_queue_size
        while (time.monotonic() < drain_deadline
               and len(self._rescued) < max_stash
               and self._results_receiver.poll(25)):
            message = self._results_receiver.recv_multipart()
            control = pickle.loads(message[0])
            if control == _WORKER_STARTED:
                # Must not be swallowed: a death during the startup barrier
                # drains here, and the barrier still needs to count peers'
                # startup acks (it consumes _rescued first).
                self._rescued.append(message)
                continue
            if isinstance(control, VentilatedItemProcessedMessage):
                self._on_item_processed(control.seq)
                continue
            if isinstance(control, RowGroupQuarantined):
                if self._on_item_processed(control.seq):
                    self._handle_quarantine(control)
                continue
            self._rescued.append(message)

    def _discard_pending_work(self, slot):
        with self._send_lock:
            self._pending_sends[slot] = []

    def _respawn_worker_transport(self, slot):
        # The old socket may hold queued-but-undelivered work; those items
        # are all registered in flight (and about to be requeued), so drop
        # the socket outright (pending queue already discarded by the mixin).
        self._worker_sockets[slot].close(linger=0)
        sock = self._context.socket(zmq.PUSH)
        port = sock.bind_to_random_port('tcp://127.0.0.1')
        self._worker_sockets[slot] = sock
        self._worker_ports[slot] = port
        self._processes[slot] = self._spawn_worker(slot, port)

    # --- lifecycle ---------------------------------------------------------

    def _all_done(self):
        # `completed` must be observed FIRST (see thread_pool._all_done).
        ventilator_done = self._ventilator is None or self._ventilator.completed()
        if not ventilator_done:
            return False
        with self._count_lock:
            return self._ventilated_unprocessed == 0

    def stop(self):
        if self._ventilator is not None:
            self._ventilator.stop()
        self._stopped = True
        if self._control_sender is not None and not self._control_sender.closed:
            try:
                self._control_sender.send(_CONTROL_FINISHED)
            except zmq.ZMQError:  # already torn down (stop after join)
                pass

    def join(self):
        # Slow-joiner-safe shutdown: rebroadcast FINISHED until every worker
        # exits (parity: process_pool.py:287-304).
        if not self._stopped:
            self.stop()
        while True:
            alive = [p for p in self._processes if p.poll() is None]
            if not alive:
                break
            self._control_sender.send(_CONTROL_FINISHED)
            # Drain results so workers blocked on a full PUSH can exit.
            while self._results_receiver.poll(0):
                self._results_receiver.recv_multipart()
            time.sleep(_JOIN_REBROADCAST_INTERVAL_S)
        for sock in ([self._control_sender, self._results_receiver]
                     + self._worker_sockets):
            if sock is not None:
                sock.close(linger=_SOCKET_LINGER_MS)
        if self._context is not None:
            self._context.term()
        self._processes = []
        self._worker_sockets = []
        self._pending_sends = []

    @property
    def diagnostics(self):
        with self._count_lock:
            unprocessed = self._ventilated_unprocessed
        diag = {'ventilated_unprocessed': unprocessed,
                'workers_count': self._workers_count}
        diag.update(self._supervision_diagnostics())
        return diag

    @property
    def results_qsize(self):
        return 0  # unknown for zmq transport


def _run_worker_item(worker, seq, args, kwargs, send_control):
    """Shared per-item execution: process, ack, or quarantine/fail.

    Returns a `_WorkerError` to report, or None when handled.
    """
    import traceback

    from petastorm_tpu.faults import maybe_inject

    maybe_inject('worker-kill')
    try:
        worker.process(*args, **kwargs)
        send_control(VentilatedItemProcessedMessage(worker.worker_id, seq))
    except Exception as e:  # noqa: BLE001
        record = quarantine_record_for(worker, e, args, kwargs)
        if record is not None:
            record.seq = seq
            logger.warning('Worker %s quarantining item %s: %s',
                           worker.worker_id, record.item, record.error)
            send_control(record)
            return None
        return _WorkerError(e, traceback.format_exc())
    return None


def _worker_bootstrap(worker_class, worker_id, worker_args,
                      ventilator_port, control_port, results_port,
                      serializer_type, parent_pid):
    """Entry point of a spawned worker process.

    Parity: reference ``process_pool.py:334-417``.
    """
    import traceback

    from petastorm_tpu.faults import maybe_inject
    from petastorm_tpu.trace import install_worker_tracer

    serializer = serializer_type()
    context = zmq.Context()

    work_receiver = context.socket(zmq.PULL)
    work_receiver.connect('tcp://127.0.0.1:{}'.format(ventilator_port))
    control_receiver = context.socket(zmq.SUB)
    control_receiver.connect('tcp://127.0.0.1:{}'.format(control_port))
    control_receiver.setsockopt(zmq.SUBSCRIBE, b'')
    results_sender = context.socket(zmq.PUSH)
    results_sender.connect('tcp://127.0.0.1:{}'.format(results_port))

    _start_orphan_watchdog(parent_pid)
    # Cross-process tracing (trace.py): when PETASTORM_TPU_TRACE_DIR is set
    # (inherited through the spawn environment), this worker's read/decode/
    # handoff spans spill to a per-process JSONL sidecar the parent merges
    # into one timeline. None when unarmed — spans then hit the NullTracer.
    worker_tracer = install_worker_tracer(
        role='worker-{}'.format(worker_id))

    current_seq = [None, 0]  # [item seq, chunk index within the item]

    def publish(data):
        maybe_inject('queue-stall')
        header = ('data', current_seq[0], current_seq[1])
        current_seq[1] += 1
        results_sender.send_multipart([pickle.dumps(header),
                                       serializer.serialize(data)])

    def send_control(obj):
        results_sender.send_multipart([pickle.dumps(obj), b''])

    worker = worker_class(worker_id, publish, worker_args)
    try:
        worker.initialize()
    except Exception as e:  # noqa: BLE001
        send_control(_WorkerError(e, traceback.format_exc()))
        return

    send_control(_WORKER_STARTED)

    poller = zmq.Poller()
    poller.register(work_receiver, zmq.POLLIN)
    poller.register(control_receiver, zmq.POLLIN)
    try:
        while True:
            socks = dict(poller.poll())
            if socks.get(control_receiver) == zmq.POLLIN:
                if control_receiver.recv() == _CONTROL_FINISHED:
                    break
            if socks.get(work_receiver) == zmq.POLLIN:
                seq, args, kwargs = dill.loads(work_receiver.recv())
                current_seq[0], current_seq[1] = seq, 0
                error = _run_worker_item(worker, seq, args, kwargs, send_control)
                if error is not None:
                    send_control(error)
                current_seq[0] = None
    finally:
        worker.shutdown()
        if worker_tracer is not None:
            worker_tracer.close()
        for sock in (work_receiver, control_receiver, results_sender):
            sock.close(linger=_SOCKET_LINGER_MS)
        context.term()


def _start_orphan_watchdog(parent_pid):
    """Kill this worker if the parent process dies (parity: ``:324-331``)."""
    import psutil

    def watch():
        while True:
            if not psutil.pid_exists(parent_pid):
                os._exit(1)
            time.sleep(1)

    threading.Thread(target=watch, daemon=True,
                     name='pst-orphan-watch').start()
