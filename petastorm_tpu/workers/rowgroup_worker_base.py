"""Shared machinery for row-group workers (dict & arrow flavors).

Hosts the per-worker Parquet file-handle LRU cache, the native C++ row-group
fast path, and the shuffle-row-drop-partition slice computation so the two
worker implementations cannot drift apart.
"""

import logging
import os
from collections import OrderedDict
from urllib.parse import urlparse

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from petastorm_tpu.workers import WorkerBase

logger = logging.getLogger(__name__)

_PARQUET_FILE_CACHE_SIZE = 32


class RowGroupWorkerBase(WorkerBase):
    """Worker base with a lazily-connected store and an LRU of open files."""

    #: Whether 'auto' native-parquet mode picks the C++ reader for this worker
    #: class. Columnar workers (tensor/arrow) win from its zero-copy export;
    #: the per-row dict worker converts to Python rows anyway and measures
    #: faster on pyarrow, whose column decode parallelizes internally
    #: (round-3 profile: ~5-10% on the hello_world per-row path).
    _prefer_native_parquet = True

    def __init__(self, worker_id, publish_func, args):
        super().__init__(worker_id, publish_func, args)
        self._store = None
        self._file_cache = OrderedDict()
        self._native_parquet = None      # resolved lazily at first read
        self._native_required = False
        self._leaf_index_cache = {}

    def initialize(self):
        self._store = self.args['store_factory']()

    def _publish_hole(self, pst_det):
        """Deterministic mode: a ventilated item that produced no chunk
        (empty after predicate / drop-partition slicing) still publishes a
        placeholder carrying its ``pst_det`` tag, so the consumer-side
        resequencer's expected-seq frontier advances past it instead of
        waiting forever. No-op outside deterministic mode. Arrow workers
        override (their transport serializes tables, not dicts)."""
        if pst_det is not None:
            from petastorm_tpu.determinism import hole_marker
            self.publish_func(hole_marker(pst_det))

    # --- row-group reads ----------------------------------------------

    def _native_parquet_enabled(self):
        """Native C++ row-group decode (SURVEY §2.9): used for local stores
        when the library builds; ``PETASTORM_TPU_NATIVE_PARQUET=0`` disables,
        ``=1`` requires — build failure, a remote store, or a native read
        error then raise instead of silently measuring the pyarrow path."""
        if self._native_parquet is None:
            setting = os.environ.get('PETASTORM_TPU_NATIVE_PARQUET', 'auto')
            self._native_required = setting == '1'
            if setting == '0' or (setting == 'auto'
                                  and not self._prefer_native_parquet):
                self._native_parquet = False
            else:
                from petastorm_tpu.native import parquet as native_pq
                local = urlparse(self._store.url).scheme == 'file'
                available = native_pq.is_available()
                if self._native_required:
                    if not available:
                        raise RuntimeError('PETASTORM_TPU_NATIVE_PARQUET=1 but '
                                           'the native parquet reader failed to build')
                    if not local:
                        raise RuntimeError('PETASTORM_TPU_NATIVE_PARQUET=1 but the '
                                           'store is not local ({}); the C++ reader '
                                           'opens filesystem paths'.format(self._store.url))
                self._native_parquet = bool(available and local)
        return self._native_parquet

    def _leaf_indices(self, path, columns):
        # Keyed by (path, columns): files written by different writers may
        # order the same columns differently.
        key = (path, tuple(columns))
        indices = self._leaf_index_cache.get(key, -1)
        if indices == -1:
            from petastorm_tpu.native import parquet as native_pq
            indices = native_pq.leaf_indices_for_fields(
                self._parquet_file(path).schema, columns)
            self._leaf_index_cache[key] = indices  # None => nested; fall back
        return indices

    def _read_row_group(self, piece, columns):
        """One row-group as a ``pa.Table``, restricted to ``columns``.

        Native path: decode runs wholly in C++ with the GIL released and the
        buffers import zero-copy (Arrow C Data Interface). Falls back to
        pyarrow for remote stores, nested columns, or build failure.
        """
        from petastorm_tpu.trace import get_global_tracer
        with get_global_tracer().span('read', 'worker'):
            return self._read_row_group_traced(piece, columns)

    def _read_row_group_traced(self, piece, columns):
        from petastorm_tpu.faults import maybe_inject, rowgroup_fault_key
        fault_key = rowgroup_fault_key(piece.path, piece.row_group)
        maybe_inject('fs-read-delay', key=fault_key)
        maybe_inject('fs-read-error', key=fault_key)
        if self._native_parquet_enabled():
            indices = self._leaf_indices(piece.path, columns)
            if indices is not None:
                from petastorm_tpu.native import parquet as native_pq
                try:
                    batch = self._native_file(piece.path).read_row_group(
                        piece.row_group, columns=indices)
                    table = pa.Table.from_batches([batch])
                    # Column order follows leaf order; restore the request's.
                    return table.select(columns)
                except native_pq.NativeParquetError as e:
                    if self._native_required:
                        raise
                    logger.warning('native row-group read failed (%s); '
                                   'falling back to pyarrow', e)
                    self._native_parquet = False
        pf = self._parquet_file(piece.path)
        return pf.read_row_group(piece.row_group, columns=columns)

    def _native_file(self, path):
        """Handle-cached native reader, LRU'd alongside the pyarrow handles."""
        from petastorm_tpu.native import parquet as native_pq

        key = ('native', path)
        nf = self._file_cache.get(key)
        if nf is not None:
            self._file_cache.move_to_end(key)
            return nf
        if len(self._file_cache) >= _PARQUET_FILE_CACHE_SIZE:
            _, old = self._file_cache.popitem(last=False)
            try:
                old.close()
            except Exception:  # noqa: BLE001
                pass
        nf = native_pq.NativeParquetFile(path)
        self._file_cache[key] = nf
        return nf

    def _parquet_file(self, path):
        pf = self._file_cache.get(path)
        if pf is not None:
            self._file_cache.move_to_end(path)
            return pf
        if len(self._file_cache) >= _PARQUET_FILE_CACHE_SIZE:
            _, old = self._file_cache.popitem(last=False)  # least recently used
            try:
                old.close()
            except Exception:  # noqa: BLE001
                pass
        if urlparse(self._store.url).scheme == 'file':
            # Local store: hand pyarrow the OS path so reads run on its
            # native (memory-mapped) IO instead of round-tripping every
            # buffer through a Python fsspec file object — measured ~6% of
            # the per-row hot path (round-4 profile, PROFILE_r04.md).
            pf = pq.ParquetFile(path, memory_map=True)
        else:
            pf = pq.ParquetFile(self._store.open_file(path))
        self._file_cache[path] = pf
        return pf

    def shutdown(self):
        for pf in self._file_cache.values():
            try:
                pf.close()
            except Exception:  # noqa: BLE001
                pass
        self._file_cache = OrderedDict()


def compute_row_slice(num_rows, shuffle_row_drop_partition, ngram=None):
    """(start, stop) row bounds for one drop-partition of a row-group.

    Parity: reference ``py_dict_reader_worker.py:254-274`` — for ngram the
    kept slice is tail-extended so windows spanning the boundary survive.
    Returns None when the whole range is kept.
    """
    if shuffle_row_drop_partition is None:
        return None
    this_partition, num_partitions = shuffle_row_drop_partition
    if num_partitions <= 1:
        return None
    bounds = [int(round(i * num_rows / num_partitions)) for i in range(num_partitions + 1)]
    start, stop = bounds[this_partition], bounds[this_partition + 1]
    if ngram is not None:
        stop = min(num_rows, stop + ngram.length - 1)
    return start, stop


def chunk_row_permutation(seed, dataset_hash, piece_path, row_group,
                          shuffle_row_drop_partition, n_rows):
    """Stable row permutation for one chunk (``shuffle_rows_in_chunk``).

    Keyed by the row-group's identity, NOT by epoch or arrival order — the
    same chunk permutes identically in every epoch and every session, which
    is what keeps checkpoint-resume row skips exact. The permutation is
    computed by argsorting a splitmix64 hash of each row index (NOT a numpy
    Generator stream, whose bit-exactness across numpy versions is not
    guaranteed — a resume under a different numpy must reproduce it).
    """
    import hashlib
    drop_idx = shuffle_row_drop_partition[0] if shuffle_row_drop_partition else 0
    digest = hashlib.md5('{}:{}:{}:{}:{}'.format(
        seed, dataset_hash, piece_path, row_group, drop_idx).encode()).digest()
    base = np.uint64(int.from_bytes(digest[:8], 'little'))
    z = np.arange(n_rows, dtype=np.uint64) + base
    # splitmix64 finalizer: well-mixed, pure uint64 arithmetic (wraps mod
    # 2^64 in numpy), identical on every platform/version.
    z = (z + np.uint64(0x9E3779B97F4A7C15))
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    z = z ^ (z >> np.uint64(31))
    return np.argsort(z, kind='stable')
