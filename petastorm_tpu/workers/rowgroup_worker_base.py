"""Shared machinery for row-group workers (dict & arrow flavors).

Hosts the per-worker Parquet file-handle LRU cache and the
shuffle-row-drop-partition slice computation so the two worker
implementations cannot drift apart.
"""

from collections import OrderedDict

import pyarrow.parquet as pq

from petastorm_tpu.workers import WorkerBase

_PARQUET_FILE_CACHE_SIZE = 32


class RowGroupWorkerBase(WorkerBase):
    """Worker base with a lazily-connected store and an LRU of open files."""

    def __init__(self, worker_id, publish_func, args):
        super().__init__(worker_id, publish_func, args)
        self._store = None
        self._file_cache = OrderedDict()

    def initialize(self):
        self._store = self.args['store_factory']()

    def _parquet_file(self, path):
        pf = self._file_cache.get(path)
        if pf is not None:
            self._file_cache.move_to_end(path)
            return pf
        if len(self._file_cache) >= _PARQUET_FILE_CACHE_SIZE:
            _, old = self._file_cache.popitem(last=False)  # least recently used
            try:
                old.close()
            except Exception:  # noqa: BLE001
                pass
        pf = pq.ParquetFile(self._store.open_file(path))
        self._file_cache[path] = pf
        return pf

    def shutdown(self):
        for pf in self._file_cache.values():
            try:
                pf.close()
            except Exception:  # noqa: BLE001
                pass
        self._file_cache = OrderedDict()


def compute_row_slice(num_rows, shuffle_row_drop_partition, ngram=None):
    """(start, stop) row bounds for one drop-partition of a row-group.

    Parity: reference ``py_dict_reader_worker.py:254-274`` — for ngram the
    kept slice is tail-extended so windows spanning the boundary survive.
    Returns None when the whole range is kept.
    """
    if shuffle_row_drop_partition is None:
        return None
    this_partition, num_partitions = shuffle_row_drop_partition
    if num_partitions <= 1:
        return None
    bounds = [int(round(i * num_rows / num_partitions)) for i in range(num_partitions + 1)]
    start, stop = bounds[this_partition], bounds[this_partition + 1]
    if ngram is not None:
        stop = min(num_rows, stop + ngram.length - 1)
    return start, stop
