"""Worker pools: ventilator-fed parallel execution with bounded results queues.

Parity: reference ``petastorm/workers_pool/`` — sentinel messages
(``workers_pool/__init__.py:16-26``), ``WorkerBase`` protocol
(``worker_base.py:18-35``), thread/process/dummy pools, ventilator.

Robustness extensions (no reference equivalent): item-processed acks carry
``(worker_id, seq)`` so the process pools can supervise workers and
re-ventilate a dead worker's in-flight items (``supervision.py``), and
workers may *quarantine* a poison row-group (skip-and-record instead of
crashing the epoch) when the reader opted in via ``error_budget`` — the
:class:`RowGroupQuarantined` control message flows back to the consumer,
which enforces the budget.
"""


class EmptyResultError(Exception):
    """Raised by ``pool.get_results()`` when all work is done (end of epoch)."""


class TimeoutWaitingForResultError(Exception):
    pass


class VentilatedItemProcessedMessage(object):
    """Sentinel a worker publishes after fully processing one ventilated item.

    ``worker_id``/``seq`` identify which worker finished which dispatched
    item (``None`` from pools that don't track assignment, e.g. threads).
    """

    def __init__(self, worker_id=None, seq=None):
        self.worker_id = worker_id
        self.seq = seq


class RowGroupQuarantined(object):
    """Control message: a worker skipped a poison ventilated item.

    Published INSTEAD of crashing when the reader opted in via
    ``error_budget`` and the failure is one of
    ``errors.QUARANTINE_EXCEPTION_TYPES``. Counts as an item-processed ack
    for in-flight bookkeeping; the consumer side routes it to the pool's
    ``quarantine_sink`` (the reader's budget), which raises
    ``RowGroupQuarantinedError`` once the budget is spent.

    ``item`` is a pickle-safe summary of the ventilated kwargs (the raw
    kwargs may close over un-picklable predicates/transforms).
    ``decode_error`` carries the native codec's own error string when the
    failure came out of the C++ batch decoder
    (``DecodeFieldError.native_error``) — a corrupt image then reads as
    e.g. ``'not a JPEG or PNG stream'`` in the quarantine diagnostics
    instead of a bare exception repr.
    """

    def __init__(self, worker_id, item, error, traceback_str, seq=None,
                 decode_error=None):
        self.worker_id = worker_id
        self.item = item
        self.error = error
        self.traceback_str = traceback_str
        self.seq = seq
        self.decode_error = decode_error


def _summarize_item(args, kwargs):
    """Pickle/JSON-safe description of a ventilated item."""
    summary = {}
    if isinstance(kwargs, dict):
        for key in ('piece_index', 'shuffle_row_drop_partition'):
            value = kwargs.get(key)
            if isinstance(value, (int, str)) or (
                    isinstance(value, tuple)
                    and all(isinstance(v, int) for v in value)):
                summary[key] = value
        det = kwargs.get('pst_det')
        if isinstance(det, dict):
            # Deterministic-mode identity: the consumer-side resequencer
            # needs the quarantined item's seq to fill its hole (the item
            # will never publish a chunk) — see Reader's quarantine sink.
            summary['pst_det'] = {k: det.get(k)
                                  for k in ('seq', 'epoch', 'pos')}
    if not summary and args:
        summary['args'] = repr(args)[:120]
    return summary


def quarantine_record_for(worker, exc, args, kwargs):
    """``RowGroupQuarantined`` for this failure, or ``None`` when it must
    surface as a fatal error (reader didn't opt in, or the exception class
    is not a data/IO failure)."""
    worker_args = getattr(worker, 'args', None)
    if not (isinstance(worker_args, dict)
            and worker_args.get('quarantine_poison_rowgroups')):
        return None
    from petastorm_tpu.errors import QUARANTINE_EXCEPTION_TYPES
    if not isinstance(exc, QUARANTINE_EXCEPTION_TYPES):
        return None
    import traceback
    return RowGroupQuarantined(
        worker_id=getattr(worker, 'worker_id', None),
        item=_summarize_item(args, kwargs),
        error='{}: {}'.format(type(exc).__name__, exc),
        traceback_str=traceback.format_exc(),
        decode_error=getattr(exc, 'native_error', None))


def deliver_quarantine(pool, record):
    """Route a quarantine record to the pool's sink; raise when no budget is
    configured (a record with no sink means a worker quarantined something
    the consumer never opted into — surface it loudly)."""
    sink = getattr(pool, 'quarantine_sink', None)
    if sink is None:
        from petastorm_tpu.errors import RowGroupQuarantinedError
        raise RowGroupQuarantinedError(
            'worker {} quarantined {} ({}) but no quarantine sink/error '
            'budget is configured'.format(record.worker_id, record.item,
                                          record.error),
            quarantined=[record])
    sink(record)


class WorkerBase(object):
    """Parity: reference ``workers_pool/worker_base.py:18-35``."""

    def __init__(self, worker_id, publish_func, args):
        self.worker_id = worker_id
        self.publish_func = publish_func
        self.args = args

    def initialize(self):
        """Called once in the worker context before processing items."""

    def process(self, *args, **kwargs):
        raise NotImplementedError

    def publish_func(self, data):  # pragma: no cover - replaced in __init__
        raise NotImplementedError

    def shutdown(self):
        """Called when the pool stops."""
