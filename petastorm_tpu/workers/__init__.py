"""Worker pools: ventilator-fed parallel execution with bounded results queues.

Parity: reference ``petastorm/workers_pool/`` — sentinel messages
(``workers_pool/__init__.py:16-26``), ``WorkerBase`` protocol
(``worker_base.py:18-35``), thread/process/dummy pools, ventilator.
"""


class EmptyResultError(Exception):
    """Raised by ``pool.get_results()`` when all work is done (end of epoch)."""


class TimeoutWaitingForResultError(Exception):
    pass


class VentilatedItemProcessedMessage(object):
    """Sentinel a worker publishes after fully processing one ventilated item."""


class WorkerBase(object):
    """Parity: reference ``workers_pool/worker_base.py:18-35``."""

    def __init__(self, worker_id, publish_func, args):
        self.worker_id = worker_id
        self.publish_func = publish_func
        self.args = args

    def initialize(self):
        """Called once in the worker context before processing items."""

    def process(self, *args, **kwargs):
        raise NotImplementedError

    def publish_func(self, data):  # pragma: no cover - replaced in __init__
        raise NotImplementedError

    def shutdown(self):
        """Called when the pool stops."""
