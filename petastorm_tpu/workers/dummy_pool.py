"""Single-threaded synchronous pool: work happens inside ``get_results()``.

Parity: reference ``petastorm/workers_pool/dummy_pool.py`` — used for
debugging, deterministic tests, and profiler-friendly in-main-thread
execution (``dummy_pool.py:24-25``).
"""

import threading
from collections import deque

from petastorm_tpu.workers import (EmptyResultError, RowGroupQuarantined,
                                   VentilatedItemProcessedMessage,
                                   deliver_quarantine, quarantine_record_for)


class DummyPool(object):
    #: Readers build the ventilator with ``inline=True`` for this pool: work
    #: happens on the consumer thread, so a feeder thread (and its GIL
    #: ping-pong — ~50% of the 1-core per-row path, PROFILE_r04.md) would
    #: be pure overhead. ``get_results`` pumps the ventilator itself.
    inline_ventilation = True

    def __init__(self, workers_count=None):
        self._results = deque()
        self._ventilated = deque()
        self._worker = None
        self._ventilator = None
        self._stopped = False
        # Serializes item processing (consumer thread) against worker
        # shutdown (often another thread, e.g. JaxLoader.stop() while its
        # staging thread is mid-decode): closing parquet file handles under
        # an in-flight read segfaults inside pyarrow.
        self._work_lock = threading.Lock()
        self._shutdown_done = False
        #: Set by the Reader when ``error_budget`` is enabled.
        self.quarantine_sink = None

    def start(self, worker_class, worker_args=None, ventilator=None):
        self._worker = worker_class(0, self._results.append, worker_args)
        self._worker.initialize()
        self._ventilator = ventilator
        if ventilator is not None:
            ventilator._ventilate_fn = self.ventilate
            ventilator.start()

    def ventilate(self, *args, **kwargs):
        self._ventilated.append((args, kwargs))

    def get_results(self):
        while True:
            if self._stopped and not self._results:
                # Stop requested from another thread: don't start decoding
                # further items whose file handles are about to be closed.
                raise EmptyResultError()
            while self._results:
                result = self._results.popleft()
                if isinstance(result, VentilatedItemProcessedMessage):
                    if self._ventilator is not None:
                        self._ventilator.processed_item()
                    continue
                if isinstance(result, RowGroupQuarantined):
                    if self._ventilator is not None:
                        self._ventilator.processed_item()
                    deliver_quarantine(self, result)
                    continue
                if isinstance(result, Exception):
                    raise result
                return result
            if not self._ventilated:
                if self._ventilator is None:
                    raise EmptyResultError()
                if getattr(self._ventilator, 'inline', False):
                    # Everything runs on this thread: pump the ventilator
                    # directly instead of waiting on a feeder thread.
                    if not self._ventilator.pump() and not self._ventilated:
                        if self._ventilator.completed() or self._stopped:
                            raise EmptyResultError()
                        raise RuntimeError(
                            'inline ventilator stalled: nothing ventilated, '
                            'nothing queued, not completed')
                # Read `completed` BEFORE re-checking the deque: once completed
                # is observed no further ventilation can occur, so a still-empty
                # deque really means end of data (no lost-item race).
                elif self._ventilator.completed():
                    if not self._ventilated and not self._results:
                        raise EmptyResultError()
                else:
                    continue
            if not self._ventilated:
                continue
            args, kwargs = self._ventilated.popleft()
            try:
                with self._work_lock:
                    if self._shutdown_done:
                        raise EmptyResultError()
                    self._worker.process(*args, **kwargs)
                self._results.append(VentilatedItemProcessedMessage())
            except EmptyResultError:
                raise
            except Exception as e:  # noqa: BLE001 - parity: exceptions surface to consumer
                record = quarantine_record_for(self._worker, e, args, kwargs)
                self._results.append(record if record is not None else e)

    def stop(self):
        self._stopped = True
        if self._ventilator is not None:
            self._ventilator.stop()
        # Worker shutdown (closes parquet handles) waits for any in-flight
        # process() call on the consuming thread — see _work_lock.
        self._shutdown_worker()

    def _shutdown_worker(self):
        if self._worker is None:
            return
        with self._work_lock:
            if not self._shutdown_done:
                self._shutdown_done = True
                self._worker.shutdown()

    def join(self):
        self._shutdown_worker()

    @property
    def diagnostics(self):
        return {'output_queue_size': len(self._results),
                'ventilation_queue_size': len(self._ventilated)}

    @property
    def results_qsize(self):
        return len(self._results)
