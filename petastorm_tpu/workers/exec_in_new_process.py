"""Spawn (not fork) a Python callable in a brand-new interpreter.

Parity: reference ``petastorm/workers_pool/exec_in_new_process.py`` — the
callable + args are dill-dumped to a temp file and a fresh ``python -m``
process re-hydrates and runs them (``:26-69``). Spawning avoids inheriting
JVM/driver/TPU-client state into data workers (``process_pool.py:15-17`` —
on TPU-VMs, forking a process holding a libtpu client handle is unsafe).
"""

import os
import subprocess
import sys

import dill


def exec_in_new_process(func, *args, **kwargs):
    """Launch ``func(*args, **kwargs)`` in a new python process; returns Popen."""
    import tempfile
    fd, payload_path = tempfile.mkstemp(suffix='.dill')
    with os.fdopen(fd, 'wb') as f:
        # sys.path rides along (as a separate first record, so it can be
        # applied before the func record resolves imports) — by-reference
        # pickles of classes in e.g. test modules then import cleanly.
        dill.dump(list(sys.path), f)
        dill.dump((func, args, kwargs), f, recurse=False)
    # The `-m` bootstrap must be able to import petastorm_tpu BEFORE the
    # payload's sys.path record is applied, so propagate the parent's
    # sys.path through PYTHONPATH (covers uninstalled/path-inserted uses).
    env = dict(os.environ)
    parent_paths = [p for p in sys.path if p]
    existing = env.get('PYTHONPATH')
    if existing:
        parent_paths.append(existing)
    env['PYTHONPATH'] = os.pathsep.join(parent_paths)
    process = subprocess.Popen(
        [sys.executable, '-m', 'petastorm_tpu.workers.exec_in_new_process', payload_path],
        close_fds=True, env=env)
    return process


def _main():
    payload_path = sys.argv[1]
    with open(payload_path, 'rb') as f:
        parent_sys_path = dill.load(f)
        for entry in reversed(parent_sys_path):
            if entry not in sys.path:
                sys.path.insert(0, entry)
        func, args, kwargs = dill.load(f)
    try:
        os.unlink(payload_path)
    except OSError:
        pass
    func(*args, **kwargs)


if __name__ == '__main__':
    _main()
