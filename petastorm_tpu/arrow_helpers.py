"""Arrow helpers: fixed-size batch re-chunking.

Parity: reference ``petastorm/pyarrow_helpers/batching_table_queue.py:20-79``
(FIFO of Arrow record batches re-chunked to an exact batch size). In this
framework it is also the building block the JAX loader's exact-global-batch
re-chunking mirrors (``jax_loader.iter_numpy_batches``): TPU collectives need
every host to deliver identical batch shapes, so exact re-chunking is
load-bearing here, not an unused utility.
"""

from collections import deque

import pyarrow as pa


class BatchingTableQueue(object):
    """FIFO over Arrow tables that yields tables of exactly ``batch_size`` rows.

    ``put`` accepts tables of arbitrary (and varying) row counts; ``get``
    returns a table of exactly ``batch_size`` rows composed from queued data
    in arrival order (zero-copy slices of the underlying record batches).
    """

    def __init__(self, batch_size):
        if batch_size < 1:
            raise ValueError('batch_size must be >= 1, got {}'.format(batch_size))
        self._batch_size = batch_size
        self._chunks = deque()   # record batches, possibly partially consumed
        self._offset = 0         # rows already consumed from chunks[0]
        self._available = 0
        self._schema = None

    def __len__(self):
        """Rows currently buffered."""
        return self._available

    def empty(self):
        """True when fewer than ``batch_size`` rows are buffered (a ``get``
        would not be able to return a full batch)."""
        return self._available < self._batch_size

    def put(self, table_or_batch):
        """Append a ``pa.Table`` or ``pa.RecordBatch``."""
        if isinstance(table_or_batch, pa.RecordBatch):
            batches = [table_or_batch]
            schema = table_or_batch.schema
        else:
            batches = table_or_batch.to_batches()
            schema = table_or_batch.schema
        if self._schema is None:
            self._schema = schema
        elif not schema.equals(self._schema):
            raise ValueError('Schema mismatch: queue built over {} got {}'.format(
                self._schema, schema))
        for batch in batches:
            if batch.num_rows:
                self._chunks.append(batch)
                self._available += batch.num_rows

    def get(self):
        """A ``pa.Table`` of exactly ``batch_size`` rows (raises if ``empty``)."""
        if self.empty():
            raise IndexError('BatchingTableQueue underflow: {} rows buffered, '
                             'batch_size={}'.format(self._available, self._batch_size))
        needed = self._batch_size
        out = []
        while needed:
            head = self._chunks[0]
            remaining = head.num_rows - self._offset
            take = min(needed, remaining)
            out.append(head.slice(self._offset, take))
            needed -= take
            if take == remaining:
                self._chunks.popleft()
                self._offset = 0
            else:
                self._offset += take
        self._available -= self._batch_size
        return pa.Table.from_batches(out, schema=self._schema)
