"""Row predicates evaluated inside reader workers.

Parity: reference ``petastorm/predicates.py`` — a small combinator library of
predicates with ``get_fields()`` (columns the predicate needs, enabling the
two-phase predicate read at ``py_dict_reader_worker.py:188-252``) and
``do_include(values)``.
"""

import hashlib

import numpy as np


class PredicateBase(object):
    """Predicate interface: which fields it needs, and the row test."""

    def get_fields(self):
        raise NotImplementedError

    def do_include(self, values):
        """``values``: dict of field name -> value for fields in get_fields()."""
        raise NotImplementedError


class in_set(PredicateBase):
    """Include rows whose field value is in a given set."""

    def __init__(self, values, predicate_field):
        self._values = set(values)
        self._field = predicate_field

    def get_fields(self):
        return {self._field}

    def do_include(self, values):
        return values[self._field] in self._values


class in_intersection(PredicateBase):
    def __init__(self, values, predicate_field):
        self._values = set(values)
        self._field = predicate_field

    def get_fields(self):
        return {self._field}

    def do_include(self, values):
        return bool(self._values.intersection(values[self._field]))


class in_negate(PredicateBase):
    """Logical NOT of another predicate."""

    def __init__(self, predicate):
        self._predicate = predicate

    def get_fields(self):
        return self._predicate.get_fields()

    def do_include(self, values):
        return not self._predicate.do_include(values)


class in_reduce(PredicateBase):
    """Reduce multiple predicates with e.g. ``all`` or ``any``."""

    def __init__(self, predicate_list, reduce_func):
        self._predicates = list(predicate_list)
        self._reduce = reduce_func

    def get_fields(self):
        fields = set()
        for p in self._predicates:
            fields |= set(p.get_fields())
        return fields

    def do_include(self, values):
        return self._reduce([p.do_include(values) for p in self._predicates])


class in_lambda(PredicateBase):
    """Arbitrary user lambda over a declared list of fields.

    The function receives one **positional argument per declared field, in
    declaration order** (parity: reference ``predicates.py:74-101`` —
    ``in_lambda(['id'], lambda id: id < 5)``), with ``state_arg`` appended
    when given.
    """

    def __init__(self, fields, func, state_arg=None):
        if not isinstance(fields, (list, tuple)):
            raise ValueError('in_lambda fields must be a list')
        self._ordered_fields = list(fields)
        self._func = func
        self._state = state_arg

    def get_fields(self):
        return set(self._ordered_fields)

    def do_include(self, values):
        args = [values[f] for f in self._ordered_fields]
        if self._state is not None:
            args.append(self._state)
        return self._func(*args)


def _stable_hash_fraction(value, num_buckets):
    digest = hashlib.md5(str(value).encode('utf-8')).hexdigest()
    return int(digest, 16) % num_buckets


class in_pseudorandom_split(PredicateBase):
    """Deterministic train/val/test split on a hash of a key field.

    Parity: reference ``petastorm/predicates.py`` ``in_pseudorandom_split`` —
    fraction list selects which bucket range is included.
    """

    _BUCKETS = 10000

    def __init__(self, fraction_list, subset_index, predicate_field):
        if not np.isclose(sum(fraction_list), 1.0) and sum(fraction_list) > 1.0:
            raise ValueError('fractions must sum to <= 1.0')
        self._fractions = list(fraction_list)
        self._index = subset_index
        self._field = predicate_field
        bounds = np.cumsum([0.0] + self._fractions)
        self._low = int(bounds[subset_index] * self._BUCKETS)
        self._high = int(bounds[subset_index + 1] * self._BUCKETS)

    def get_fields(self):
        return {self._field}

    def do_include(self, values):
        bucket = _stable_hash_fraction(values[self._field], self._BUCKETS)
        return self._low <= bucket < self._high
