"""TransformSpec: user transforms executed inside reader workers.

Parity: reference ``petastorm/transform.py`` — a function applied per
row (dict) or per batch (pandas DataFrame for the Arrow worker), plus
declarative schema edits (``edit_fields``) and ``removed_fields`` so the
post-transform schema remains statically known (``transform.py:19-64``).
"""

from petastorm_tpu.unischema import Unischema, UnischemaField


class TransformSpec(object):
    def __init__(self, func=None, edit_fields=None, removed_fields=None, selected_fields=None,
                 version=None):
        """
        :param func: callable applied inside the worker. For row readers it
            receives/returns a dict; for batch (Arrow) readers a pandas
            DataFrame.
        :param edit_fields: list of ``UnischemaField`` (or 4/5-tuples
            ``(name, dtype, shape, [codec,] nullable)``) added/replaced in the
            output schema.
        :param removed_fields: list of field names removed by ``func``.
        :param selected_fields: if set, the output schema keeps only these
            field names (applied after edits/removals).
        :param version: optional caller-owned version tag (str/int) recorded
            into batch provenance records (``petastorm_tpu.lineage``): user
            transform code cannot be hashed, so the tag is what lets an
            audit tell two trainings apart when only the transform changed.
        """
        self.func = func
        self.edit_fields = [self._as_field(f) for f in (edit_fields or [])]
        self.removed_fields = list(removed_fields or [])
        self.selected_fields = list(selected_fields) if selected_fields is not None else None
        self.version = version

    @staticmethod
    def _as_field(f):
        if isinstance(f, UnischemaField):
            return f
        if isinstance(f, (tuple, list)):
            if len(f) == 4:
                name, dtype, shape, nullable = f
                return UnischemaField(name, dtype, shape, None, nullable)
            if len(f) == 5:
                name, dtype, shape, codec, nullable = f
                return UnischemaField(name, dtype, shape, codec, nullable)
        raise TypeError('edit_fields entries must be UnischemaField or 4/5-tuples, got {!r}'.format(f))


def transform_schema(schema, transform_spec):
    """Compute the post-transform schema.

    Parity: reference ``petastorm/transform.py:43-64``.
    """
    fields = dict(schema.fields)
    for name in transform_spec.removed_fields:
        fields.pop(name, None)
    for f in transform_spec.edit_fields:
        fields[f.name] = f
    if transform_spec.selected_fields is not None:
        missing = [n for n in transform_spec.selected_fields if n not in fields]
        if missing:
            raise ValueError('selected_fields not present after transform: {}'.format(missing))
        fields = {n: fields[n] for n in transform_spec.selected_fields}
    return Unischema(schema.name, list(fields.values()))
