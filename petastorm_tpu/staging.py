"""Pipelined staging engine: recycled host-batch arenas + overlapped
assemble/dispatch.

PROFILE_r05 shows the steady-state input pipeline is collate/memcpy-bound
and that staging never overlaps anything (``h2d_overlap_frac`` 0.0,
``stage_dispatch_s`` + ``consumer_wait_s`` dominating the pipeline wall).
This module is the fix, in the tf.data (arXiv:2101.12127) / MinatoLoader
(arXiv:2509.10712) shape: software pipelining between batch assembly and
device dispatch, plus buffer reuse so the collate path stops allocating a
fresh host batch every step.

Three pieces, each independently testable without jax:

``ArenaPool`` / ``HostArena``
    A bounded pool of preallocated per-field host buffers sized to one
    batch. The batch assembler fills arena slices in place
    (``np.copyto``/``out=``) instead of ``np.stack``/``np.concatenate``
    allocating every batch; the pool recycles an arena only once the
    dispatch stage reports its transfer done AND every consumer-visible
    view of it has been dropped (``add_hold`` — on backends where
    ``device_put`` is zero-copy the staged array aliases the arena, so
    "transfer done" alone is not permission to overwrite). Exhaustion
    applies backpressure (bounded, stop-aware wait); a wait that outlives
    ``grow_timeout_s`` allocates past ``depth`` rather than deadlocking a
    consumer that legitimately holds many batches (e.g.
    ``superbatches(k)``). Growth is sticky — ``depth`` rises to the
    high-water mark, so the timeout is paid once per working-set
    increase, not per cycle — and every allocation is visible in
    ``arena_alloc``.

``OverlapMeter``
    Wall-clock co-activity of named pipeline stages. ``overlap_s`` is the
    time during which two or more stages were simultaneously inside their
    tracked section — the direct measurement of "collate of batch N+1
    overlaps the transfer of batch N".

``StagingEngine``
    Two threads replacing the single serial stage loop: an **assemble**
    thread that drives the host-batch iterator (filling arenas), and a
    **dispatch** thread that issues the device puts and keeps a bounded
    window of in-flight transfers, blocking on the oldest when the window
    fills. Delivery order is preserved; stop/fault semantics follow PR 1
    (stop-aware puts everywhere, no thread outlives ``stop()``, in-flight
    arenas are reclaimed on shutdown).
"""

import logging
import queue
import threading
import time
import weakref
from collections import deque
from contextlib import contextmanager

import numpy as np

logger = logging.getLogger(__name__)

_DONE = object()        # assemble exhausted its iterator

#: Thread-name prefix of the per-device dispatch streams
#: (:class:`DeviceStager`); registered in
#: ``petastorm_tpu.analysis.registry`` so the conftest leak guard and the
#: pstlint thread-lifecycle checker both know who joins them.
DEVICE_PUT_THREAD_PREFIX = 'pst-device-put'

#: Per-field offset alignment inside a pinned arena slab. Page alignment
#: keeps every field's buffer on its own page boundary — the transfer
#: granularity DMA engines and ``mlock`` both work in.
PINNED_FIELD_ALIGN = 4096


def _pinned_slab_layout(spec):
    """``({name: (offset, size)}, total)`` for one arena slab: every field
    starts on a :data:`PINNED_FIELD_ALIGN` boundary."""
    offsets, total = {}, 0
    for name, (shape, dtype) in spec.items():
        size = int(np.prod(shape)) * np.dtype(dtype).itemsize
        offsets[name] = (total, size)
        padded = -(-max(size, 1) // PINNED_FIELD_ALIGN) * PINNED_FIELD_ALIGN
        total += padded
    return offsets, total


_alias_probe_memo = {}


def staging_aliases_host(jax):
    """True when ``jax.device_put`` on this backend may return an array
    aliasing the source host buffer (observed on the CPU backend for large
    aligned arrays) — recycling a staged-from arena would then corrupt
    batches the consumer still holds. Probed once per process per backend
    with a buffer large enough to take the zero-copy path; the transfer is
    fenced before the source is mutated so a copying backend whose DMA is
    still in flight can't be misread as aliasing. Any failure (or a
    misread) errs toward True — the aliasing mode is the conservative one
    (GC-gated recycling).
    """
    try:
        backend = jax.default_backend()
    except Exception:  # noqa: BLE001 - unknown backend: assume the worst
        return True
    if backend not in _alias_probe_memo:
        try:
            src = np.zeros(1 << 20, np.uint8)
            staged = jax.device_put(src)
            jax.block_until_ready(staged)
            src[0] = 1
            _alias_probe_memo[backend] = int(np.asarray(staged)[0]) == 1
        except Exception:  # noqa: BLE001
            _alias_probe_memo[backend] = True
    return _alias_probe_memo[backend]


def willneed_arrays(arrays, _mmap=None):
    """madvise(WILLNEED) the mmaps backing any mmap-based arrays.

    The NVMe chunk store (``petastorm_tpu.chunk_store``) serves decoded
    chunks as numpy views over a read-only mmap; the arena fill then
    copies mmap -> arena (``np.copyto``), and on a cold page cache every
    copied cache line is a blocking major fault inside the assemble
    thread. Hinting the whole backing mapping when the chunk *arrives*
    (one syscall per chunk) lets the kernel read the extents ahead while
    earlier batches collate. Non-mmap arrays walk a short ``.base`` chain
    and fall out — the call is safe (and near-free) on every chunk.
    Returns the number of distinct mappings hinted."""
    import mmap as mmap_mod
    if _mmap is None:
        _mmap = mmap_mod
    if not hasattr(_mmap.mmap, 'madvise'):  # pragma: no cover - py<3.8/win
        return 0
    hinted, seen = 0, set()
    for arr in arrays:
        base = arr
        while isinstance(base, np.ndarray) and base.base is not None:
            base = base.base
        if isinstance(base, memoryview):
            base = base.obj
        if isinstance(base, _mmap.mmap) and id(base) not in seen:
            seen.add(id(base))
            try:
                base.madvise(_mmap.MADV_WILLNEED)
                hinted += 1
            except (OSError, ValueError):  # pragma: no cover - advisory only
                continue
    return hinted


class HostArena(object):
    """One batch's worth of recyclable per-field host buffers.

    ``view_epoch`` is the arena's recycle generation: bumped every time
    the buffers return to the pool's free list, i.e. every time their
    bytes stop belonging to the batch a consumer may still be looking at.
    With the sanitizer armed (``PETASTORM_TPU_SANITIZE``,
    :mod:`petastorm_tpu.analysis.sanitize`) reclaim additionally poisons
    the buffers (0xCB fill) and views handed out via :meth:`borrow` carry
    the epoch as a borrow tag — touching one after reclaim raises
    ``StaleViewError`` at the stale access instead of silently reading a
    different batch's bytes."""

    def __init__(self, pool, spec, slab=None):
        # spec: {name: (shape, dtype)}; shape includes the batch dim.
        # With a pinned slab the buffers are page-aligned (optionally
        # mlocked) carve-outs of one DMA-friendly allocation; without one
        # they are plain np.empty — bit-for-bit the same to every consumer.
        if slab is not None:
            offsets, _ = _pinned_slab_layout(spec)
            self.buffers = {}
            for name, (shape, dtype) in spec.items():
                off, size = offsets[name]
                self.buffers[name] = (slab.array[off:off + size]
                                      .view(dtype).reshape(shape))
        else:
            self.buffers = {name: np.empty(shape, dtype)
                            for name, (shape, dtype) in spec.items()}
        self._slab = slab   # keeps the mapping alive while buffers exist
        self.pinned = slab is not None
        self._pool = pool
        self._lock = threading.Lock()
        self._holds = 0
        self._retired = False
        self._reclaimed = False
        self.view_epoch = 0
        # Device-sharded layout memo: per-device contiguous sub-slices of
        # each buffer, built once per arena and reused on every recycle
        # (the buffers persist, so the views stay valid) — zero re-layout
        # work at dispatch time. Keyed by (field, bounds) because a per-
        # field sharding dict may split fields across different device
        # counts.
        self._shard_views = {}

    def shard_views(self, name, bounds=None):
        """Per-device contiguous sub-slices of buffer ``name`` along the
        batch dim. ``bounds`` is a tuple of ``(start, stop)`` row ranges
        (default: the layout the pool learned via
        :meth:`ArenaPool.learn_shard_layout` — the dispatch path's form);
        the views are memoized on the arena, so after the first batch a
        dispatch pays zero slicing or layout work — the collate path
        already landed each device's rows contiguously in the recycled
        buffer."""
        if bounds is None:
            cached = self._shard_views.get((name, None))
            if cached is not None:
                return cached
            layout = self._pool.shard_layout if self._pool else None
            bounds = (layout or {}).get(name)
            if bounds is None:
                raise KeyError(
                    'no shard layout learned for field {!r}'.format(name))
            views = self.shard_views(name, bounds)
            self._shard_views[(name, None)] = views
            return views
        key = (name, tuple(bounds))
        views = self._shard_views.get(key)
        if views is None:
            buf = self.buffers[name]
            views = tuple(buf[start:stop] for start, stop in key[1])
            self._shard_views[key] = views
        return views

    def borrow(self, array):
        """Borrow-tag ``array`` (one of this arena's buffers or a view of
        one) against the current epoch. No-op passthrough unless the
        sanitizer is armed."""
        from petastorm_tpu.analysis import sanitize
        return sanitize.guard_view(array, self)

    def borrowed_buffers(self):
        """The buffer dict as handed to the batch assembler: borrow-tagged
        views when the sanitizer is armed, the raw buffers otherwise."""
        from petastorm_tpu.analysis import sanitize
        if not sanitize.sanitize_active():
            return self.buffers
        return {name: sanitize.guard_view(buf, self)
                for name, buf in self.buffers.items()}

    def _on_reclaim(self):
        """The buffers are about to rejoin the free list: any view still
        out there is now stale. Bump the borrow epoch (always — one int)
        and poison the bytes (sanitizer only)."""
        self.view_epoch += 1
        from petastorm_tpu.analysis import sanitize
        sanitize.poison(self.buffers.values())

    @property
    def nbytes(self):
        return sum(b.nbytes for b in self.buffers.values())

    def add_hold(self, obj):
        """Keep this arena out of the free list until ``obj`` is garbage
        collected (used when staged arrays alias the arena's memory)."""
        with self._lock:
            self._holds += 1
        weakref.finalize(obj, self._drop_hold)

    def _drop_hold(self):
        with self._lock:
            self._holds -= 1
            ready = (self._retired and self._holds == 0
                     and not self._reclaimed)
            if ready:
                self._retired = False
                self._reclaimed = True
        if ready:
            self._pool._reclaim(self)

    def retire(self):
        """Transfer done: return to the pool once no holds remain.
        Idempotent — stop-path drains can race the normal retire."""
        with self._lock:
            if self._reclaimed:
                return
            if self._holds:
                self._retired = True
                return
            self._reclaimed = True
        self._pool._reclaim(self)


class ArenaPool(object):
    """Bounded pool of :class:`HostArena` with backpressure and counters.

    The assembler calls :meth:`get_buffers` (blocking, stop-aware) and the
    engine pairs the yielded batch with :meth:`claim_pending`. Batches
    whose shapes differ from the pool's spec (e.g. a ``partial`` final
    batch) bypass the pool (``get_buffers`` returns ``None``).
    """

    def __init__(self, depth, stop_event=None, grow_timeout_s=0.5,
                 tracer=None, meter=None, meter_stage='assemble',
                 heartbeat=None, pinned=None):
        if depth < 1:
            raise ValueError('ArenaPool depth must be >= 1, got {}'.format(depth))
        self._depth = depth
        # Pinned (DMA-friendly) allocation mode: new arenas carve their
        # buffers out of page-aligned, pre-faulted, best-effort-mlocked
        # slabs (petastorm_tpu.native.pinned). None resolves the
        # PETASTORM_TPU_PINNED_ARENAS env ('1' arms it); allocation
        # failure falls back to np.empty per arena, so the mode can never
        # wedge a pipeline. set_pinned() retargets live (autotune toggle;
        # the governor's advisory rung unpins growth — mlocked pages are
        # exactly the ones the kernel cannot reclaim under pressure).
        if pinned is None:
            import os
            pinned = os.environ.get('PETASTORM_TPU_PINNED_ARENAS', '') == '1'
        self._pinned = bool(pinned)
        self._pinned_bytes = 0
        self._pinned_locked = 0
        self._pinned_mode = None
        self._pinned_fallback_logged = False
        self._stop = stop_event if stop_event is not None else threading.Event()
        self._grow_timeout_s = grow_timeout_s
        # Health hookup: while the assembler is parked waiting for an arena
        # its heartbeat reads 'arena-wait' and goes stale — the watchdog
        # then classifies the stall as arena-pool-wedged rather than
        # blaming collate work.
        self._heartbeat = heartbeat
        # Backpressure waits happen inside the assembler's tracked section;
        # pausing the meter keeps them out of busy/overlap accounting (an
        # arena-starved pipeline must not read as perfectly overlapped —
        # arena_wait_s reports the stall instead).
        self._meter = meter
        self._meter_stage = meter_stage
        if tracer is None:
            from petastorm_tpu.trace import NullTracer
            tracer = NullTracer()
        self._tracer = tracer
        self._cond = threading.Condition()
        self._free = []
        self._spec = None
        self._allocated = 0
        self._pending = None
        # Device-sharded layout ({field: ((start, stop), ...)} row bounds),
        # learned once per schema from the NamedSharding by the loader;
        # arenas consult it to memoize per-device sub-slice views.
        self._shard_layout = None
        # counters (reset_stats() zeroes these, never the pool itself)
        self._alloc = 0
        self._reuse = 0
        self._wait_s = 0.0
        # Registry mirror (petastorm_tpu.metrics): per-acquisition wait
        # latency — the machine-scrapable arena-backpressure signal.
        from petastorm_tpu import metrics as metrics_mod
        self._m_wait = metrics_mod.histogram(
            'pst_arena_wait_seconds',
            'Assembler blocked time per arena acquisition (backpressure)')
        self._m_pinned = metrics_mod.gauge(
            'pst_arena_pinned_bytes',
            'Host bytes in live pinned (page-aligned/mlocked) arena slabs '
            'across all pools (inc/dec per slab lifetime)')

    def _matches(self, spec):
        if self._spec is None:
            self._spec = dict(spec)
            return True
        return spec == self._spec

    def get_buffers(self, spec):
        """Buffers for one batch of ``spec`` ({name: (shape, dtype)}), or
        ``None`` when the spec mismatches the pool or the pool is stopping.
        Blocks (stop-aware) while every arena is out; waits longer than
        ``grow_timeout_s`` allocate past ``depth`` instead of deadlocking.
        """
        with self._cond:
            if not self._matches(spec):
                return None
            waited = 0.0
            waiting_hb = False
            while True:
                if self._stop.is_set():
                    return None
                if self._free:
                    arena = self._free.pop()
                    arena._reclaimed = False
                    self._reuse += 1
                    break
                if self._allocated < self._depth or waited >= self._grow_timeout_s:
                    arena = self._new_arena()
                    self._allocated += 1
                    self._alloc += 1
                    # Growth is STICKY: depth tracks the high-water mark so
                    # a consumer that legitimately pins more than the
                    # initial depth (superbatches(k)) pays the grow timeout
                    # once, not once per extra arena on every cycle.
                    if self._allocated > self._depth:
                        self._depth = self._allocated
                    break
                if self._heartbeat is not None and not waiting_hb:
                    # One beat on entry, then let the age accrue: a wedged
                    # pool must read as a stale 'arena-wait' heartbeat.
                    self._heartbeat.beat('arena-wait')
                    waiting_hb = True
                # Real wakeups: release and GC-settle notify the condition
                # (see _reclaim) and stop() paths call wake(), so acquire
                # latency is no longer quantized to a poll interval and a
                # missed wakeup cannot masquerade as arena starvation. The
                # timeout is the grow deadline, capped only so an EXTERNAL
                # stop_event set without wake() is still observed promptly
                # (that cap bounds stop latency, not acquire latency).
                timeout = min(max(self._grow_timeout_s - waited, 0.005), 0.25)
                t0 = time.perf_counter()
                if self._meter is not None:
                    with self._meter.pause(self._meter_stage):
                        self._cond.wait(timeout=timeout)
                else:
                    self._cond.wait(timeout=timeout)
                waited += time.perf_counter() - t0
                self._wait_s += time.perf_counter() - t0
            if waiting_hb:
                self._heartbeat.beat('collate')
            if waited:
                self._m_wait.observe(waited)
            self._pending = arena
            self._tracer.counter('arena_pool_free', len(self._free), 'staging')
            return arena.borrowed_buffers()

    def _new_arena(self):
        """One arena in the pool's current allocation mode (called with
        the pool condition held). Pinned mode carves the buffers out of a
        DMA-friendly slab; any slab failure (no native tier, mmap limit,
        RLIMIT) falls back to a plain arena — logged once, never raised."""
        slab = None
        if self._pinned:
            try:
                from petastorm_tpu.native import pinned as pinned_mod
                _, total = _pinned_slab_layout(self._spec)
                slab = pinned_mod.allocate(total, lock=True)
            except Exception:  # noqa: BLE001 - pinned mode is best-effort
                slab = None
            if slab is None and not self._pinned_fallback_logged:
                self._pinned_fallback_logged = True
                logger.warning('pinned arena allocation unavailable; '
                               'falling back to unpinned host buffers')
        arena = HostArena(self, self._spec, slab=slab)
        if slab is not None:
            self._pinned_bytes += slab.nbytes
            self._pinned_mode = slab.mode
            if slab.locked:
                self._pinned_locked += 1
            self._m_pinned.inc(slab.nbytes)
            # The condition's lock is an RLock, so the finalizer (run at
            # GC time on an arbitrary thread, possibly mid-critical-
            # section) can re-enter safely — same contract _drop_hold
            # already relies on.
            weakref.finalize(arena, self._drop_pinned,
                             slab.nbytes, slab.locked)
        return arena

    def _drop_pinned(self, nbytes, locked):
        with self._cond:
            self._pinned_bytes -= nbytes
            if locked:
                self._pinned_locked -= 1
        self._m_pinned.inc(-nbytes)

    def set_pinned(self, enabled):
        """Toggle pinned allocation for arenas allocated from now on
        (autotune pinned-arena knob; the loader's governor advisory also
        drops it). Existing arenas keep their slabs — they drain as the
        working set cycles through ``set_depth``-style replacement."""
        with self._cond:
            self._pinned = bool(enabled)

    @property
    def pinned(self):
        with self._cond:
            return self._pinned

    @property
    def pinned_nbytes(self):
        """Bytes in live pinned slabs (page-padded actual mapping sizes;
        the membudget ``arena-pool`` pool already counts these buffers —
        this is the mlock-exposure view, not extra memory)."""
        with self._cond:
            return self._pinned_bytes

    def claim_pending(self):
        """The arena handed out by the latest ``get_buffers`` call (or
        ``None``): called by the engine right after the host iterator
        yields, pairing the batch with its backing arena."""
        with self._cond:
            arena, self._pending = self._pending, None
            return arena

    def _reclaim(self, arena):
        arena._on_reclaim()
        with self._cond:
            if len(self._free) < self._depth:
                self._free.append(arena)
            else:
                self._allocated -= 1   # grown-past-depth arena: let it die
            self._cond.notify_all()
            self._tracer.counter('arena_pool_free', len(self._free), 'staging')

    def reclaim_pending(self):
        """Shutdown path: an arena handed out but never claimed (the
        assembler died between fill and yield) must not leak."""
        arena = self.claim_pending()
        if arena is not None:
            arena.retire()

    def learn_shard_layout(self, field_bounds):
        """Teach the pool the device-sharded layout of its batches:
        ``{field: ((start, stop), ...)}`` per-device row bounds along the
        batch dim, computed ONCE per schema from the ``NamedSharding``
        (see ``parallel.mesh.device_shard_plan``). Arenas then hand the
        dispatch stage memoized contiguous sub-slice views
        (:meth:`HostArena.shard_views`) — the collate path needs no
        change because a batch-dim shard of a C-contiguous buffer IS a
        contiguous sub-slice of it. Incremental: fields merge into the
        layout as their shardings are first seen."""
        with self._cond:
            if self._shard_layout is None:
                self._shard_layout = {}
            for name, bounds in field_bounds.items():
                self._shard_layout[name] = tuple(
                    (int(start), int(stop)) for start, stop in bounds)

    @property
    def shard_layout(self):
        with self._cond:
            return dict(self._shard_layout) if self._shard_layout else None

    def wake(self):
        """Wake any waiter so it can observe the stop flag promptly (the
        condition is otherwise only notified on arena release)."""
        with self._cond:
            self._cond.notify_all()

    def set_depth(self, depth):
        """Retarget the pool depth at runtime (autotune hookup). Growing
        wakes a backpressured assembler to allocate immediately; shrinking
        lets excess arenas die on their next reclaim (``_reclaim`` drops
        frees beyond ``depth``) — memory drains as the working set cycles,
        with no arena yanked from under an in-flight transfer."""
        depth = max(1, int(depth))
        with self._cond:
            if depth == self._depth:
                return
            self._depth = depth
            while len(self._free) > depth:
                self._free.pop()
                self._allocated -= 1
            self._cond.notify_all()

    @property
    def depth(self):
        """Current pool depth (autotune knob getter — cheaper than a full
        :meth:`stats` sample on a sub-second tick)."""
        with self._cond:
            return self._depth

    @property
    def nbytes(self):
        """Bytes pinned by every allocated arena (free, filled, and
        in-flight alike: an arena waiting recycle is just as resident) —
        the memory governor's ``arena-pool`` accounting hook. This also
        covers the staging engine's in-flight window: staged batches are
        arena-backed, so window bytes ARE allocated-arena bytes."""
        with self._cond:
            if self._spec is None:
                return 0
            per_arena = sum(
                int(np.prod(shape)) * np.dtype(dtype).itemsize
                for shape, dtype in self._spec.values())
            return self._allocated * per_arena

    @property
    def wait_seconds(self):
        """Cumulative assembler backpressure seconds (the autotuner's
        arena-bound signal)."""
        with self._cond:
            return self._wait_s

    def stats(self):
        with self._cond:
            return {'arena_alloc': self._alloc,
                    'arena_reuse': self._reuse,
                    'arena_wait_s': round(self._wait_s, 4),
                    'arena_depth': self._depth,
                    'arena_allocated': self._allocated,
                    'arena_pinned': self._pinned,
                    'arena_pinned_bytes': self._pinned_bytes,
                    'arena_pinned_locked': self._pinned_locked,
                    'arena_pinned_mode': self._pinned_mode or 'off',
                    # Context for watchdog diagnoses: a wait can only
                    # outlive this before growth relieves it, so a pool
                    # that CAN grow shows wedges as climbing arena_alloc
                    # (memory), not as long arena-waits.
                    'arena_grow_timeout_s': self._grow_timeout_s}

    def reset_stats(self):
        with self._cond:
            self._alloc = 0
            self._reuse = 0
            self._wait_s = 0.0


class OverlapMeter(object):
    """Wall-clock co-activity of named stages (assemble vs dispatch).

    ``reset()`` starts a new measurement window (the bench resets after
    warmup) but lifetime totals survive it — on zero-copy backends the
    cache-warm steady state has nearly nothing left to overlap (both
    stages are view handoffs), so the decode-bound phase where dispatch
    genuinely hides under assembly is only visible in the totals.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._active = 0
        self._mark = None
        self._busy = {}
        self._overlap_s = 0.0
        self._base_busy = {}
        self._base_overlap = 0.0
        # Spans currently open ({token: (name, t0)}): stats() credits
        # their elapsed time live. With fence pipelining the stager's
        # 'h2d' span is open whenever any stream window holds a transfer
        # — i.e. ~always in steady state — so exit-only accounting would
        # chronically report busy_s['h2d'] = 0 and overlap_frac = 0.0 at
        # every mid-stream stats read.
        self._live = {}

    def _transition(self, delta):
        now = time.perf_counter()
        if self._active >= 2 and self._mark is not None:
            self._overlap_s += now - self._mark
        self._active += delta
        self._mark = now
        return now

    def _busy_snapshot(self, now):
        busy = dict(self._busy)
        for name, t0 in self._live.values():
            busy[name] = busy.get(name, 0.0) + (now - t0)
        return busy

    def _overlap_snapshot(self, now):
        overlap = self._overlap_s
        if self._active >= 2 and self._mark is not None:
            overlap += now - self._mark
        return overlap

    @contextmanager
    def track(self, name):
        token = object()
        with self._lock:
            t0 = self._transition(+1)
            self._live[token] = (name, t0)
        try:
            yield
        finally:
            with self._lock:
                t1 = self._transition(-1)
                self._live.pop(token, None)
                self._busy[name] = self._busy.get(name, 0.0) + (t1 - t0)

    @contextmanager
    def pause(self, name):
        """Suspend a stage from inside its ``track`` section — used while
        the assembler is merely *blocked* (reader starvation) so idle wait
        doesn't masquerade as busy/overlapping collate time. The paused
        span is subtracted from the stage's busy seconds and stops overlap
        accrual for its duration."""
        with self._lock:
            t0 = self._transition(-1)
        try:
            yield
        finally:
            with self._lock:
                t1 = self._transition(+1)
                self._busy[name] = self._busy.get(name, 0.0) - (t1 - t0)

    @staticmethod
    def _frac(busy, overlap):
        floor = min(busy.values()) if len(busy) >= 2 else 0.0
        return min(1.0, overlap / floor) if floor > 1e-9 else 0.0

    def stats(self, total=False):
        with self._lock:
            now = time.perf_counter()
            busy = self._busy_snapshot(now)
            overlap = self._overlap_snapshot(now)
            if not total:
                busy = {k: v - self._base_busy.get(k, 0.0)
                        for k, v in busy.items()}
                overlap -= self._base_overlap
        return {'busy_s': {k: round(v, 4) for k, v in busy.items()},
                'overlap_s': round(overlap, 4),
                'overlap_frac': round(self._frac(busy, overlap), 4)}

    def reset(self):
        """Start a new window; lifetime totals (``stats(total=True)``)
        keep accumulating. Spans open across the reset contribute only
        their post-reset elapsed time to the new window (their
        elapsed-so-far is folded into the base)."""
        with self._lock:
            now = time.perf_counter()
            self._base_busy = self._busy_snapshot(now)
            self._base_overlap = self._overlap_snapshot(now)


class MeteredReader(object):
    """Iteration proxy reporting time blocked in the underlying reader as
    *paused* assemble time (``OverlapMeter.pause``): the assemble stage's
    busy/overlap accounting then covers collate work only, not reader
    starvation — an input-bound run must not read as perfectly overlapped
    pipelining. Every non-iteration attribute passes through."""

    def __init__(self, reader, meter, stage='assemble', heartbeat=None):
        self._pst_reader = reader
        self._pst_meter = meter
        self._pst_stage = stage
        self._pst_hb = heartbeat
        # Cumulative seconds the assembler spent blocked in the reader —
        # the autotuner's reader-starved signal (written by the assemble
        # thread only; float rebinding is atomic for readers).
        self.reader_wait_s = 0.0

    def __iter__(self):
        return self

    def __next__(self):
        hb = self._pst_hb
        if hb is not None:
            # State labels bracket the reader pull so a stale heartbeat
            # tells the watchdog *what* starved: 'reader-wait' = the
            # decode/IO tier produced nothing (reader-starved); 'collate'
            # = the batch-assembly work itself wedged (assemble-stuck).
            hb.beat('reader-wait')
        t0 = time.perf_counter()
        try:
            with self._pst_meter.pause(self._pst_stage):
                return next(self._pst_reader)
        finally:
            self.reader_wait_s += time.perf_counter() - t0
            if hb is not None:
                hb.beat('collate')

    def __getattr__(self, name):
        return getattr(self._pst_reader, name)


class DeviceStagerStopped(RuntimeError):
    """A shard wave was aborted because the stager (or its pipeline) is
    stopping — the batch never reached the device and must not be
    delivered."""


class DeviceStager(object):
    """One overlapped ``device_put`` stream per addressable device.

    The one-shot ``jax.make_array_from_process_local_data`` path issues
    every device's transfer from a single thread and fences the whole
    batch at once, so the collate of batch N+1 can only hide under the
    *aggregate* transfer of batch N. This runs one dispatch stream (a
    ``pst-device-put-<k>`` thread) per device instead: shard puts issue
    concurrently across devices, each stream keeps its own bounded
    in-flight window (blocking on its *oldest* transfer when full), and
    the caller stitches the staged shards into a global ``jax.Array``
    with ``jax.make_array_from_single_device_arrays`` — so collate of
    shard k+1 hides under the transfer of shard k on *every* device, not
    just along the batch dim of one.

    jax-free by construction (``put_fn`` injected), so the stream
    discipline — ordering, windows, donation accounting, stop semantics —
    is unit-testable without a backend.

    :param stream_keys: one label per stream (device ids); sets the
        stream count and the ``device`` label on
        ``pst_device_put_seconds``.
    :param put_fn: ``(array, stream_index, donate) -> staged array``;
        called on the stream's own thread, must be thread-safe across
        streams (``jax.device_put`` is).
    :param inflight: per-stream in-flight transfer window (the autotune
        ``device_inflight`` knob; :meth:`set_inflight` retargets live).
    :param ready_fn: ``staged -> None`` blocking until the transfer
        completed; used for window backpressure only.
    :param stop_event: shared stop flag; no stream outlives it.
    """

    def __init__(self, stream_keys, put_fn, inflight=2, ready_fn=None,
                 stop_event=None, tracer=None, meter=None):
        self._keys = tuple(str(k) for k in stream_keys)
        if not self._keys:
            raise ValueError('DeviceStager needs at least one stream')
        self._put_fn = put_fn
        self._ready_fn = ready_fn or (lambda staged: None)
        self._inflight = max(1, int(inflight))
        # Streamed-path overlap measurement: the owner tracks its
        # host-side staging work as 'host' on this meter; the stager
        # keeps ONE refcounted 'h2d' span open while ANY stream holds an
        # unfenced transfer (all streams collapse into one logical h2d
        # lane — per-stream spans would measure stream-vs-stream
        # co-activity, not transfer-vs-host overlap). stats() then
        # reports h2d_overlap_frac for the streamed path, which the
        # bench's one-shot probe cannot see.
        self.meter = meter
        self._h2d_tokens = 0
        self._h2d_span = None
        self._stop = stop_event if stop_event is not None else threading.Event()
        if tracer is None:
            from petastorm_tpu.trace import NullTracer
            tracer = NullTracer()
        self._tracer = tracer
        from petastorm_tpu import metrics as metrics_mod
        self._m_put = metrics_mod.histogram(
            'pst_device_put_seconds',
            'Per-device shard device_put latency (issue time; window '
            'fences are reported separately)', labelnames=('device',))
        self._m_donated = metrics_mod.counter(
            'pst_shards_donated_total',
            'Arena-backed shards handed to the device transfer with no '
            'loader-side host copy (stream-tier puts additionally donate '
            'the buffer to the backend)')
        self._stats_lock = threading.Lock()
        self._put_s = {k: 0.0 for k in self._keys}
        self._put_bytes = {k: 0 for k in self._keys}
        self._shards_put = 0
        self._donated = 0
        self._ready_wait_s = 0.0
        self._window_bytes = 0
        self._leaked_threads = []
        # Bounded (pstlint bounded-queues): one submission wave queues at
        # most fields-per-batch items per stream before the submitter
        # blocks on the wave's completion, so 128 is generous headroom —
        # the bound exists so a bug can't grow an unbounded backlog.
        self._queues = [queue.Queue(maxsize=128) for _ in self._keys]
        self._start_lock = threading.Lock()
        self._started = False
        self._threads = [
            threading.Thread(target=self._stream_loop, args=(i,),
                             daemon=True,
                             name='pst-device-put-{}'.format(key))
            for i, key in enumerate(self._keys)]

    def start(self):
        """Start the stream threads. Idempotent, and called lazily from
        the first :meth:`put_shards` wave — an owner whose constructor
        fails after building the stager must not leak 8 parked threads
        with no reachable stop path (the inline tier never starts them
        at all)."""
        with self._start_lock:
            if not self._started:
                self._started = True
                for t in self._threads:
                    t.start()
        return self

    @property
    def n_streams(self):
        return len(self._keys)

    # -- submission --------------------------------------------------------

    def put_shards(self, items):
        """Dispatch one wave of shards: ``items`` is a list of
        ``(stream_index, array, donate)``; returns the staged arrays in
        the same order once every put has been *issued* (transfers
        complete in the background against the per-stream windows).
        ``donate`` marks that shard's source buffer donated — an
        arena-backed sub-slice whose recycling is already gated on
        transfer completion (and consumer GC on aliasing backends), so
        the backend may consume it without a defensive host copy; the
        caller must not donate a buffer shared by another shard of the
        wave (replicated bounds). Raises :class:`DeviceStagerStopped`
        when the stager is stopping mid-wave; re-raises the first
        ``put_fn`` failure otherwise."""
        if not self._started:
            self.start()
        results = [None] * len(items)
        state = {'remaining': len(items), 'error': None}
        done = threading.Event()
        lock = threading.Lock()
        for slot, (stream, array, donate) in enumerate(items):
            self._enqueue(stream, (array, bool(donate), slot, results,
                                   state, lock, done))
        while not done.is_set():
            if self._stop.is_set():
                raise DeviceStagerStopped(
                    'device stager stopping mid-wave ({} shard(s) '
                    'outstanding)'.format(state['remaining']))
            done.wait(0.1)
        if state['error'] is not None:
            raise state['error']
        return results

    def _enqueue(self, stream, item):
        q = self._queues[stream]
        while not self._stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return
            except queue.Full:
                continue
        raise DeviceStagerStopped('device stager stopping')

    # -- per-stream loop ---------------------------------------------------

    def _stream_loop(self, index):
        window = deque()    # (staged, nbytes) — owned by this thread only
        q = self._queues[index]
        key = self._keys[index]
        try:
            while True:
                try:
                    item = q.get(timeout=0.1)
                except queue.Empty:
                    if self._stop.is_set():
                        return
                    # Idle streams opportunistically drain their window so
                    # arenas retire without waiting for the next wave.
                    while window and not self._stop.is_set():
                        if not self._retire_oldest(window, block=False):
                            break
                    continue
                array, donate, slot, results, state, lock, done = item
                try:
                    # Fence pipelining: make room at SUBMIT time, not
                    # after delivery. The window only gives up its oldest
                    # transfer when a new one is about to take the slot,
                    # so between waves every slot stays occupied by an
                    # in-flight transfer — the h2d stream never drains —
                    # and the fence is frequently free because the oldest
                    # transfer completed while the stream sat waiting for
                    # this wave.
                    while len(window) >= self._inflight:
                        self._retire_oldest(window, block=True)
                    # A wave item may account itself (the streamed
                    # batched-put tier calls record_inline_wave with the
                    # true per-device breakdown from inside put_fn); the
                    # stream then only does window/byte bookkeeping.
                    self_acct = bool(getattr(array, 'pst_self_accounting',
                                             False))
                    t0 = time.perf_counter()
                    staged = self._put_fn(array, index, donate)
                    dt = time.perf_counter() - t0
                    nbytes = int(getattr(array, 'nbytes', 0))
                    if not self_acct:
                        self._m_put.labels(key).observe(dt)
                        if donate:
                            self._m_donated.inc()
                    with self._stats_lock:
                        if not self_acct:
                            self._put_s[key] += dt
                            self._put_bytes[key] += nbytes
                            self._shards_put += 1
                            if donate:
                                self._donated += 1
                        self._window_bytes += nbytes
                    window.append((staged, nbytes))
                    self._h2d_enter()
                    # Deliver immediately: the caller stitches (and the
                    # assemble thread collates the next batch) while the
                    # transfers ride the window.
                    with lock:
                        results[slot] = staged
                        state['remaining'] -= 1
                        if state['remaining'] <= 0:
                            done.set()
                except Exception as e:  # noqa: BLE001 - surfaced to the wave
                    with lock:
                        state['error'] = e
                        done.set()
        finally:
            # Stop path: drop the window's byte accounting (the staged
            # arrays keep their own memory alive; nothing to fence on a
            # pipeline that is going away).
            while window:
                self._retire_oldest(window, block=False)

    def _retire_oldest(self, window, block):
        """Retire the stream's oldest in-flight transfer. ``block=True``
        fences it (window backpressure); ``block=False`` only retires an
        already-complete transfer. Returns whether an entry retired."""
        staged, nbytes = window.popleft()
        if block and not self._stop.is_set():
            t0 = time.perf_counter()
            try:
                self._ready_fn(staged)
            except Exception:  # noqa: BLE001 - a dying fence must not kill the stream
                logger.debug('device stager ready_fn failed', exc_info=True)
            with self._stats_lock:
                self._ready_wait_s += time.perf_counter() - t0
                self._window_bytes -= nbytes
            self._h2d_exit()
            return True
        if not block and not self._stop.is_set():
            try:
                if not self._probe_ready(staged):
                    window.appendleft((staged, nbytes))
                    return False
            except Exception:  # noqa: BLE001
                pass
        with self._stats_lock:
            self._window_bytes -= nbytes
        self._h2d_exit()
        return True

    @staticmethod
    def _probe_ready(staged):
        probe = getattr(staged, 'is_ready', None)
        return True if probe is None else bool(probe())

    # -- streamed-path overlap ---------------------------------------------

    def _h2d_enter(self):
        """A transfer entered some stream's window: open (or refcount)
        the single logical 'h2d' span on the stager's meter."""
        if self.meter is None:
            return
        with self._stats_lock:
            self._h2d_tokens += 1
            if self._h2d_tokens == 1:
                self._h2d_span = self.meter.track('h2d')
                self._h2d_span.__enter__()

    def _h2d_exit(self):
        """A transfer retired; close the 'h2d' span when no stream holds
        an unfenced transfer any more."""
        if self.meter is None:
            return
        with self._stats_lock:
            self._h2d_tokens -= 1
            if self._h2d_tokens == 0 and self._h2d_span is not None:
                span, self._h2d_span = self._h2d_span, None
                span.__exit__(None, None, None)

    def record_inline_wave(self, stream_indices, nbytes_list, elapsed,
                           donate):
        """Account one batched per-device wave — issued inline on the
        owner's thread (the small-shard fast tier) or from a stream
        thread as a self-accounting wave item (the streamed-batched
        tier) — so per-device put seconds/bytes and donation counts
        stay coherent across tiers. Issue time is attributed evenly
        across the wave's shards (the batched call is one C++ fan-out;
        per-shard splits are not observable)."""
        count = max(1, len(stream_indices))
        per_shard = elapsed / count
        for index, nbytes in zip(stream_indices, nbytes_list):
            key = self._keys[index]
            self._m_put.labels(key).observe(per_shard)
            if donate:
                self._m_donated.inc()
        with self._stats_lock:
            for index, nbytes in zip(stream_indices, nbytes_list):
                key = self._keys[index]
                self._put_s[key] += per_shard
                self._put_bytes[key] += int(nbytes)
                self._shards_put += 1
                if donate:
                    self._donated += 1

    # -- knobs / stats / lifecycle ----------------------------------------

    def set_inflight(self, n):
        """Retarget the per-stream in-flight window (the autotune
        ``device_inflight`` knob): each stream re-reads it at submit
        time, so widening takes effect on the next put and narrowing
        fences the excess oldest transfers before the next one issues."""
        self._inflight = max(1, int(n))

    @property
    def inflight_window(self):
        return self._inflight

    @property
    def ready_wait_seconds(self):
        """Cumulative seconds streams spent fenced on their oldest
        in-flight transfer — folded into the autotuner's dispatch-bound
        signal next to the engine's batch-level fence."""
        with self._stats_lock:
            return self._ready_wait_s

    @property
    def window_nbytes(self):
        """Host bytes currently referenced by every stream's in-flight
        window (the membudget ``device-put-window`` pool; the loader
        reports 0 when the same bytes are already accounted by the arena
        pool)."""
        with self._stats_lock:
            return self._window_bytes

    def stats(self):
        # Meter first (its own lock) so nothing nests under _stats_lock.
        overlap = self.meter.stats() if self.meter is not None else None
        with self._stats_lock:
            out = {
                'n_devices': len(self._keys),
                'device_inflight': self._inflight,
                'shards_put': self._shards_put,
                'shards_donated': self._donated,
                'device_ready_wait_s': round(self._ready_wait_s, 4),
                'device_put_s': {k: round(v, 4)
                                 for k, v in self._put_s.items()},
                'device_put_bytes': dict(self._put_bytes),
                'leaked_threads': list(self._leaked_threads)}
        if overlap is not None:
            # The streamed-path measurement the bench's one-shot probe
            # cannot see: 'h2d' (any transfer unfenced in a window) vs
            # 'host' (the owner's staging work) co-activity.
            out['h2d_overlap'] = overlap
            out['h2d_overlap_frac'] = overlap['overlap_frac']
        return out

    def reset_stats(self):
        if self.meter is not None:
            self.meter.reset()
        with self._stats_lock:
            self._put_s = {k: 0.0 for k in self._keys}
            self._put_bytes = {k: 0 for k in self._keys}
            self._shards_put = 0
            self._donated = 0
            self._ready_wait_s = 0.0

    @property
    def alive(self):
        return any(t.is_alive() for t in self._threads)

    def stop(self, join_timeout_s=10):
        """Idempotent: set stop, join every stream. A stream outliving
        the join (a put hung on a wedged device) is recorded in
        ``stats()['leaked_threads']`` and logged — mirroring
        :meth:`StagingEngine.stop`'s never-pretend-success contract."""
        self._stop.set()
        leaked = []
        with self._start_lock:
            started = self._started
        for t in self._threads:
            if not started:
                break
            t.join(timeout=join_timeout_s)
            if t.is_alive():
                leaked.append(t.name)
        if leaked:
            with self._stats_lock:
                self._leaked_threads.extend(
                    n for n in leaked if n not in self._leaked_threads)
            for name in leaked:
                self._tracer.instant('device-stager-leaked:{}'.format(name),
                                     cat='watchdog')
            logger.warning(
                'DeviceStager.stop: stream thread(s) %s still alive after '
                '%.1fs join — a hung device_put is leaking them past '
                'shutdown.', leaked, join_timeout_s)
        return leaked


class _StageError(object):
    def __init__(self, exc):
        self.exc = exc


class StagingEngine(object):
    """Assemble/dispatch pipeline feeding a consumer queue.

    :param host_iter: iterator of host-batch dicts (typically
        ``iter_numpy_batches(..., batch_buffers=pool.get_buffers)`` so the
        batches land in pool arenas).
    :param stage_fn: host batch dict -> staged dict (async device puts).
    :param out_queue: bounded consumer queue; receives staged dicts in
        order, then ``end_sentinel`` (or an ``Exception`` on failure).
    :param stop_event: shared stop flag; no engine thread outlives it.
    :param pool: the :class:`ArenaPool` backing ``host_iter`` (or None).
    :param inflight: max staged batches whose transfers may be in flight
        before the dispatch thread blocks on the oldest (the backpressure
        window from the ISSUE; also bounds how much arena memory a burst
        can pin).
    :param ready_fn: staged dict -> blocks until its transfer completed
        (``jax.block_until_ready``). Called before an arena is retired.
    :param is_ready_fn: staged dict -> bool, non-blocking (opportunistic
        early retirement); optional.
    :param holds_mode: staged arrays alias arena memory (zero-copy
        backends): register GC holds so an arena is never recycled while
        the consumer can still observe it.
    :param on_drop: optional zero-arg callback fired when an assembled
        batch is discarded without reaching the consumer (stop-time
        races). The loader's provenance tracker pairs pending records
        FIFO with delivered batches, so a dropped batch must retract its
        record or every later record would describe the wrong batch.
    """

    def __init__(self, host_iter, stage_fn, out_queue, stop_event,
                 end_sentinel, pool=None, inflight=2, ready_fn=None,
                 is_ready_fn=None, holds_mode=False, tracer=None,
                 meter=None, health=None, on_drop=None,
                 stage_with_arena=False):
        self._host_iter = host_iter
        self._stage_fn = stage_fn
        # stage_with_arena: call ``stage_fn(batch, arena)`` so a device-
        # sharded stage can reuse the arena's memoized per-device
        # sub-slice views (HostArena.shard_views) instead of re-slicing
        # every batch. The arena still joins the in-flight window AFTER
        # staging, exactly as before.
        self._stage_with_arena = bool(stage_with_arena)
        self._out = out_queue
        self._stop = stop_event
        self._end = end_sentinel
        self._pool = pool
        self._window = max(1, int(inflight))
        self._ready_fn = ready_fn or (lambda staged: None)
        self._is_ready_fn = is_ready_fn
        self._holds_mode = holds_mode
        self._on_drop = on_drop
        if tracer is None:
            from petastorm_tpu.trace import NullTracer
            tracer = NullTracer()
        self._tracer = tracer
        self.meter = meter if meter is not None else OverlapMeter()
        # Registry mirror (petastorm_tpu.metrics): per-batch assemble and
        # dispatch latencies — the staging halves of the scrape surface.
        from petastorm_tpu import metrics as metrics_mod
        self._m_assemble = metrics_mod.histogram(
            'pst_assemble_seconds', 'Host-batch collate latency per batch')
        self._m_dispatch = metrics_mod.histogram(
            'pst_dispatch_seconds', 'Device staging dispatch latency per '
            'batch (put issue time, not transfer completion)')
        self._stats_lock = threading.Lock()
        self._retired = 0
        self._ready_wait_s = 0.0
        self._leaked_threads = []
        # Health hookup (petastorm_tpu.health): both stage threads beat a
        # named heartbeat at every phase transition, so the watchdog can
        # tell a hung device_put ('device_put'/'ready-wait') from a full
        # consumer queue ('out-put') from waiting on upstream
        # ('stageq-get' — an innocent state; blame lands on assemble).
        self._hb_assemble = self._hb_dispatch = None
        if health is not None:
            self._hb_assemble = health.register('assemble')
            self._hb_dispatch = health.register('dispatch')
            health.register_probe('staging', self.stats)
        self._stage_q = queue.Queue(maxsize=2)
        self._threads = [
            threading.Thread(target=self._assemble_loop, daemon=True,
                             name='pst-staging-assemble'),
            threading.Thread(target=self._dispatch_loop, daemon=True,
                             name='pst-staging-dispatch'),
        ]

    def start(self):
        for t in self._threads:
            t.start()
        return self

    # -- stop-aware queue helpers ----------------------------------------

    def _put(self, q, obj):
        """Bounded-queue put that never outlives stop() (PR 1 semantics:
        an unbounded put can leak the thread forever if the consumer left).
        Returns whether ``obj`` was actually enqueued — the caller owns its
        cleanup ONLY on False, or a stop-time race would settle the same
        arena twice. When stopping, a final non-blocking attempt still
        wakes a consumer already parked in an untimed get()."""
        while not self._stop.is_set():
            try:
                q.put(obj, timeout=0.1)
                return True
            except queue.Full:
                continue
        try:
            q.put_nowait(obj)
            return True
        except queue.Full:
            return False

    def _get(self):
        while not self._stop.is_set():
            try:
                return self._stage_q.get(timeout=0.1)
            except queue.Empty:
                continue
        try:
            return self._stage_q.get_nowait()
        except queue.Empty:
            return None

    # -- assemble stage ---------------------------------------------------

    def _assemble_loop(self):
        hb = self._hb_assemble
        try:
            self._assemble_body(hb)
        finally:
            if hb is not None:
                hb.beat('idle')   # exited (done, stopped, or errored-and-
                                  # delivered): quiet is no longer a stall

    def _assemble_body(self, hb):
        try:
            while not self._stop.is_set():
                if hb is not None:
                    hb.beat('collate')
                try:
                    t_assemble = time.perf_counter()
                    with self.meter.track('assemble'):
                        with self._tracer.span('assemble', 'host'):
                            batch = next(self._host_iter)
                    self._m_assemble.observe(
                        time.perf_counter() - t_assemble)
                except StopIteration:
                    break
                arena = self._pool.claim_pending() if self._pool else None
                if hb is not None:
                    hb.beat('stageq-put')
                if not self._put(self._stage_q, (batch, arena)):
                    if arena is not None:
                        arena.retire()
                    self._notify_drop()
                    return
        except Exception as e:  # noqa: BLE001 - surfaced to consumer
            if self._pool is not None:
                self._pool.reclaim_pending()
            self._put(self._stage_q, _StageError(e))
            return
        self._put(self._stage_q, _DONE)

    def _notify_drop(self):
        """An assembled batch will never reach the consumer: tell the
        owner (provenance accounting) exactly once per dropped batch."""
        if self._on_drop is not None:
            try:
                self._on_drop()
            except Exception:  # noqa: BLE001 - advisory accounting only
                logger.debug('staging on_drop callback failed', exc_info=True)

    # -- dispatch stage ---------------------------------------------------

    def _head_ready(self, staged):
        if self._is_ready_fn is None:
            return False
        try:
            return bool(self._is_ready_fn(staged))
        except Exception:  # noqa: BLE001 - readiness probe must not kill dispatch
            return False

    def _retire(self, staged, arena, wait):
        if arena is None:
            return
        if wait and not self._stop.is_set():
            if self._hb_dispatch is not None:
                self._hb_dispatch.beat('ready-wait')
            t0 = time.perf_counter()
            self._ready_fn(staged)
            with self._stats_lock:
                self._ready_wait_s += time.perf_counter() - t0
        # Seeded use-after-reclaim (fault site 'arena-stale-view'): keep a
        # borrow-tagged view across the retire and touch it after. Armed
        # (PETASTORM_TPU_SANITIZE) the touch raises StaleViewError at the
        # stale access; unarmed it silently reads recycled bytes — the
        # exact bug class the sanitizer exists to catch. (In holds mode a
        # reclaim defers to consumer GC, so the seeded proof drives the
        # engine with holds_mode=False; see tests/test_pstlint.py.)
        stale_probe = None
        from petastorm_tpu import faults
        if faults.faults_active() \
                and faults.get_injector().should_fire('arena-stale-view'):
            stale_probe = arena.borrow(next(iter(arena.buffers.values())))
        arena.retire()
        if stale_probe is not None:
            stale_probe.sum()   # raises StaleViewError when sanitizer armed
        with self._stats_lock:
            self._retired += 1

    def _dispatch_loop(self):
        hb = self._hb_dispatch
        try:
            self._dispatch_body(hb)
        finally:
            if hb is not None:
                hb.beat('idle')

    def _dispatch_body(self, hb):
        inflight = deque()
        arena = None    # the current batch's arena until the window owns it
        try:
            while True:
                if hb is not None:
                    hb.beat('stageq-get')
                item = self._get()
                if item is None:          # stopping
                    return
                if item is _DONE:
                    while inflight:
                        self._retire(*inflight.popleft(), wait=True)
                    self._put(self._out, self._end)
                    return
                if isinstance(item, _StageError):
                    while inflight:
                        self._retire(*inflight.popleft(), wait=True)
                    self._put(self._out, item.exc)
                    return
                batch, arena = item
                if self._stop.is_set():
                    # Never issue device puts into a stopping pipe (the old
                    # stage loop's fetch/stage stop-check): on a wedged
                    # device a put can hang past the join timeout, leaving
                    # a leaked thread holding reader views whose teardown
                    # it races.
                    self._notify_drop()
                    return
                if hb is not None:
                    hb.beat('device_put')
                # Seeded lock-order inversion (fault site
                # 'lock-order-invert'): near-zero when inactive; armed,
                # the sanitizer's recorder raises before blocking and the
                # violation is delivered to the consumer like any
                # pipeline error.
                from petastorm_tpu.analysis import sanitize
                sanitize.maybe_inject_lock_inversion()
                t_dispatch = time.perf_counter()
                with self.meter.track('dispatch'):
                    with self._tracer.span('dispatch', 'device'):
                        if self._stage_with_arena:
                            staged = self._stage_fn(batch, arena)
                        else:
                            staged = self._stage_fn(batch)
                self._m_dispatch.observe(time.perf_counter() - t_dispatch)
                if arena is not None:
                    if self._holds_mode:
                        for value in staged.values():
                            arena.add_hold(value)
                    inflight.append((staged, arena))
                    arena = None
                    self._tracer.counter('staging_inflight', len(inflight),
                                         'staging')
                del batch
                if hb is not None:
                    hb.beat('out-put')
                if not self._put(self._out, staged):
                    self._notify_drop()
                    return
                del staged
                # Opportunistic early retirement, then hard backpressure:
                # block on the OLDEST in-flight transfer once the window
                # is full — collate of batch N+1 proceeds in the assemble
                # thread meanwhile, which is the overlap this engine exists
                # to create.
                while inflight and self._head_ready(inflight[0][0]):
                    self._retire(*inflight.popleft(), wait=False)
                while len(inflight) > self._window:
                    self._retire(*inflight.popleft(), wait=True)
        except Exception as e:  # noqa: BLE001 - surfaced to consumer
            # Deliver first (the stop-aware put is reliable while the
            # consumer lives), THEN stop the whole engine: the assembler
            # must not keep retrying its bounded put forever (a leaked
            # stager holding reader refs), and with stop set no arena can
            # be handed out again, making the wait=False drain below safe.
            self._put(self._out, e)
            self._stop.set()
        finally:
            # Shutdown: no arena may leak — neither the failing batch's
            # (claimed but never appended to the window) nor the window's.
            # Stop is set on every path that reaches here with entries
            # outstanding, so a retired arena cannot be re-handed-out and
            # overwritten under a still-running transfer; the transfers
            # themselves keep their memory alive via their own references.
            if arena is not None:
                arena.retire()
            while inflight:
                self._retire(*inflight.popleft(), wait=False)

    # -- lifecycle / stats -------------------------------------------------

    def set_inflight(self, n):
        """Retarget the in-flight transfer window at runtime (autotune
        hookup): the dispatch loop re-reads the window every batch, so a
        widened window takes effect on the next dispatch and a narrowed
        one drains by blocking on the oldest transfers."""
        self._window = max(1, int(n))

    @property
    def inflight_window(self):
        return self._window

    @property
    def ready_wait_seconds(self):
        """Cumulative seconds the dispatch stage spent fenced on the
        oldest in-flight transfer — the autotuner's dispatch-bound signal
        (cheaper than a full :meth:`stats` sample on a sub-second tick)."""
        with self._stats_lock:
            return self._ready_wait_s

    def stop(self, join_timeout_s=10):
        """Idempotent: set stop, unblock both threads, join them, settle
        arena bookkeeping. The caller drains ``out_queue`` (it owns it).

        A thread that outlives ``join_timeout_s`` (e.g. a ``device_put``
        hung on a wedged device) is NOT silently forgotten: it is recorded
        in ``stats()['leaked_threads']``, traced, and logged with the
        stuck thread's stack — shutdown must never pretend it succeeded.
        Returns the list of thread names leaked by *this* call.
        """
        self._stop.set()
        if self._pool is not None:
            self._pool.wake()   # waiters observe the stop flag immediately
        leaked = []
        for t in self._threads:
            t.join(timeout=join_timeout_s)
            if t.is_alive():
                leaked.append(t.name)
        if leaked:
            from petastorm_tpu.health import dump_all_stacks
            with self._stats_lock:
                self._leaked_threads.extend(
                    n for n in leaked if n not in self._leaked_threads)
            for name in leaked:
                self._tracer.instant('staging-leaked-thread:{}'.format(name),
                                     cat='watchdog')
            logger.warning(
                'StagingEngine.stop: thread(s) %s still alive after %.1fs '
                'join — a hung transfer is leaking them past shutdown. '
                'Thread stacks:\n%s', leaked, join_timeout_s,
                dump_all_stacks())
        if self._pool is not None:
            self._pool.reclaim_pending()
        # Drain whatever assemble left between the stages.
        while True:
            try:
                item = self._stage_q.get_nowait()
            except queue.Empty:
                break
            if isinstance(item, tuple) and item[1] is not None:
                item[1].retire()
        return leaked   # THIS call's leaks; stats() keeps the cumulative list

    @property
    def alive(self):
        return any(t.is_alive() for t in self._threads)

    def stats(self):
        m = self.meter.stats()
        total = self.meter.stats(total=True)
        with self._stats_lock:
            retired, ready_wait = self._retired, self._ready_wait_s
            leaked = list(self._leaked_threads)
        return {'assemble_s': m['busy_s'].get('assemble', 0.0),
                'dispatch_s': m['busy_s'].get('dispatch', 0.0),
                'overlap_s': m['overlap_s'],
                'overlap_frac': m['overlap_frac'],
                'overlap_frac_total': total['overlap_frac'],
                'inflight_retired': retired,
                'ready_wait_s': round(ready_wait, 4),
                'leaked_threads': leaked}

    def reset_stats(self):
        self.meter.reset()
        with self._stats_lock:
            self._retired = 0
            self._ready_wait_s = 0.0
