"""Unischema: a framework-neutral dataset schema with per-field codecs.

Parity: reference ``petastorm/unischema.py`` — named fields with numpy dtype,
shape (``None`` = variable dim), codec and nullability; schema views by field
object or full-match regex (``unischema.py:188-229,414-441``); namedtuple row
types with a cache so repeated calls return the identical type
(``unischema.py:83-103``); inference from an Arrow schema including partition
columns (``unischema.py:291-340``); encode-on-write (``dict_to_spark_row``,
``unischema.py:343-383``) and ``insert_explicit_nulls`` (``:386-401``).

TPU-first differences:
  * Schemas serialize to/from JSON (``to_json``/``from_json``) instead of
    pickle, so dataset metadata survives package renames and Python upgrades.
  * Encoding targets Arrow tables directly (``encode_row`` +
    ``arrow_schema()``) — no Spark Row/StructType on the write path.
"""

import copy
import re
from collections import OrderedDict, namedtuple

import numpy as np
import pyarrow as pa

from petastorm_tpu.codecs import (CompressedImageCodec, NdarrayCodec,  # noqa: F401
                                  ScalarCodec, codec_from_json)
from petastorm_tpu.errors import SchemaError


class UnischemaField(object):
    """A single schema field: ``(name, numpy_dtype, shape, codec, nullable)``.

    ``shape`` is a tuple; ``None`` entries are variable-size dimensions.
    Equality intentionally ignores the codec, matching the reference
    (``petastorm/unischema.py:35-43``) so that schema views and re-encoded
    datasets compare equal.
    """

    __slots__ = ('name', 'numpy_dtype', 'shape', 'codec', 'nullable')

    def __init__(self, name, numpy_dtype, shape=(), codec=None, nullable=False):
        self.name = name
        self.numpy_dtype = np.dtype(numpy_dtype)
        self.shape = tuple(shape)
        self.codec = codec
        self.nullable = nullable

    def _key(self):
        return (self.name, self.numpy_dtype, self.shape, self.nullable)

    def __eq__(self, other):
        if not isinstance(other, UnischemaField):
            return NotImplemented
        return self._key() == other._key()

    def __hash__(self):
        return hash(self._key())

    def __repr__(self):
        return 'UnischemaField({!r}, {}, {}, {}, nullable={})'.format(
            self.name, self.numpy_dtype, self.shape, self.codec, self.nullable)

    @property
    def is_scalar(self):
        return self.shape == ()

    def resolved_codec(self):
        """The codec to use: explicit one, else a default inferred from shape.

        Scalar fields default to a native typed column; tensor fields default
        to ``NdarrayCodec`` bytes.
        """
        if self.codec is not None:
            return self.codec
        if self.is_scalar:
            return ScalarCodec(self.numpy_dtype)
        return NdarrayCodec()

    def to_json(self):
        return {
            'name': self.name,
            'dtype': self.numpy_dtype.str,
            'shape': [d if d is not None else None for d in self.shape],
            'codec': self.codec.to_json() if self.codec is not None else None,
            'nullable': bool(self.nullable),
        }

    @classmethod
    def from_json(cls, spec):
        return cls(spec['name'], np.dtype(spec['dtype']),
                   tuple(spec.get('shape', ())),
                   codec_from_json(spec.get('codec')),
                   spec.get('nullable', False))


class _NamedtupleCache(object):
    """Caches generated namedtuple types by (schema name, field names).

    Needed so e.g. tf.data sees the *same* Python type across epochs — parity
    with reference ``petastorm/unischema.py:83-103``.
    """

    _store = {}

    @classmethod
    def get(cls, parent_name, field_names):
        key = (parent_name, tuple(field_names))
        if key not in cls._store:
            cls._store[key] = namedtuple('{}_view'.format(parent_name), list(field_names))
        return cls._store[key]


class Unischema(object):
    """An ordered collection of :class:`UnischemaField`.

    Fields are accessible as attributes (``schema.my_field``) and via the
    ordered dict ``schema.fields``.
    """

    def __init__(self, name, fields):
        self._name = name
        self._fields = OrderedDict((f.name, f) for f in sorted(fields, key=lambda f: f.name))
        for f in self._fields.values():
            if not _valid_attr_name(f.name):
                raise SchemaError('Field name {!r} is not a valid identifier'.format(f.name))

    @property
    def name(self):
        return self._name

    @property
    def fields(self):
        return self._fields

    def __getattr__(self, item):
        fields = object.__getattribute__(self, '_fields')
        if item in fields:
            return fields[item]
        raise AttributeError('{!r} object has no attribute/field {!r}'.format(
            type(self).__name__, item))

    def __repr__(self):
        lines = ['Unischema({!r}, ['.format(self._name)]
        lines.extend('  {!r},'.format(f) for f in self._fields.values())
        lines.append('])')
        return '\n'.join(lines)

    # --- views ------------------------------------------------------------

    def create_schema_view(self, fields_or_patterns):
        """Subset view by field objects and/or full-match regex strings.

        Parity: reference ``petastorm/unischema.py:188-229`` (regex resolution
        at ``:414-441``). Unknown fields / non-matching patterns raise.
        """
        selected = match_unischema_fields(self, fields_or_patterns, allow_empty_match=False)
        view_fields = []
        for f in selected:
            if f.name not in self._fields or self._fields[f.name] != f:
                raise SchemaError('create_schema_view: field {!r} does not belong to schema {!r}'.format(
                    f.name, self._name))
            view_fields.append(self._fields[f.name])
        return Unischema(self._name, view_fields)

    # --- row types --------------------------------------------------------

    def make_namedtuple(self, **kwargs):
        """Build a row namedtuple instance from keyword values."""
        return self.namedtuple_type()(**{k: kwargs[k] for k in self._fields})

    def make_namedtuple_tf(self, *args, **kwargs):
        return self.namedtuple_type()(*args, **kwargs)

    def namedtuple_type(self):
        return _NamedtupleCache.get(self._name, list(self._fields))

    # --- (de)serialization ------------------------------------------------

    def to_json(self):
        return {'name': self._name,
                'fields': [f.to_json() for f in self._fields.values()]}

    @classmethod
    def from_json(cls, spec):
        return cls(spec['name'], [UnischemaField.from_json(f) for f in spec['fields']])

    # --- arrow ------------------------------------------------------------

    def arrow_schema(self, partition_fields=()):
        """Arrow schema of the *encoded* representation (for the write path).

        ``partition_fields`` are excluded — they become directory names.
        """
        cols = []
        for f in self._fields.values():
            if f.name in partition_fields:
                continue
            cols.append(pa.field(f.name, f.resolved_codec().arrow_type(), nullable=True))
        return pa.schema(cols)

    @classmethod
    def from_arrow_schema(cls, arrow_schema, schema_name='inferred_schema',
                          partition_columns=(), omit_unsupported_fields=False):
        """Infer a Unischema from a plain Arrow/Parquet schema.

        Used for non-petastorm Parquet stores (``make_batch_reader`` path).
        Parity: reference ``petastorm/unischema.py:291-340``.
        """
        fields = []
        for name in arrow_schema.names:
            arrow_field = arrow_schema.field(name)
            try:
                np_dtype, shape = _arrow_to_numpy_dtype(arrow_field.type)
            except SchemaError:
                if omit_unsupported_fields:
                    continue
                raise
            fields.append(UnischemaField(name, np_dtype, shape, codec=None,
                                         nullable=arrow_field.nullable))
        for name in partition_columns:
            if not any(f.name == name for f in fields):
                fields.append(UnischemaField(name, np.dtype('O'), (), codec=None, nullable=False))
        return cls(schema_name, fields)


def _valid_attr_name(name):
    return re.match(r'^[A-Za-z_][A-Za-z0-9_]*$', name) is not None


def _arrow_to_numpy_dtype(arrow_type):
    """Map an Arrow type to (numpy dtype, shape) — lists become 1-D fields.

    Parity: reference ``petastorm/unischema.py:444-477``.
    """
    if pa.types.is_list(arrow_type) or pa.types.is_large_list(arrow_type):
        inner, inner_shape = _arrow_to_numpy_dtype(arrow_type.value_type)
        if inner_shape != ():
            raise SchemaError('Nested lists are not supported: {}'.format(arrow_type))
        return inner, (None,)
    if pa.types.is_string(arrow_type) or pa.types.is_large_string(arrow_type):
        return np.dtype('O'), ()
    if pa.types.is_binary(arrow_type) or pa.types.is_large_binary(arrow_type):
        return np.dtype('O'), ()
    if pa.types.is_decimal(arrow_type):
        return np.dtype('O'), ()
    if pa.types.is_timestamp(arrow_type) or pa.types.is_date(arrow_type):
        return np.dtype('datetime64[ns]'), ()
    if pa.types.is_dictionary(arrow_type):
        return _arrow_to_numpy_dtype(arrow_type.value_type)
    try:
        return np.dtype(arrow_type.to_pandas_dtype()), ()
    except NotImplementedError:
        raise SchemaError('Unsupported Arrow type: {}'.format(arrow_type))


def match_unischema_fields(schema, fields_or_patterns, allow_empty_match=True):
    """Resolve a mixed list of UnischemaField objects and regex strings.

    Regexes are full-match against field names (reference
    ``petastorm/unischema.py:414-441``).
    """
    if fields_or_patterns is None:
        return list(schema.fields.values())
    resolved = OrderedDict()
    for item in fields_or_patterns:
        if isinstance(item, UnischemaField):
            resolved[item.name] = item
        elif isinstance(item, str):
            pattern = re.compile(item)
            matched = [f for n, f in schema.fields.items() if pattern.fullmatch(n)]
            if not matched and not allow_empty_match:
                raise SchemaError('Pattern {!r} matched no fields of schema {!r}'.format(
                    item, schema.name))
            for f in matched:
                resolved[f.name] = f
        else:
            raise TypeError('Expected UnischemaField or str pattern, got {!r}'.format(item))
    return list(resolved.values())


def insert_explicit_nulls(schema, row_dict):
    """Add ``None`` for missing nullable fields; raise for missing non-nullable.

    Parity: reference ``petastorm/unischema.py:386-401``.
    """
    for name, field in schema.fields.items():
        if name not in row_dict:
            if field.nullable:
                row_dict[name] = None
            else:
                raise ValueError('Field {!r} is not nullable but is missing from the row'.format(name))


def encode_row(schema, row_dict):
    """Encode a user row dict into Parquet-storable cell values.

    Parity: reference ``dict_to_spark_row`` (``petastorm/unischema.py:343-383``)
    minus the Spark Row wrapper — the output feeds ``pa.Table`` construction.
    """
    if not isinstance(row_dict, dict):
        raise TypeError('row must be a dict, got {}'.format(type(row_dict)))
    row = dict(row_dict)
    unknown = set(row.keys()) - set(schema.fields.keys())
    if unknown:
        raise ValueError('Row has fields not in schema {!r}: {}'.format(schema.name, sorted(unknown)))
    insert_explicit_nulls(schema, row)
    encoded = {}
    for name, field in schema.fields.items():
        value = row[name]
        if value is None:
            if not field.nullable:
                raise ValueError('Field {!r} is not nullable but got None'.format(name))
            encoded[name] = None
        else:
            encoded[name] = field.resolved_codec().encode(field, value)
    return encoded


def decode_row(row, schema):
    """Decode an encoded row dict back into user-facing numpy values.

    Parity: reference ``petastorm/utils.py:54-87`` (``decode_row``).
    """
    from petastorm_tpu.errors import DecodeFieldError
    decoded = {}
    for name, value in row.items():
        if name not in schema.fields:
            continue
        field = schema.fields[name]
        if value is None:
            decoded[name] = None
            continue
        try:
            decoded[name] = field.resolved_codec().decode(field, value)
        except Exception as e:
            raise DecodeFieldError('Unable to decode field {!r}: {}'.format(name, e)) from e
    return decoded


def decode_rows(rows, schema, num_threads=None, fault_key=None):
    """Decode a whole row-group's encoded rows.

    Equivalent to ``[decode_row(r, schema) for r in rows]`` but image fields
    are decoded together through the native C++ batch decoder
    (``native/src/image_codec.cc``) with the GIL released — the hot-loop
    upgrade over the reference's per-row ``cv2.imdecode`` dispatch
    (reference ``py_dict_reader_worker.py:181`` -> ``utils.py:54-87``).
    Fixed-shape uint8 image fields go through the same one-native-call-
    per-(row-group, field) block core as the tensor path
    (:func:`petastorm_tpu.codecs.decode_image_batch_into`) — each row's
    value is a disjoint view of the column block, zero intermediate
    per-image ndarrays; variable-shape fields keep the per-image-output
    ``decode_batch`` (one batched header probe sizes the outputs).

    ``num_threads`` caps the C++ decode threads; ``None`` resolves to the
    caller's live fair share of the process decode-thread budget
    (``PETASTORM_TPU_DECODE_THREADS``) so N concurrent workers don't
    oversubscribe. ``fault_key`` is the row-group identity for the
    ``decode-corrupt-batch`` fault site.
    """
    from petastorm_tpu import codecs as _codecs
    from petastorm_tpu.errors import DecodeFieldError

    native = _codecs._native_image()
    image_fields = []
    if native is not None and len(rows) > 1 \
            and _codecs.decode_path() == 'batched':
        image_fields = [name for name, field in schema.fields.items()
                        if isinstance(field.resolved_codec(), _codecs.CompressedImageCodec)]
    if not image_fields:
        return [decode_row(row, schema) for row in rows]
    if num_threads is None:
        from petastorm_tpu import decode_budget
        num_threads = decode_budget.get_budget().share()

    def _block_decodable(field):
        return (field.shape and not any(d is None for d in field.shape)
                and np.dtype(field.numpy_dtype) == np.uint8)

    rest_fields = [n for n in schema.fields if n not in image_fields]
    rest_schema = schema.create_schema_view(rest_fields) if rest_fields else None
    decoded = []
    slots = {name: [] for name in image_fields}   # (row_index, blob) per field
    for i, row in enumerate(rows):
        # decode_row skips fields outside the view, so no need to pre-filter
        d = decode_row(row, rest_schema) if rest_schema is not None else {}
        for name in image_fields:
            if name not in row:
                continue
            value = row[name]
            if value is None:
                d[name] = None
            else:
                slots[name].append((i, bytes(value)))
                d[name] = None  # filled below
        decoded.append(d)
    conform = _codecs.CompressedImageCodec.conform_channels
    for name in image_fields:
        present = slots[name]
        if not present:
            continue
        field = schema.fields[name]
        if _block_decodable(field):
            out = np.empty((len(present),) + tuple(field.shape),
                           dtype=np.uint8)
            _codecs.decode_image_batch_into(
                field, out, lambda j, _p=present: _p[j][1],
                decode_threads=num_threads, fault_key=fault_key)
            for j, (i, _) in enumerate(present):
                # Copied OUT of the scratch block, never a view of it:
                # rows live independent lives downstream (row caches,
                # shuffling buffers retain single rows for a long time),
                # and one retained view would pin the whole row-group
                # block. The copy is one extra memcpy against a decode
                # that costs 10-50x more.
                decoded[i][name] = out[j].copy()
            continue
        try:
            images = native.decode_batch([b for _, b in present],
                                         num_threads=num_threads)
        except Exception as e:
            raise DecodeFieldError('Unable to batch-decode image field {!r}: {}'
                                   .format(name, e)) from e
        for (i, _), img in zip(present, images):
            decoded[i][name] = conform(img, field)
    return decoded


def copy_schema(schema, name=None):
    """Deep-copy a schema (used by transform_schema edits)."""
    return Unischema(name or schema.name, [copy.copy(f) for f in schema.fields.values()])
