"""Row-group caches.

Parity: reference ``petastorm/cache.py`` (``CacheBase.get(key, fill_fn)``,
``NullCache``) and ``petastorm/local_disk_cache.py`` /
``local_disk_arrow_table_cache.py``.

The reference uses the ``diskcache`` package (SQLite-backed FanoutCache).
That package is not a TPU-VM given, so ``LocalDiskCache`` here is a small
self-contained file-per-key cache designed for the local NVMe of a TPU-VM
host: hashed filenames, atomic renames for crash safety, and lazy size-based
LRU eviction.
"""

import hashlib
import os
import pickle
import tempfile
import threading

import pyarrow as pa

from petastorm_tpu.errors import CorruptChunkError
from petastorm_tpu.membudget import approx_nbytes


class CacheBase(object):
    #: Serving-tier label stamped into batch provenance segments
    #: (``petastorm_tpu.lineage``) when a worker's chunk comes out of this
    #: cache instead of a fresh decode.
    lineage_tier = 'cache'

    def get(self, key, fill_cache_func):
        """Return the cached value for ``key``; on miss call ``fill_cache_func``
        and store its result."""
        raise NotImplementedError

    def cleanup(self):
        pass


class NullCache(CacheBase):
    """No-op cache: always calls the fill function."""

    lineage_tier = 'decode'     # every get() is a fresh decode

    def get(self, key, fill_cache_func):
        return fill_cache_func()


class MemoryCache(CacheBase):
    """In-RAM LRU cache with an approximate byte cap.

    Built for the decoded-chunk hot path (``make_tensor_reader``): a
    row-group's decoded tensor blocks are ~10 MB and re-reading them every
    epoch costs a jpeg decode per sample; a RAM cache turns steady-state
    epochs into pure memcpy. The reference has no equivalent (its
    ``LocalDiskCache`` is SQLite-backed disk only) — on a TPU-VM host with
    hundreds of GB of RAM this is the faster tier above the NVMe cache.

    Values are cached by reference (no serialization): callers must treat
    cached values as immutable. With process pools each worker process holds
    its own instance (no cross-process sharing) — prefer the thread pool
    when using this cache, or the mmap-backed ``chunk-store`` tier
    (``petastorm_tpu.chunk_store``) for cross-process sharing of decoded
    chunks on NVMe.
    """

    lineage_tier = 'memory'

    def __init__(self, size_limit_bytes=None):
        from collections import OrderedDict
        self._entries = OrderedDict()   # key -> (value, nbytes)
        self._total = 0
        self._size_limit = size_limit_bytes
        self._lock = threading.Lock()
        self._inflight = {}             # key -> Event (single-flight fills)
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _nbytes(value):
        # One definition of "how big is a cached value" for the whole
        # package (module-scope import: this runs per cached chunk): the
        # governor accounts the very same values this cap gates, and two
        # drifting estimators would let them disagree.
        return approx_nbytes(value)

    def get(self, key, fill_cache_func):
        # Single-flight per key: the ventilator dispatches the SAME row
        # group for epoch N+1 while epoch N's decode of it may still be
        # in flight, and two concurrent misses would both pay the decode
        # (pure waste — on a 1-core host it directly steals throughput at
        # every epoch boundary until the cache is warm). The second
        # thread waits (GIL released) and reads the first one's entry.
        while True:
            with self._lock:
                entry = self._entries.get(key)
                if entry is not None:
                    self._entries.move_to_end(key)
                    self.hits += 1
                    return entry[0]
                event = self._inflight.get(key)
                if event is None:
                    event = self._inflight[key] = threading.Event()
                    break               # this thread does the fill
            event.wait()
            # Fill finished (or failed/returned None): re-check; on a
            # still-absent entry the loop claims the fill for this thread.
        value, filled = None, False
        try:
            value = fill_cache_func()
            filled = True
        finally:
            try:
                # Returned None IS cached (as (None, 0)): empty row-groups
                # would otherwise never warm the cache and every epoch's
                # duplicate dispatch would serialize behind a futile fill.
                # A RAISING fill caches nothing — a transient read error
                # must not become a permanently-served empty chunk.
                if filled:
                    nbytes = self._nbytes(value) if value is not None else 0
                    with self._lock:
                        self.misses += 1
                        if key not in self._entries:
                            self._entries[key] = (value, nbytes)
                            self._total += nbytes
                            if self._size_limit is not None:
                                while (self._total > self._size_limit
                                       and len(self._entries) > 1):
                                    _, (_, old) = self._entries.popitem(
                                        last=False)
                                    self._total -= old
            finally:
                # Unconditionally un-register and wake waiters — a raise
                # anywhere above (a value whose .nbytes property throws,
                # the fill itself) must never leave an unset Event behind,
                # or every future get() for this key deadlocks.
                with self._lock:
                    self._inflight.pop(key, None)
                event.set()
        return value

    @property
    def nbytes(self):
        """Current resident bytes — the memory governor's accounting hook
        (``membudget.py``: this cache registers as pool ``memory-cache``)."""
        with self._lock:
            return self._total

    def evict(self, keep_frac=0.5):
        """Drop LRU entries until at most ``keep_frac`` of the current
        bytes remain (the governor's *degrade* hook: repeated calls keep
        halving, so a rung that persists converges on empty). Returns the
        bytes freed. Evicted entries simply refill on their next miss —
        slower, never wrong."""
        freed = 0
        with self._lock:
            target = self._total * float(keep_frac)
            while self._entries and self._total > target:
                _, (_, nbytes) = self._entries.popitem(last=False)
                self._total -= nbytes
                freed += nbytes
        return freed

    def cleanup(self):
        with self._lock:
            self._entries.clear()
            self._total = 0


class LocalDiskCache(CacheBase):
    """File-per-key disk cache with size-limited LRU eviction.

    :param path: cache directory (created if missing).
    :param size_limit: approximate maximum total bytes; ``None`` = unlimited.
    :param expected_row_size_bytes: accepted for reference-API parity
        (``local_disk_cache.py:22``); unused by this implementation.
    :param cleanup: if True, remove the whole cache dir on ``cleanup()``.
    """

    _SUFFIX = '.pkl'
    lineage_tier = 'disk'

    def __init__(self, path, size_limit=None, expected_row_size_bytes=None,
                 shards=None, cleanup=False, **_):
        self._path = path
        self._size_limit = size_limit
        self._cleanup = cleanup
        self._lock = threading.Lock()
        os.makedirs(path, exist_ok=True)

    def _key_path(self, key):
        digest = hashlib.md5(str(key).encode('utf-8')).hexdigest()
        return os.path.join(self._path, digest + self._SUFFIX)

    def _serialize(self, value):
        # Decoded ndarray-dict values (the tensor hot path) take the
        # chunk store's raw-buffer layout (header + np-format field dumps
        # + CRC32s): a hit then parses a tiny JSON header and wraps the
        # payload bytes zero-copy, where pickle paid a full deserialize
        # copy per hit. Anything else (row dicts, scalars) still pickles.
        from petastorm_tpu.chunk_store import (conforms_tensor_chunk,
                                               pack_tensor_chunk)
        if conforms_tensor_chunk(value):
            return pack_tensor_chunk(value)
        return pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)

    def _deserialize(self, blob):
        # Old caches hold pickle entries; the magic check keeps that read
        # path alive (a raw-layout blob can never collide with it: pickle
        # streams start with an opcode, not b'PSTC').
        from petastorm_tpu.chunk_store import is_tensor_chunk, read_tensor_chunk
        if is_tensor_chunk(blob):
            return read_tensor_chunk(blob)
        return pickle.loads(blob)

    def get(self, key, fill_cache_func):
        target = self._key_path(key)
        try:
            with open(target, 'rb') as f:
                blob = f.read()
            os.utime(target, None)  # LRU touch
            return self._deserialize(blob)
        except (FileNotFoundError, EOFError, pickle.UnpicklingError,
                CorruptChunkError):
            pass
        value = fill_cache_func()
        blob = self._serialize(value)
        fd, tmp = tempfile.mkstemp(dir=self._path, suffix='.tmp')
        try:
            with os.fdopen(fd, 'wb') as f:
                f.write(blob)
            os.replace(tmp, target)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        self._maybe_evict()
        return value

    def _maybe_evict(self):
        if self._size_limit is None:
            return
        with self._lock:
            entries = []
            total = 0
            for name in os.listdir(self._path):
                if not name.endswith(self._SUFFIX):
                    continue
                full = os.path.join(self._path, name)
                try:
                    st = os.stat(full)
                except OSError:
                    continue
                entries.append((st.st_mtime, st.st_size, full))
                total += st.st_size
            if total <= self._size_limit:
                return
            entries.sort()  # oldest first
            for _, size, full in entries:
                try:
                    os.unlink(full)
                except OSError:
                    continue
                total -= size
                if total <= self._size_limit:
                    break

    def cleanup(self):
        if not self._cleanup:
            return
        import shutil
        shutil.rmtree(self._path, ignore_errors=True)


class LocalDiskArrowTableCache(LocalDiskCache):
    """Disk cache specialized for ``pyarrow.Table`` values.

    Serializes via the Arrow IPC stream format (zero pickle), matching the
    role of reference ``local_disk_arrow_table_cache.py:20-40``.
    """

    def _serialize(self, table):
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, table.schema) as writer:
            writer.write_table(table)
        return sink.getvalue().to_pybytes()

    def _deserialize(self, blob):
        with pa.ipc.open_stream(pa.BufferReader(blob)) as reader:
            return reader.read_all()
