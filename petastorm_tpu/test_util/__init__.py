"""Testing utilities for downstream users (parity: reference ``petastorm/test_util/``)."""

from petastorm_tpu.test_util.reader_mock import ReaderMock  # noqa: F401
