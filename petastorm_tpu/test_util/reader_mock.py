"""ReaderMock: a schema-driven fake Reader generating synthetic rows (no IO).

Parity: reference ``petastorm/test_util/reader_mock.py:19-65`` +
``schema_data_generator_example`` (``:68-82``). Lets downstream users test
training loops without a dataset.
"""

from petastorm_tpu.generator import generate_datapoint


class ReaderMock(object):
    """Infinite iterator of synthetic rows matching a Unischema.

    :param schema: Unischema describing the rows.
    :param schema_data_generator: optional ``(schema, rng) -> dict`` override.
    """

    def __init__(self, schema, schema_data_generator=None, seed=0):
        import numpy as np

        self.schema = schema
        self._generator = schema_data_generator or generate_datapoint
        self._rng = np.random.default_rng(seed)
        self.last_row_consumed = False

    @property
    def batched_output(self):
        return False

    @property
    def ngram(self):
        return None

    @property
    def transformed_schema(self):
        return self.schema

    def __iter__(self):
        return self

    def __next__(self):
        row = self._generator(self.schema, self._rng)
        return self.schema.make_namedtuple(**row)

    next = __next__

    def stop(self):
        pass

    def join(self):
        pass

    @property
    def diagnostics(self):
        return {}

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False
