"""Shuffle-quality analysis: correlation of shuffled vs ordered id streams.

Parity: reference ``petastorm/test_util/shuffling_analysis.py:52-85``
(``compute_correlation_distribution``).
"""

import numpy as np


def compute_correlation_distribution(ordered_ids, shuffled_id_streams):
    """|corrcoef| of each shuffled stream against the ordered stream.

    Low values mean good decorrelation. Returns (mean, per-stream list).
    """
    ordered = np.asarray(ordered_ids, dtype=np.float64)
    correlations = []
    for stream in shuffled_id_streams:
        stream = np.asarray(stream, dtype=np.float64)
        n = min(len(ordered), len(stream))
        if n < 2:
            continue
        corr = abs(float(np.corrcoef(ordered[:n], stream[:n])[0, 1]))
        correlations.append(corr)
    return (float(np.mean(correlations)) if correlations else 0.0), correlations
