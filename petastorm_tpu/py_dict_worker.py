"""Per-row row-group worker: Parquet read -> codec decode -> transform -> rows.

Parity: reference ``petastorm/py_dict_reader_worker.py`` — one row-group per
``process()`` call, cached loads (``:160``), two-phase predicate read
(predicate columns first, early exit, then the rest — ``:188-252``), row-drop
partitioning with ngram tail extension (``:254-274``), per-row TransformSpec
(``:38-52``), ngram window formation (``:165-166``), and the paired results
queue reader that buffers a chunk and pops single rows (``:64-97``).
"""

import hashlib

from petastorm_tpu.checkpoint import chunk_key
from petastorm_tpu.determinism import ResequencedReads, is_hole
from petastorm_tpu.unischema import decode_rows
from petastorm_tpu.workers.rowgroup_worker_base import (RowGroupWorkerBase,
                                                        compute_row_slice)


class PyDictWorker(RowGroupWorkerBase):
    """Worker args (dict):
      store_factory: picklable zero-arg -> ParquetStore
      schema: Unischema view of fields to read+decode
      full_schema: stored dataset Unischema
      ngram: NGram or None
      row_groups: list[RowGroupPiece]
      cache: CacheBase
      transform_spec: TransformSpec or None
      transformed_schema: post-transform Unischema (for output filtering)
      partition_names: list of hive partition column names
      dataset_path_hash: stable dataset identity for cache keys
    """

    _prefer_native_parquet = False  # pyarrow is faster for the to-rows path

    #: Reader-mode tag for batch provenance contexts (lineage.py).
    lineage_mode = 'py_dict'

    def process(self, piece_index, worker_predicate=None,
                shuffle_row_drop_partition=None, pst_det=None):
        from petastorm_tpu.faults import maybe_inject, rowgroup_fault_key

        piece = self.args['row_groups'][piece_index]
        schema = self.args['schema']
        ngram = self.args['ngram']
        maybe_inject('decode-corrupt',
                     key=rowgroup_fault_key(piece.path, piece.row_group))

        decoded_fresh = []
        if worker_predicate is not None:
            rows = self._load_rows_with_predicate(piece, worker_predicate)
            decoded_fresh.append(True)
        else:
            rows = self._load_rows_cached(piece, decoded_fresh)

        row_slice = compute_row_slice(len(rows), shuffle_row_drop_partition, ngram)
        if row_slice is not None:
            rows = rows[row_slice[0]:row_slice[1]]

        transform_spec = self.args.get('transform_spec')
        if transform_spec is not None and transform_spec.func is not None and ngram is None:
            rows = [self._apply_transform(row, transform_spec) for row in rows]

        if ngram is not None:
            rows = ngram.form_ngram(rows, schema)
            if transform_spec is not None and transform_spec.func is not None:
                rows = [{offset: self._apply_transform(r, transform_spec)
                         for offset, r in window.items()} for window in rows]

        if rows:
            # Envelope tags the chunk with its ventilation key so the consumer
            # can track per-row-group consumption for checkpoint/resume
            # (petastorm_tpu.checkpoint), plus its provenance segment for the
            # batch lineage ledger (petastorm_tpu.lineage). NGram windows
            # re-index rows (a window is not a storage row), so their
            # lineage is omitted — batch records over ngrams are inexact.
            from petastorm_tpu.lineage import chunk_lineage
            from petastorm_tpu.trace import get_global_tracer
            lineage = None
            if ngram is None:
                tier = ('decode' if decoded_fresh
                        else getattr(self.args['cache'], 'lineage_tier',
                                     'cache'))
                lineage = chunk_lineage(
                    piece, piece_index, shuffle_row_drop_partition, len(rows),
                    tier, filtered=worker_predicate is not None,
                    worker_id=self.worker_id)
            payload = {'__pst_chunk__': 1,
                       'key': chunk_key(piece_index, shuffle_row_drop_partition),
                       'lineage': lineage,
                       'rows': rows}
            if pst_det is not None:
                payload['det'] = pst_det
            with get_global_tracer().span('handoff', 'worker'):
                self.publish_func(payload)
        else:
            self._publish_hole(pst_det)

    def _apply_transform(self, row, transform_spec):
        out = transform_spec.func(row)
        for name in transform_spec.removed_fields:
            out.pop(name, None)
        return out

    # --- loading ------------------------------------------------------

    def _columns_to_read(self, field_names):
        partition_names = set(self.args['partition_names'])
        return [n for n in field_names if n not in partition_names]

    def _read_columns(self, piece, column_names):
        physical = self._columns_to_read(column_names)
        table = self._read_row_group(piece, physical)
        encoded_rows = table.to_pylist()
        for row in encoded_rows:
            for name, value in piece.partition_values.items():
                if name in column_names:
                    row[name] = value
        return encoded_rows

    def _load_rows_cached(self, piece, decoded_fresh=None):
        schema = self.args['schema']
        if self.args['ngram'] is not None:
            field_names = sorted(self.args['ngram'].get_field_names_at_all_timesteps())
        else:
            field_names = list(schema.fields)
        cache_key = '{}:{}:{}:{}'.format(
            self.args['dataset_path_hash'], piece.path, piece.row_group,
            hashlib.md5(','.join(field_names).encode()).hexdigest()[:8])

        def load():
            from petastorm_tpu.faults import rowgroup_fault_key
            from petastorm_tpu.trace import get_global_tracer
            if decoded_fresh is not None:
                decoded_fresh.append(True)
            encoded_rows = self._read_columns(piece, field_names)
            decode_schema = (self.args['full_schema'].create_schema_view(
                [n for n in field_names if n in self.args['full_schema'].fields])
                if self.args['ngram'] is not None else schema)
            with get_global_tracer().span('decode', 'worker'):
                return decode_rows(encoded_rows, decode_schema,
                                   num_threads=self.args.get('decode_threads'),
                                   fault_key=rowgroup_fault_key(
                                       piece.path, piece.row_group))

        return self.args['cache'].get(cache_key, load)

    def _load_rows_with_predicate(self, piece, predicate):
        """Two-phase read: predicate columns -> early exit -> remaining columns.

        Parity: reference ``py_dict_reader_worker.py:188-252``.
        """
        schema = self.args['schema']
        full_schema = self.args['full_schema']
        predicate_fields = set(predicate.get_fields())
        unknown = predicate_fields - set(full_schema.fields)
        if unknown:
            raise ValueError('Predicate uses unknown fields: {}'.format(sorted(unknown)))
        other_fields = [n for n in schema.fields if n not in predicate_fields]

        predicate_schema = full_schema.create_schema_view(sorted(predicate_fields))
        encoded_pred_rows = self._read_columns(piece, sorted(predicate_fields))
        decoded_pred_rows = decode_rows(encoded_pred_rows, predicate_schema,
                                        num_threads=self.args.get('decode_threads'))
        mask = [predicate.do_include(row) for row in decoded_pred_rows]
        if not any(mask):
            return []

        if other_fields:
            other_schema = schema.create_schema_view(other_fields)
            encoded_other = self._read_columns(piece, other_fields)
            surviving = [(pred_row, other_row) for include, pred_row, other_row
                         in zip(mask, decoded_pred_rows, encoded_other) if include]
            decoded_other = decode_rows([other for _, other in surviving], other_schema,
                                        num_threads=self.args.get('decode_threads'))
            result = []
            for (pred_row, _), decoded in zip(surviving, decoded_other):
                decoded.update({k: v for k, v in pred_row.items() if k in schema.fields})
                result.append(decoded)
            return result
        return [{k: v for k, v in row.items() if k in schema.fields}
                for row, include in zip(decoded_pred_rows, mask) if include]

class PyDictResultsQueueReader(ResequencedReads):
    """Consumer-side: buffers a published chunk, pops single rows.

    Parity: reference ``py_dict_reader_worker.py:64-97``. In deterministic
    mode chunk pops route through the reader's resequencer
    (``ResequencedReads``) so delivery order equals ventilation order.
    """

    def __init__(self):
        from collections import deque
        self._buffer = deque()
        self._tracker = None
        self._last_lineage = None
        self._last_det = None

    def set_tracker(self, tracker):
        self._tracker = tracker

    @property
    def batched_output(self):
        return False

    @property
    def last_chunk_lineage(self):
        """Provenance segment of the single row most recently returned:
        the producing chunk's segment narrowed to that row
        (``row_start`` = the row's index within the published chunk;
        consecutive rows coalesce downstream). ``None`` for untagged or
        ngram payloads."""
        return self._last_lineage

    @property
    def last_chunk_det(self):
        """Deterministic-mode tag of the chunk the most recently returned
        row came from, or None outside deterministic mode."""
        return self._last_det

    def read_next(self, pool, schema, ngram):
        while not self._buffer:
            chunk = self._pull(pool)
            if is_hole(chunk):
                continue
            if isinstance(chunk, dict) and chunk.get('__pst_chunk__'):
                key, rows = chunk['key'], chunk['rows']
                lineage = chunk.get('lineage')
                det = chunk.get('det')
            else:  # untagged payload (e.g. a custom worker)
                key, rows, lineage, det = None, chunk, None, None
            skip = 0
            if self._tracker is not None and key is not None:
                skip = self._tracker.on_chunk(key, len(rows), det=det)
            self._buffer.extend(
                (key, row, lineage, skip + i, det)
                for i, row in enumerate(rows[skip:]))
        key, row, lineage, row_index, det = self._buffer.popleft()
        if lineage is not None:
            self._last_lineage = dict(lineage, row_start=row_index)
        else:
            self._last_lineage = None
        self._last_det = det
        if self._tracker is not None and key is not None:
            self._tracker.rows_yielded(key, 1)
        if ngram is not None:
            return {offset: ngram.get_schema_at_timestep(schema, offset).make_namedtuple(**fields)
                    for offset, fields in row.items()}
        return schema.make_namedtuple(**row)
