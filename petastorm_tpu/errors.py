"""Exception types for petastorm_tpu.

Parity: reference ``petastorm/errors.py`` (NoDataAvailableError) plus decode
errors from ``petastorm/utils.py:50``.
"""


class PetastormTpuError(Exception):
    """Base class for all petastorm_tpu errors."""


class NoDataAvailableError(PetastormTpuError):
    """Raised when sharding/filtering leaves a reader with no row-groups.

    Parity: reference ``petastorm/errors.py:16`` raised at ``reader.py:495-497``.
    """


class DecodeFieldError(PetastormTpuError):
    """Raised when a field value cannot be decoded by its codec.

    Parity: reference ``petastorm/utils.py:50``.
    """


class SchemaError(PetastormTpuError):
    """Raised for schema definition / inference problems."""
